"""Chaos scenario suite: serving-path failure containment, on CPU.

Deterministic fault injection (utils/failpoints.py, seeded) drives the
recovery paths the robustness plan wired in (docs/ROBUSTNESS.md):

  A. ENGINE — injected step/admit faults: every client request either
     completes or fails with a STRUCTURED retriable error (zero hung
     futures/streams); requests that never sampled a token are
     resurrected, not failed; the resurrection budget bounds retries;
     the engine serves normally after every reset.
  B. LB — a killed/flapping upstream: bounded retries reroute
     idempotent-safe requests to a healthy replica; the per-replica
     circuit breaker opens after consecutive failures, sheds traffic,
     half-open-probes, and re-closes after recovery — with metric and
     journal evidence.
  C. LB↔ENGINE through the ChaosProxy — connection kills mid-headers
     and mid-stream, slow-loris reads: clients see bounded, clear
     failures; the LB reroutes what is safe to reroute.
  D. DRAIN — a DRAINING replica leaves the routable set, completes
     100% of its accepted in-flight requests, then tears down (the
     deadline bounds the wait); DRAINING can never be resurrected to
     READY.

All hermetic and CPU-backed (JAX_PLATFORMS=cpu), like the rest of
tier-1.
"""
import asyncio
import dataclasses
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import aiohttp
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient
from aiohttp.test_utils import TestServer as AioTestServer

import jax.numpy as jnp

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.serve import engine as engine_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.utils import failpoints
from tests.chaos.chaos_proxy import ChaosProxy


@pytest.fixture(scope='module')
def engine():
    eng = engine_lib.InferenceEngine('llama-debug', max_len=128)
    # fp32 (CPU argmax stability) + spec off: these scenarios pin the
    # pipelined path, like test_engine_pipeline.
    eng.cfg = dataclasses.replace(eng.cfg, dtype=jnp.float32)
    eng.spec_k = 0
    eng.warmup()
    return eng


@pytest.fixture(autouse=True)
def chaos_env(tmp_path, monkeypatch):
    """Every scenario starts with a disarmed failpoint plane and its
    own journal DB; nothing leaks across tests."""
    failpoints.reset()
    monkeypatch.setenv('SKYTPU_OBSERVE_DB', str(tmp_path / 'observe.db'))
    yield
    failpoints.reset()


def _run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(
            asyncio.wait_for(coro, timeout=timeout))
    finally:
        loop.close()


def _with_engine_client(engine, fn, timeout=120):
    async def inner():
        client = TestClient(AioTestServer(engine_lib.build_app(engine)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()
    return _run(inner(), timeout=timeout)


# ---------------------------------------------------------------------------
# A. Engine fault containment
# ---------------------------------------------------------------------------

class TestEngineFaultContainment:

    def test_injected_step_faults_zero_hangs_structured_errors(
            self, engine):
        """Seeded step faults mid-traffic: every request resolves —
        200, or a STRUCTURED retriable 503 — inside a hard timeout
        (zero hangs), and the engine serves cleanly afterwards."""
        failpoints.arm('engine.step', every=3, max_fires=2)

        async def fn(client):
            async def one(i):
                r = await client.post('/generate', json={
                    'tokens': [i + 1] * 8, 'max_new_tokens': 6})
                return r.status, await r.json()

            results = await asyncio.gather(*(one(i) for i in range(8)))
            # Recovery proof: with faults off, the rebuilt pool must
            # serve normally (disarm explicitly — the burst may not
            # have consumed every scheduled firing).
            failpoints.reset()
            r = await client.post('/generate', json={
                'tokens': [3] * 8, 'max_new_tokens': 5})
            after = r.status, await r.json()
            return results, after

        results, after = _with_engine_client(engine, fn)
        for status, body in results:
            assert status in (200, 503), body
            if status == 503:
                err = body['error']
                assert err['type'] == 'engine_reset_error'
                assert err['retriable'] is True
                assert isinstance(err['tokens_emitted'], int)
            else:
                assert len(body['tokens']) == 6
        assert after[0] == 200 and len(after[1]['tokens']) == 5
        # Zero leaked state: no slot, no in-flight handle, no hold.
        assert all(s is None for s in engine.slots)
        assert engine._inflight == []
        assert engine._hold == []

    def test_admit_fault_resurrects_request_to_completion(self, engine):
        """A request whose ADMISSION device call faults never sampled a
        token — it is resubmitted internally and completes with 200;
        the client never sees the fault."""
        before = engine.resurrected_total
        metric_before = engine_lib._M_RESURRECTED.value()
        failpoints.arm('engine.admit', once=True)

        async def fn(client):
            r = await client.post('/generate', json={
                'tokens': [5] * 8, 'max_new_tokens': 6})
            return r.status, await r.json()

        status, body = _with_engine_client(engine, fn)
        assert status == 200
        assert len(body['tokens']) == 6
        assert engine.resurrected_total == before + 1
        assert engine_lib._M_RESURRECTED.value() == metric_before + 1

    def test_resurrection_budget_bounds_retries(self, engine):
        """An admission that faults EVERY time must surface a bounded,
        structured failure — not loop forever."""
        before = engine.resurrected_total
        failpoints.arm('engine.admit', every=1)

        async def fn(client):
            r = await client.post('/generate', json={
                'tokens': [6] * 8, 'max_new_tokens': 4})
            return r.status, await r.json()

        status, body = _with_engine_client(engine, fn)
        assert status == 503
        err = body['error']
        assert err['type'] == 'engine_reset_error'
        assert err['tokens_emitted'] == 0
        # Exactly RESURRECT_MAX internal resubmissions were spent.
        assert engine.resurrected_total == \
            before + engine_lib.RESURRECT_MAX

    def test_fail_all_dispositions_each_row_minimally(self, engine):
        """The containment matrix, row by row (regression for the
        pre-fix behavior that failed EVERYTHING with the step's
        exception): finished rows resolve with their results; rows
        mid-prefill (zero tokens) resurrect; rows mid-decode fail with
        tokens_emitted; a pending admit-group item resurrects."""
        async def fn():
            loop = asyncio.get_running_loop()

            def item(fut, toks):
                return (toks, 4, 0.0, None, None, 0.0, 0.0, (), False,
                        None, fut)

            def entry(fut, out, finish, prefill_item=None):
                e = {'fut': fut, 'stream': None, 'finish': finish,
                     'out': list(out), 'lps': [0.0] * len(out),
                     'tops': [[] for _ in out], 'sent': 0, 'want': 4,
                     'want_tops': False, 'stop': frozenset(),
                     'ctx': [1] + list(out), 't_submit_ns': None}
                if prefill_item is not None:
                    e['prefill'] = {'item': prefill_item, 'pos': 0,
                                    't_admit_ns': 0}
                else:
                    e['item'] = item(fut, [1] * 8)
                return e

            fut_done = loop.create_future()      # finished, unpublished
            fut_mid = loop.create_future()       # mid-decode, 2 tokens
            fut_pre = loop.create_future()       # mid-chunked-prefill
            fut_queued = loop.create_future()    # in the admit group
            item_pre = item(fut_pre, [2] * 8)
            item_queued = item(fut_queued, [3] * 8)
            engine.slots[0] = entry(fut_done, [7, 8], 'length')
            engine.slots[1] = entry(fut_mid, [9, 10], None)
            engine.slots[2] = entry(fut_pre, [], None,
                                    prefill_item=item_pre)
            try:
                await engine._fail_all(RuntimeError('boom'),
                                       extra=[item_queued])
                out, finish, _, _ = fut_done.result()
                assert (out, finish) == ([7, 8], 'length')
                with pytest.raises(engine_lib.EngineResetError) as ei:
                    fut_mid.result()
                assert ei.value.tokens_emitted == 2
                assert ei.value.retriable is True
                # Zero-token rows were RESURRECTED, not failed —
                # oldest (the prefilling slot) ahead of the pending
                # admit item, both ahead of anything newly held.
                assert not fut_pre.done() and not fut_queued.done()
                assert engine._hold[:2] == [item_pre, item_queued]
            finally:
                engine._hold.clear()
                engine._resurrect_counts.clear()
                for f in (fut_pre, fut_queued):
                    f.cancel()

        _run(fn())

    def test_streaming_reset_is_structured_and_never_hangs(self,
                                                           engine):
        """A stream cut by a device failure ends with a structured
        retriable error event carrying tokens_emitted — never a silent
        stall — and the engine serves the next request."""
        failpoints.arm('engine.step', every=2, max_fires=1)

        async def fn(client):
            r = await client.post('/v1/completions', json={
                'prompt': [4] * 8, 'max_tokens': 48, 'stream': True,
                'temperature': 0})
            assert r.status == 200
            body = (await r.read()).decode()
            r2 = await client.post('/generate', json={
                'tokens': [2] * 8, 'max_new_tokens': 4})
            return body, r2.status

        body, after_status = _with_engine_client(engine, fn)
        if 'engine_reset_error' in body:
            assert 'tokens_emitted' in body
        else:
            # The fault landed between this stream's steps (e.g. on
            # admit of the follow-up): the stream then completed.
            assert 'data: [DONE]' in body
        assert after_status == 200


# ---------------------------------------------------------------------------
# B. LB retries + circuit breaker (fake upstreams — pure asyncio)
# ---------------------------------------------------------------------------

def _toggle_app(state):
    """An upstream whose handler can be broken (kills the connection
    before any response byte — the LB sees a pre-response disconnect)
    and counts attempts/successes."""
    app = web.Application()

    async def handler(request):
        state['attempts'] += 1
        if state['broken']:
            request.transport.close()
            return web.Response()
        state['hits'] += 1
        return web.json_response({'ok': True, 'who': state['name']})

    app.router.add_route('*', '/{tail:.*}', handler)
    return app


def _make_lb(monkeypatch, urls, retries=2, threshold=2, cooldown=30.0,
             connect=5.0, read=5.0):
    monkeypatch.setenv('SKYTPU_LB_RETRIES', str(retries))
    monkeypatch.setenv('SKYTPU_LB_BREAKER_THRESHOLD', str(threshold))
    monkeypatch.setenv('SKYTPU_LB_BREAKER_COOLDOWN', str(cooldown))
    monkeypatch.setenv('SKYTPU_LB_CONNECT_TIMEOUT', str(connect))
    monkeypatch.setenv('SKYTPU_LB_READ_TIMEOUT', str(read))
    lb = lb_lib.LoadBalancer('round_robin', service_name='chaos-svc')
    lb.set_ready_replicas(urls)
    return lb


class TestLBRetriesAndBreaker:

    def test_retry_reroutes_and_breaker_opens_then_recloses(
            self, monkeypatch):
        """The full breaker arc with a flapping upstream: every client
        request succeeds (rerouted), the breaker opens after
        `threshold` consecutive failures and sheds traffic, then
        half-open-probes and re-closes once the upstream recovers —
        metrics + journal record the whole story."""
        bad = {'name': 'bad', 'broken': True, 'attempts': 0, 'hits': 0}
        good = {'name': 'good', 'broken': False, 'attempts': 0,
                'hits': 0}
        retries_before = sum(
            lb_lib._LB_RETRIES.value(reason=r)
            for r in lb_lib._RETRY_REASONS)

        async def fn():
            bad_srv = AioTestServer(_toggle_app(bad))
            good_srv = AioTestServer(_toggle_app(good))
            await bad_srv.start_server()
            await good_srv.start_server()
            bad_url = str(bad_srv.make_url('')).rstrip('/')
            good_url = str(good_srv.make_url('')).rstrip('/')
            lb = _make_lb(monkeypatch, [bad_url, good_url],
                          threshold=2, cooldown=1.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                # Phase 1: flapping upstream. Every request must still
                # return 200 (retried onto the healthy replica).
                for _ in range(6):
                    r = await client.get('/ping')
                    assert r.status == 200
                    assert (await r.json())['who'] == 'good'
                assert lb._breakers[bad_url].state == 'open'
                open_attempts = bad['attempts']
                # Phase 2: breaker open — traffic sheds (no new
                # attempts reach the broken replica inside cooldown).
                for _ in range(4):
                    r = await client.get('/ping')
                    assert r.status == 200
                assert bad['attempts'] == open_attempts
                # Phase 3: upstream recovers; after the cooldown the
                # half-open probe succeeds and the breaker re-closes.
                bad['broken'] = False
                await asyncio.sleep(1.1)
                for _ in range(4):
                    r = await client.get('/ping')
                    assert r.status == 200
                assert lb._breakers[bad_url].state == 'closed'
                assert bad['hits'] > 0
            finally:
                await client.close()
                await bad_srv.close()
                await good_srv.close()
            return bad_url

        bad_url = _run(fn())
        # Metric evidence: retries were counted with a reason.
        retries_after = sum(
            lb_lib._LB_RETRIES.value(reason=r)
            for r in lb_lib._RETRY_REASONS)
        assert retries_after > retries_before
        # Journal evidence: the breaker's transitions, with the
        # replica URL in the event payload.
        events = journal.query(kind='lb_breaker')
        arcs = [e['reason'] for e in events
                if (e.get('data') or {}).get('replica') == bad_url]
        assert 'closed->open' in arcs
        assert any(a.endswith('->closed') for a in arcs)

    def test_all_replicas_broken_bounded_structured_503(
            self, monkeypatch):
        """With every replica down, the client gets a bounded,
        structured, retriable error — not a hang, not a raw 502 per
        attempt forever."""
        bad = {'name': 'bad', 'broken': True, 'attempts': 0, 'hits': 0}

        async def fn():
            bad_srv = AioTestServer(_toggle_app(bad))
            await bad_srv.start_server()
            bad_url = str(bad_srv.make_url('')).rstrip('/')
            lb = _make_lb(monkeypatch, [bad_url], retries=1,
                          threshold=2, cooldown=30.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                out = []
                for _ in range(4):
                    r = await client.get('/ping')
                    out.append((r.status, await r.json()))
                return out
            finally:
                await client.close()
                await bad_srv.close()

        results = _run(fn(), timeout=60)
        for status, body in results:
            assert status in (502, 503)
            assert body.get('retriable') is True

    def test_aborted_half_open_probe_releases_token(self):
        """Half-open allows exactly ONE probe — an aborted probe
        (client hung up mid-attempt) must release the token, or the
        breaker wedges half-open and the replica never routes again."""
        b = lb_lib.CircuitBreaker(threshold=1, cooldown=0.0)
        assert b.record_failure(0.0) == ('closed', 'open')
        assert b.routable(1.0)                 # cooldown elapsed
        assert b.begin_attempt(1.0) == ('open', 'half_open')
        assert not b.routable(1.0)             # probe token consumed
        b.abort_attempt()                      # client abort mid-probe
        assert b.routable(1.0)                 # released, not wedged
        b.begin_attempt(1.0)
        assert b.record_success() == ('half_open', 'closed')

    def test_client_abort_does_not_poison_breaker(self, monkeypatch):
        """A client hanging up mid-stream is NOT an upstream failure:
        the replica's breaker must not move (threshold=1 here, so one
        misattributed failure would open it and shed a healthy
        replica), and the outcome is counted as client_abort."""
        async def fn():
            app = web.Application()

            async def slow_stream(request):
                resp = web.StreamResponse()
                await resp.prepare(request)
                for _ in range(100):
                    await resp.write(b'x' * 64)
                    await asyncio.sleep(0.05)
                return resp

            async def ping(request):
                return web.json_response({'ok': True})

            app.router.add_get('/slow', slow_stream)
            app.router.add_get('/ping', ping)
            srv = AioTestServer(app)
            await srv.start_server()
            url = str(srv.make_url('')).rstrip('/')
            lb = _make_lb(monkeypatch, [url], retries=1, threshold=1,
                          cooldown=30.0, read=10.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                for _ in range(3):
                    try:
                        await client.get(
                            '/slow',
                            timeout=aiohttp.ClientTimeout(total=0.3))
                    except (asyncio.TimeoutError,
                            aiohttp.ClientError):
                        pass        # the client gave up — that's the point
                # Give the LB loop a beat to observe the dead writes.
                await asyncio.sleep(0.3)
                assert lb._breakers[url].state == 'closed'
                r = await client.get('/ping')
                assert r.status == 200
            finally:
                await client.close()
                await srv.close()

        before = lb_lib._LB_REQUESTS.value(policy='round_robin',
                                           outcome='client_abort')
        _run(fn(), timeout=60)
        assert lb_lib._LB_REQUESTS.value(
            policy='round_robin', outcome='client_abort') > before

    def test_connect_refused_counts_and_reroutes(self, monkeypatch):
        """A replica that refuses connections entirely (dead port):
        connect-level failure, retried onto the live replica."""
        good = {'name': 'good', 'broken': False, 'attempts': 0,
                'hits': 0}
        before = lb_lib._LB_RETRIES.value(reason='connect_error')

        async def fn():
            good_srv = AioTestServer(_toggle_app(good))
            await good_srv.start_server()
            good_url = str(good_srv.make_url('')).rstrip('/')
            # Port 1: nothing listens (connection refused).
            lb = _make_lb(monkeypatch,
                          ['http://127.0.0.1:1', good_url],
                          threshold=1, cooldown=30.0, connect=2.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                for _ in range(3):
                    r = await client.get('/ping')
                    assert r.status == 200
            finally:
                await client.close()
                await good_srv.close()

        _run(fn(), timeout=60)
        assert lb_lib._LB_RETRIES.value(reason='connect_error') > before


# ---------------------------------------------------------------------------
# C. LB ↔ live engine replica through the ChaosProxy
# ---------------------------------------------------------------------------

class TestLBEngineChaos:

    def test_mid_headers_kill_is_retried_to_healthy_route(
            self, engine, monkeypatch):
        """The nastiest LB case: request fully delivered, response
        headers never arrive. Idempotent-safe → retried; with a
        healthy route available every request still completes."""
        async def fn():
            eng_srv = AioTestServer(engine_lib.build_app(engine))
            await eng_srv.start_server()
            proxy = ChaosProxy('127.0.0.1', eng_srv.port,
                               kill_every=1, mode='mid_headers')
            proxy_port = proxy.start()
            direct = str(eng_srv.make_url('')).rstrip('/')
            lb = _make_lb(monkeypatch,
                          [f'http://127.0.0.1:{proxy_port}', direct],
                          retries=2, threshold=3, cooldown=30.0,
                          read=10.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                for i in range(4):
                    r = await client.post('/generate', json={
                        'tokens': [i + 1] * 8, 'max_new_tokens': 4})
                    assert r.status == 200
                    assert len((await r.json())['tokens']) == 4
            finally:
                await client.close()
                proxy.stop()
                await eng_srv.close()

        _run(fn())

    def test_mid_stream_kill_truncates_without_hanging(
            self, engine, monkeypatch):
        """A streaming response killed mid-flight: the client sees a
        truncated stream promptly (never a hang), the LB records the
        upstream failure, and the engine stays healthy."""
        async def fn():
            eng_srv = AioTestServer(engine_lib.build_app(engine))
            await eng_srv.start_server()
            proxy = ChaosProxy('127.0.0.1', eng_srv.port,
                               kill_every=1, mode='response')
            proxy_port = proxy.start()
            lb = _make_lb(monkeypatch,
                          [f'http://127.0.0.1:{proxy_port}'],
                          retries=1, threshold=3, cooldown=30.0,
                          read=10.0)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                r = await client.post('/v1/completions', json={
                    'prompt': [5] * 8, 'max_tokens': 40,
                    'stream': True, 'temperature': 0})
                try:
                    body = (await r.read()).decode()
                except Exception:       # noqa: BLE001 — torn transfer
                    body = ''
                # Truncated: the stream never reached its terminator.
                assert 'data: [DONE]' not in body
                # Engine is fine: a direct request completes.
                direct = TestClient(eng_srv)
                r2 = await direct.post('/generate', json={
                    'tokens': [2] * 8, 'max_new_tokens': 3})
                assert r2.status == 200
            finally:
                await client.close()
                proxy.stop()
                await eng_srv.close()

        _run(fn())

    def test_slow_loris_read_timeout_reroutes(self, engine,
                                              monkeypatch):
        """A replica trickling bytes slower than the between-bytes
        timeout is detected (sock_read), and requests reroute to the
        healthy route — the split-timeout shape at work."""
        before = lb_lib._LB_RETRIES.value(reason='timeout')

        async def fn():
            eng_srv = AioTestServer(engine_lib.build_app(engine))
            await eng_srv.start_server()
            proxy = ChaosProxy('127.0.0.1', eng_srv.port,
                               kill_every=10 ** 9, byte_delay=1.0)
            proxy_port = proxy.start()
            direct = str(eng_srv.make_url('')).rstrip('/')
            lb = _make_lb(monkeypatch,
                          [f'http://127.0.0.1:{proxy_port}', direct],
                          retries=2, threshold=5, cooldown=30.0,
                          read=0.3)
            client = TestClient(AioTestServer(lb.build_app()))
            await client.start_server()
            try:
                for i in range(4):
                    r = await client.post('/generate', json={
                        'tokens': [i + 2] * 8, 'max_new_tokens': 3})
                    assert r.status == 200
            finally:
                await client.close()
                proxy.stop()
                await eng_srv.close()

        _run(fn())
        assert lb_lib._LB_RETRIES.value(reason='timeout') > before


# ---------------------------------------------------------------------------
# D. Graceful drain
# ---------------------------------------------------------------------------

class _HealthHandler(BaseHTTPRequestHandler):
    state = None        # injected per server

    def do_GET(self):
        doc = json.dumps({'status': 'ok',
                          'queue_depth': self.state['queue_depth'],
                          'in_flight': self.state['in_flight']})
        self.send_response(200)
        self.send_header('Content-Type', 'application/json')
        self.end_headers()
        self.wfile.write(doc.encode())

    def log_message(self, *args):
        pass


def _health_server(state):
    handler = type('H', (_HealthHandler,), {'state': state})
    srv = HTTPServer(('127.0.0.1', 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f'http://127.0.0.1:{srv.server_port}'


@pytest.fixture
def serve_db(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_DB', str(tmp_path / 'serve.db'))
    yield


def _manager(name='dsvc'):
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve import service_spec as spec_lib
    spec = spec_lib.ServiceSpec.from_yaml_config({
        'readiness_probe': '/health', 'replicas': 2})
    task = task_lib.Task.from_yaml_config({'run': 'sleep 1'})
    return replica_managers.ReplicaManager(name, task, spec)


class TestGracefulDrain:

    def _seed_ready(self, name, rid, url=''):
        serve_state.add_replica(name, rid, cluster_name=f'c{rid}')
        assert serve_state.set_replica_status(name, rid,
                                              ReplicaStatus.STARTING)
        assert serve_state.set_replica_status(name, rid,
                                              ReplicaStatus.READY)
        if url:
            serve_state.upsert_replica(name, rid, url=url)

    def test_drain_waits_for_in_flight_then_tears_down(
            self, serve_db, enable_local_cloud, monkeypatch):
        """The drain arc against live telemetry: DRAINING leaves the
        routable set at once; teardown happens ONLY when in-flight
        work reaches zero; metric + journal evidence lands."""
        mgr = _manager()
        state = {'in_flight': 2, 'queue_depth': 1}
        srv, url = _health_server(state)
        torn = []
        monkeypatch.setattr(mgr, 'terminate_replica',
                            lambda rid, status=None: torn.append(rid))
        monkeypatch.setattr(mgr, '_cluster_gone', lambda rid: False)
        try:
            self._seed_ready('dsvc', 1, url=url)
            assert mgr.drain_replica(1) is True
            reps = serve_state.get_replicas('dsvc')
            assert reps[0]['status'] is ReplicaStatus.DRAINING
            # Out of the routable set immediately.
            assert mgr.ready_urls() == []
            rep = reps[0]
            now = rep['launched_at'] + 1
            # Busy: both passes leave it finishing.
            mgr._reconcile_draining(rep, now)
            state['in_flight'] = 1
            state['queue_depth'] = 0
            mgr._reconcile_draining(rep, now + 1)
            assert torn == []
            # Idle: teardown fires, with evidence.
            state['in_flight'] = 0
            mgr._reconcile_draining(rep, now + 2)
            assert torn == [1]
            finishes = journal.query(kind='drain_finish')
            assert finishes and finishes[-1]['reason'] == 'complete'
            assert journal.query(kind='drain_start')
        finally:
            srv.shutdown()

    def test_drain_deadline_bounds_a_stuck_replica(
            self, serve_db, enable_local_cloud, monkeypatch):
        mgr = _manager()
        state = {'in_flight': 5, 'queue_depth': 3}   # never drains
        srv, url = _health_server(state)
        torn = []
        monkeypatch.setattr(mgr, 'terminate_replica',
                            lambda rid, status=None: torn.append(rid))
        monkeypatch.setattr(mgr, '_cluster_gone', lambda rid: False)
        monkeypatch.setenv('SKYTPU_SERVE_DRAIN_SECONDS', '0.1')
        try:
            self._seed_ready('dsvc', 1, url=url)
            assert mgr.drain_replica(1)
            rep = serve_state.get_replicas('dsvc')[0]
            start = mgr._drain_started[1]
            mgr._reconcile_draining(rep, start)          # within deadline
            assert torn == []
            mgr._reconcile_draining(rep, start + 0.2)    # past deadline
            assert torn == [1]
            finishes = journal.query(kind='drain_finish')
            assert finishes[-1]['reason'] == 'deadline'
        finally:
            srv.shutdown()

    def test_draining_replica_cannot_resurrect_to_ready(
            self, serve_db, enable_local_cloud):
        self._seed_ready('dsvc', 1)
        assert serve_state.set_replica_status(
            'dsvc', 1, ReplicaStatus.DRAINING)
        # The resurrect-refusal contract: a drain decision sticks.
        assert not serve_state.set_replica_status(
            'dsvc', 1, ReplicaStatus.READY)
        assert not serve_state.set_replica_status(
            'dsvc', 1, ReplicaStatus.NOT_READY)
        assert serve_state.get_replicas('dsvc')[0]['status'] is \
            ReplicaStatus.DRAINING
        # The legal exits still work.
        assert serve_state.set_replica_status(
            'dsvc', 1, ReplicaStatus.SHUTTING_DOWN)

    def test_scale_down_drains_ready_replicas(
            self, serve_db, enable_local_cloud, monkeypatch):
        """Autoscaler scale-down retires via DRAIN, not kill: the shed
        replica transitions to DRAINING (and stays up finishing);
        non-ready replicas still tear down immediately."""
        mgr = _manager()
        monkeypatch.setattr(mgr, '_cluster_gone', lambda rid: False)
        monkeypatch.setattr(replica_managers, 'probe_url',
                            lambda *a, **k: True)
        self._seed_ready('dsvc', 1)
        self._seed_ready('dsvc', 2)
        mgr.reconcile(target=1)
        statuses = {r['replica_id']: r['status']
                    for r in serve_state.get_replicas('dsvc')}
        assert sorted(statuses.values(), key=lambda s: s.value) == \
            [ReplicaStatus.DRAINING, ReplicaStatus.READY]

    def test_drained_engine_completes_all_accepted_requests(
            self, serve_db, enable_local_cloud, engine, monkeypatch):
        """THE zero-loss contract, against a live engine replica:
        requests accepted before the drain decision ALL complete;
        teardown happens only after the engine reports idle."""
        mgr = _manager()
        torn = []
        monkeypatch.setattr(mgr, 'terminate_replica',
                            lambda rid, status=None: torn.append(rid))
        monkeypatch.setattr(mgr, '_cluster_gone', lambda rid: False)

        async def fn():
            eng_srv = AioTestServer(engine_lib.build_app(engine))
            await eng_srv.start_server()
            url = str(eng_srv.make_url('')).rstrip('/')
            self._seed_ready('dsvc', 1, url=url)
            client = TestClient(eng_srv)
            # Accept in-flight work BEFORE the drain decision.
            tasks = [asyncio.create_task(client.post('/generate', json={
                'tokens': [i + 1] * 8, 'max_new_tokens': 40}))
                for i in range(3)]
            await asyncio.sleep(0)      # let them enqueue
            assert mgr.drain_replica(1)
            rep = serve_state.get_replicas('dsvc')[0]
            assert rep['status'] is ReplicaStatus.DRAINING
            # Reconcile-drain loop: poll off-loop so the engine keeps
            # decoding on this loop.
            start = mgr._drain_started[1]
            deadline = time.monotonic() + 60
            while not torn and time.monotonic() < deadline:
                await asyncio.to_thread(
                    mgr._reconcile_draining, rep, start + 1.0)
                await asyncio.sleep(0.05)
            results = []
            for t in tasks:
                r = await t
                results.append((r.status, await r.json()))
            await client.close()
            await eng_srv.close()
            return results

        results = _run(fn())
        assert torn == [1]
        # 100% of accepted requests completed, in full.
        for status, body in results:
            assert status == 200
            assert len(body['tokens']) == 40
        finishes = journal.query(kind='drain_finish')
        assert finishes and finishes[-1]['reason'] == 'complete'
