"""Chaos proof for the disaggregated input service (ISSUE 10).

The load-bearing invariant: the batch at step N is a pure function of
``(seed, corpus, step)``, so killing a data worker mid-run — SIGKILL,
no goodbye — changes NOTHING about training except a bounded stall:

  * a real (single-device CPU jax) train loop fed by the service with
    3 workers, one SIGKILLed mid-run under seeded failpoints, produces
    a loss trajectory BIT-IDENTICAL to an unchurned 1-worker run;
  * the dispatcher journals the death (``data_worker_lost``) and the
    split handoff (``data_worker_reassign``);
  * the stall is bounded by the configured heartbeat timeout plus the
    client's backoff budget, not by luck.

Workers are REAL subprocesses of ``python -m skypilot_tpu.data_service
worker`` (no jax inside — a data worker is pure CPU/numpy); the
dispatcher runs in-process so the test can read its journal and DB.
This extends the churn methodology of test_train_churn.py (mesh churn)
to the input plane.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from skypilot_tpu.data_service import client as client_lib
from skypilot_tpu.data_service import dispatcher as dispatcher_lib
from skypilot_tpu.data_service import protocol
from skypilot_tpu.data_service import spec as spec_lib
from skypilot_tpu.observe import journal
from skypilot_tpu.utils import failpoints

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

HEARTBEAT_TIMEOUT = 1.5
HEARTBEAT_INTERVAL = 0.3
STALL_BUDGET_S = 60.0
VOCAB = 64
STEPS = 16
KILL_AT_STEP = 6


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_OBSERVE_DB', str(tmp_path / 'observe.db'))
    failpoints.reset()
    yield
    failpoints.reset()


@pytest.fixture
def corpus(tmp_path):
    rng = np.random.default_rng(42)
    path = tmp_path / 'corpus.npy'
    np.save(path, rng.integers(0, VOCAB, size=20_000).astype(np.int32))
    return str(path)


def _spec(corpus):
    return spec_lib.DatasetSpec(batch_size=8, seq_len=32,
                                vocab_size=VOCAB, seed=5,
                                data_path=corpus)


def _spawn_worker(dispatcher_addr, extra_env=None):
    env = {**os.environ, 'PYTHONPATH': REPO}
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.data_service', 'worker',
         '--dispatcher', f'{dispatcher_addr[0]}:{dispatcher_addr[1]}',
         '--host', '127.0.0.1',
         '--heartbeat-interval', str(HEARTBEAT_INTERVAL)],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)


def _wait_workers(dispatcher, n, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reply, _ = protocol.request(dispatcher.addr, {'op': 'routes'},
                                    timeout=5.0)
        if len(reply['workers']) >= n and \
                len(reply['assignments']) == dispatcher.num_splits:
            return reply
        time.sleep(0.1)
    raise AssertionError(f'{n} workers not routable within {timeout}s')


def _train_losses(batches, on_step=None):
    """A real (tiny) train loop: single-device CPU jax, SGD on an
    embed->logits LM. Single device on purpose — no ambient-mesh APIs,
    so this runs on every jax version the repo supports, and two runs
    in one process execute the identical jitted program (bit-equal
    inputs => bit-equal losses)."""
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {'emb': jax.random.normal(k1, (VOCAB, 16)) * 0.02,
              'out': jax.random.normal(k2, (16, VOCAB)) * 0.02}

    def loss_of(p, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        logits = p['emb'][inp] @ p['out']
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tgt[..., None],
                                   axis=-1)[..., 0]
        return (logz - gold).mean()

    @jax.jit
    def step_fn(p, tokens):
        loss, grads = jax.value_and_grad(loss_of)(p, tokens)
        return jax.tree.map(lambda a, g: a - 0.1 * g, p, grads), loss

    losses = []
    gaps = []
    t_prev = time.monotonic()
    for step in range(STEPS):
        batch = next(batches)
        gaps.append(time.monotonic() - t_prev)
        params, loss = step_fn(params, jnp.asarray(batch['tokens']))
        losses.append(float(loss))
        t_prev = time.monotonic()
        if on_step is not None:
            on_step(step)
    return losses, gaps


def _service_run(tmp_path, tag, corpus, n_workers, *, kill_one=False,
                 worker_env=None, client_faults=False):
    d = dispatcher_lib.Dispatcher(
        str(tmp_path / f'disp-{tag}.db'), num_splits=4,
        heartbeat_timeout=HEARTBEAT_TIMEOUT).start()
    procs = [_spawn_worker(d.addr, worker_env) for _ in range(n_workers)]
    killed = {}
    try:
        before = _wait_workers(d, n_workers)
        if client_faults:
            # Seeded probabilistic fetch faults: bit-reproducible
            # chaos on the client's retry path, on top of the kill.
            failpoints.arm('data.fetch', prob=0.2, seed=9)
        cl = client_lib.DataServiceClient(
            f'{d.addr[0]}:{d.addr[1]}', _spec(corpus),
            stall_budget_s=STALL_BUDGET_S)
        cl.start()

        def on_step(step):
            if kill_one and step == KILL_AT_STEP and not killed:
                procs[0].send_signal(signal.SIGKILL)
                procs[0].wait(timeout=10)
                killed['at'] = time.monotonic()
                killed['survivors'] = None

        try:
            losses, gaps = _train_losses(iter(cl), on_step=on_step)
        finally:
            failpoints.reset()
            cl.close()
        after, _ = protocol.request(d.addr, {'op': 'routes'},
                                    timeout=5.0)
        if kill_one:
            killed['dead_id'] = (set(before['workers']) -
                                 set(after['workers'])).pop()
        return losses, gaps, killed
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                p.wait(timeout=10)
        d.stop()


class TestInputChurn:

    def test_worker_kill_is_invisible_to_the_loss_trajectory(
            self, tmp_path, corpus):
        """THE acceptance pin: unchurned 1-worker run vs 3-worker run
        with one SIGKILL mid-run (+ seeded fetch faults + heartbeat
        faults on the workers) — bit-identical losses, journaled
        reassignment, bounded stall."""
        base_losses, base_gaps, _ = _service_run(
            tmp_path, 'base', corpus, n_workers=1)
        churn_losses, churn_gaps, killed = _service_run(
            tmp_path, 'churn', corpus, n_workers=3, kill_one=True,
            client_faults=True,
            worker_env={'SKYTPU_FAILPOINTS': 'data.heartbeat=every:7'})

        # Bit-identical: not allclose — IDENTICAL. The input stream is
        # a pure function of (seed, corpus, step); worker churn and
        # injected faults must not perturb one bit of it.
        assert churn_losses == base_losses
        assert len(base_losses) == STEPS

        # The kill was real and journaled: lost + reassign events for
        # the killed worker id, with the orphaned splits named.
        dead_id = killed['dead_id']
        events = {}
        for ev in journal.query(limit=200):
            if ev['entity'] == dead_id:
                events.setdefault(ev['kind'], []).append(ev)
        assert 'data_worker_lost' in events
        reassigns = events['data_worker_reassign']
        assert reassigns and reassigns[0]['data']['splits']

        # Bounded stall: no inter-batch gap beyond the heartbeat
        # timeout + reaper cadence + a few backoff rounds (generous
        # slack for this contended box, but a BOUND — pre-containment
        # the stream would hang on the dead worker forever).
        stall_bound = HEARTBEAT_TIMEOUT * 2 + 10.0
        assert max(churn_gaps) < stall_bound, (
            f'max inter-batch gap {max(churn_gaps):.1f}s exceeds the '
            f'{stall_bound:.1f}s heartbeat+backoff budget')

    def test_post_kill_pool_still_balanced(self, tmp_path, corpus):
        """After the reaper evicts a killed worker, the survivors own
        every split (no orphaned split may strand a step forever)."""
        d = dispatcher_lib.Dispatcher(
            str(tmp_path / 'disp-bal.db'), num_splits=4,
            heartbeat_timeout=HEARTBEAT_TIMEOUT).start()
        procs = [_spawn_worker(d.addr) for _ in range(2)]
        try:
            _wait_workers(d, 2)
            procs[1].send_signal(signal.SIGKILL)
            procs[1].wait(timeout=10)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                reply, _ = protocol.request(d.addr, {'op': 'routes'},
                                            timeout=5.0)
                if len(reply['workers']) == 1 and \
                        len(reply['assignments']) == 4:
                    break
                time.sleep(0.1)
            assert len(reply['workers']) == 1
            assert set(reply['assignments'].values()) == \
                set(reply['workers'])
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=10)
            d.stop()

    def test_cli_dispatcher_readiness_and_stats(self, tmp_path):
        """The `python -m skypilot_tpu.data_service dispatcher` entry:
        readiness JSON on stdout, stats answerable over the wire."""
        env = {**os.environ, 'PYTHONPATH': REPO,
               'SKYTPU_OBSERVE_DB': str(tmp_path / 'cli-observe.db')}
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.data_service',
             'dispatcher', '--host', '127.0.0.1', '--port', '0',
             '--db', str(tmp_path / 'cli-disp.db'),
             '--num-splits', '2'],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        try:
            ready = None
            for _ in range(10):   # log lines may precede the JSON
                line = proc.stdout.readline().strip()
                if line.startswith('{'):
                    ready = json.loads(line)
                    break
            assert ready is not None, 'no readiness JSON on stdout'
            assert ready['role'] == 'dispatcher'
            addr = protocol.parse_addr(ready['addr'])
            reply, _ = protocol.request(addr, {'op': 'stats'},
                                        timeout=10.0)
            assert reply['num_splits'] == 2
        finally:
            proc.terminate()
            proc.wait(timeout=10)
