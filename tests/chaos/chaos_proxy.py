"""TCP chaos proxy: forwards client↔server traffic, killing every Nth
connection mid-flight.

Reference analog: tests/chaos/chaos_proxy.py — placed between the client
and the API server to prove the control plane degrades cleanly (clear
errors, no corrupted state) under network faults.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Optional


class ChaosProxy:

    def __init__(self, upstream_host: str, upstream_port: int,
                 kill_every: int = 3):
        """Every `kill_every`-th connection is accepted then torn down
        after the first payload bytes flow — the nastiest failure point."""
        self.upstream = (upstream_host, upstream_port)
        self.kill_every = kill_every
        self._count = 0
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.port: Optional[int] = None

    # ------------------------------------------------------------------
    def start(self) -> int:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(('127.0.0.1', 0))
        self._listener.listen(32)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._count += 1
                doomed = (self._count % self.kill_every == 0)
            threading.Thread(target=self._handle,
                             args=(client, doomed), daemon=True).start()

    def _handle(self, client: socket.socket, doomed: bool) -> None:
        try:
            upstream = socket.create_connection(self.upstream, timeout=10)
        except OSError:
            client.close()
            return

        def pump(src, dst, kill_after_first: bool):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
                    if kill_after_first:
                        # Chaos: first bytes made it through, then the
                        # connection dies (RST via SO_LINGER 0).
                        for s in (client, upstream):
                            try:
                                s.setsockopt(
                                    socket.SOL_SOCKET, socket.SO_LINGER,
                                    struct.pack('ii', 1, 0))
                                s.close()
                            except OSError:
                                pass
                        return
            except OSError:
                pass
            finally:
                for s in (client, upstream):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        threading.Thread(target=pump, args=(upstream, client, False),
                         daemon=True).start()
        pump(client, upstream, doomed)
