"""TCP chaos proxy: forwards client↔server traffic, injecting faults.

Reference analog: tests/chaos/chaos_proxy.py — placed between the client
and the API server (and, since the serving-robustness work, between the
serve LB and an engine replica) to prove both planes degrade cleanly
(clear errors, no corrupted state, bounded client-visible failures)
under network faults.

Fault modes (per doomed connection, every ``kill_every``-th):
  mode='midstream'    kill after the first REQUEST bytes flow — the
                      server got (some of) the request; the response
                      dies. Downstream of an LB this looks like an
                      upstream disconnection before/at response start.
  mode='response'     forward the request intact, then kill after the
                      first RESPONSE bytes reach the client — a true
                      mid-stream kill (the client already has data).
  mode='mid_headers'  kill the instant the server starts answering,
                      BEFORE any response byte is forwarded — the
                      nastiest LB case: request fully delivered,
                      response headers lost.

``byte_delay`` > 0 turns the proxy into a slow-loris: every response
chunk is trickled after that many seconds, on EVERY connection —
tripping between-bytes (sock_read) timeouts without ever going silent.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional


def _rst_close(*socks: socket.socket) -> None:
    """Hard-kill sockets with RST via SO_LINGER 0."""
    for s in socks:
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack('ii', 1, 0))
            s.close()
        except OSError:
            pass


class ChaosProxy:

    def __init__(self, upstream_host: str, upstream_port: int,
                 kill_every: int = 3, mode: str = 'midstream',
                 byte_delay: float = 0.0):
        """Every `kill_every`-th connection is accepted then torn down
        at the point `mode` selects — after first payload bytes flow
        (the nastiest failure point), after first response bytes, or
        just before any response byte escapes."""
        if mode not in ('midstream', 'response', 'mid_headers'):
            raise ValueError(f'unknown chaos mode {mode!r}')
        self.upstream = (upstream_host, upstream_port)
        self.kill_every = kill_every
        self.mode = mode
        self.byte_delay = byte_delay
        self._count = 0
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.port: Optional[int] = None

    # ------------------------------------------------------------------
    def start(self) -> int:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(('127.0.0.1', 0))
        self._listener.listen(32)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._count += 1
                doomed = (self._count % self.kill_every == 0)
            threading.Thread(target=self._handle,
                             args=(client, doomed), daemon=True).start()

    def _handle(self, client: socket.socket, doomed: bool) -> None:
        try:
            upstream = socket.create_connection(self.upstream, timeout=10)
        except OSError:
            client.close()
            return

        # Which direction's first bytes trigger the kill:
        #   midstream   → client→upstream (request bytes made it)
        #   response    → upstream→client AFTER forwarding one chunk
        #   mid_headers → upstream→client BEFORE forwarding anything
        kill_on_request = doomed and self.mode == 'midstream'
        kill_on_response = doomed and self.mode in ('response',
                                                    'mid_headers')
        kill_before_forward = doomed and self.mode == 'mid_headers'

        def pump(src, dst, kill_after_first: bool,
                 kill_before: bool = False, delay: float = 0.0) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if kill_before:
                        # The server answered; no response byte may
                        # escape (mid-headers kill).
                        _rst_close(client, upstream)
                        return
                    if delay > 0:
                        time.sleep(delay)
                    dst.sendall(data)
                    if kill_after_first:
                        # Chaos: first bytes made it through, then the
                        # connection dies (RST via SO_LINGER 0).
                        _rst_close(client, upstream)
                        return
            except OSError:
                pass
            finally:
                for s in (client, upstream):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        threading.Thread(
            target=pump,
            args=(upstream, client, kill_on_response),
            kwargs={'kill_before': kill_before_forward,
                    'delay': self.byte_delay},
            daemon=True).start()
        pump(client, upstream, kill_on_request)
