"""Fleet telemetry plane, END TO END on a live CPU stack (the ISSUE 9
acceptance arc). Marked slow — two real engine subprocesses warm up in
it — so tier-1 (-m 'not slow') skips it; run explicitly:

    JAX_PLATFORMS=cpu pytest tests/chaos/test_fleet_e2e.py -m slow

One test, three acts against TWO live `skypilot_tpu.serve.engine`
replicas behind a real LoadBalancer wired exactly as the service
controller wires it (Scraper + SLOEngine + ScrapeLoop + attach_fleet):

  1. traffic through the LB → merged fleet TTFT/TPOT quantiles at
     /-/fleet/metrics, per-replica saturation at /-/fleet/status, the
     `observe fleet` CLI against the live endpoints;
  2. kill one replica → scrape_failed journal events, the staleness
     gauge trips, the availability SLO escalates to breach with a
     journaled slo_breach event carrying both burn rates;
  3. the saturation autoscaler consumed scraped queue depth while
     fresh, and falls back to the QPS signal once samples go stale.
"""
import asyncio
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


@pytest.fixture()
def fleet_env(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_OBSERVE_DB', str(tmp_path / 'observe.db'))
    monkeypatch.setenv('SKYTPU_SATURATION_STALE_SECONDS', '5')
    from skypilot_tpu.observe import metrics
    metrics.REGISTRY.reset_for_tests()
    yield tmp_path
    metrics.REGISTRY.reset_for_tests()


def test_fleet_plane_end_to_end(fleet_env):
    from aiohttp import web

    from skypilot_tpu.observe import journal
    from skypilot_tpu.observe import metrics
    from skypilot_tpu.observe import promtext
    from skypilot_tpu.observe import scrape
    from skypilot_tpu.observe import slo as slo_lib
    from skypilot_tpu.serve import autoscalers as autoscaler_lib
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve import service_spec as spec_lib

    ports = [_free_port(), _free_port()]
    engines = []
    for p in ports:
        engines.append(subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.serve.engine',
             '--model', 'llama-debug', '--max-len', '64',
             '--warm-buckets', '16', '--host', '127.0.0.1',
             '--port', str(p)],
            stdout=sys.stderr, stderr=sys.stderr,
            env={**os.environ, 'JAX_PLATFORMS': 'cpu',
                 'SKYTPU_OBSERVE_DB': str(fleet_env / f'rep-{p}.db')}))
    try:
        deadline = time.time() + 300
        for p in ports:
            while True:
                try:
                    if json.loads(_get(
                            f'http://127.0.0.1:{p}/health'))['status'] \
                            == 'ok':
                        break
                except OSError:
                    pass
                assert time.time() < deadline, 'engine never ready'
                time.sleep(1)

        policy = spec_lib.ReplicaPolicy(
            min_replicas=1, max_replicas=4, target_qps_per_replica=2.0,
            target_queue_depth_per_replica=2.0,
            upscale_delay_seconds=0.0, downscale_delay_seconds=0.0)
        autoscaler = autoscaler_lib.Autoscaler.make(policy)
        assert isinstance(autoscaler,
                          autoscaler_lib.SaturationAutoscaler)
        scraper = scrape.Scraper(timeout=2.0, staleness_seconds=5.0)
        slo_engine = slo_lib.SLOEngine([slo_lib.SLOSpec(
            kind='availability', objective=0.9, fast_window=6.0,
            slow_window=15.0, fast_burn=1.5, slow_burn=1.0,
            clear_rounds=3)], entity='fleet-demo')
        lb = lb_lib.LoadBalancer('least_load', autoscaler,
                                 service_name='fleet-demo')
        lb.attach_fleet(scraper, slo_engine)
        urls = [f'http://127.0.0.1:{p}' for p in ports]
        lb.set_ready_replicas(urls)
        scraper.set_targets([scrape.Target(f'fleet-demo/{i}', u)
                             for i, u in enumerate(urls)])

        def on_round(s):
            snap = s.saturation_snapshot()
            depths = {u: sat.queue_depth for u, sat in snap.items()}
            lb.set_replica_saturation(depths)
            autoscaler.observe_saturation(depths)
            slo_engine.evaluate()

        scrape_loop = scrape.ScrapeLoop(scraper, interval=1.0,
                                        on_round=on_round)
        lb_port = _free_port()

        async def arc():
            runner = web.AppRunner(lb.build_app())
            await runner.setup()
            await web.TCPSite(runner, '127.0.0.1', lb_port).start()
            scrape_loop.start()
            try:
                import aiohttp
                async with aiohttp.ClientSession() as sess:
                    async def one(i):
                        async with sess.post(
                                f'http://127.0.0.1:{lb_port}/generate',
                                json={'tokens': [(i % 30) + 1] * 8,
                                      'max_new_tokens': 4}) as r:
                            assert r.status == 200, await r.text()
                            await r.json()
                    await asyncio.gather(*(one(i) for i in range(12)))
                await asyncio.sleep(3)      # a couple of rounds

                # Act 1: merged fleet quantiles + status + CLI.
                text = await asyncio.to_thread(
                    _get, f'http://127.0.0.1:{lb_port}/-/fleet/metrics')
                for fam in ('skytpu_engine_ttft_seconds',
                            'skytpu_engine_tpot_seconds'):
                    for q in (0.5, 0.95):
                        v = promtext.quantile_from_text(text, fam, q)
                        assert v == v, f'NaN fleet quantile for {fam}'
                fams = promtext.parse(text)
                reqs = sum(s.value for s in fams[
                    'skytpu_engine_requests_total'].samples)
                assert reqs >= 12      # both replicas' counters merged
                status = json.loads(await asyncio.to_thread(
                    _get, f'http://127.0.0.1:{lb_port}/-/fleet/status'))
                assert len(status['replicas']) == 2
                assert status['slo'] == {'availability': 'ok'}
                cli = await asyncio.to_thread(
                    subprocess.run,
                    [sys.executable, '-m', 'skypilot_tpu.observe',
                     'fleet', '--url', f'127.0.0.1:{lb_port}'],
                    capture_output=True, text=True,
                    env={**os.environ, 'PYTHONPATH': REPO})
                assert cli.returncode == 0, cli.stderr
                assert 'ttft_p95_ms' in cli.stdout

                # Act 2: kill replica 1 → journal + staleness + breach.
                engines[1].kill()
                engines[1].wait()
                t_end = time.time() + 30
                while time.time() < t_end and \
                        slo_engine.state('availability') != 'breach':
                    await asyncio.sleep(0.5)
                assert slo_engine.state('availability') == 'breach'
                failed = journal.query(kind='scrape_failed')
                assert failed
                assert all(e['entity'] == 'fleet-demo/1'
                           for e in failed)
                breaches = journal.query(kind='slo_breach')
                assert breaches
                assert breaches[0]['data']['burn_fast'] >= 1.5
                t_end = time.time() + 20    # staleness window trails
                stale = 0.0
                while time.time() < t_end:
                    stale = metrics.REGISTRY._metrics[  # pylint: disable=protected-access
                        'skytpu_scrape_stale_targets'].value()
                    if stale >= 1:
                        break
                    await asyncio.sleep(0.5)
                assert stale >= 1
                status = json.loads(await asyncio.to_thread(
                    _get, f'http://127.0.0.1:{lb_port}/-/fleet/status'))
                assert status['slo'] == {'availability': 'breach'}

                # Act 3: stop scraping → snapshot stale → QPS fallback.
                scrape_loop.stop()
                await asyncio.sleep(6)
                for _ in range(10):
                    autoscaler.record_request()
                autoscaler.target_replicas()
                fb = metrics.REGISTRY._metrics[  # pylint: disable=protected-access
                    'skytpu_serve_autoscaler_fallback_total'].value(
                        reason='stale')
                assert fb >= 1
            finally:
                scrape_loop.stop()
                await runner.cleanup()

        asyncio.run(arc())
    finally:
        for e in engines:
            if e.poll() is None:
                e.terminate()
        for e in engines:
            try:
                e.wait(timeout=10)
            except subprocess.TimeoutExpired:
                e.kill()
