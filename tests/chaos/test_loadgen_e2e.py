"""The traffic harness END TO END on a live CPU stack (the ISSUE 12
acceptance arc). Marked slow — two real engine subprocesses warm up
inside it — so tier-1 (-m 'not slow') skips it; run explicitly:

    JAX_PLATFORMS=cpu pytest tests/chaos/test_loadgen_e2e.py -m slow

One test, one CLI invocation, every contract checked on the artifact:

  * the scorecard's per-class TTFT/TPOT quantiles are FLEET-attributed
    (present for every class the schedule offered, parsed from
    /-/fleet/metrics — client stopwatches are labeled secondary);
  * goodput books balance: fleet-side good+slow equals the client's
    completed count, and the burn/state columns agree with the SLO
    engine's journaled slo_* events;
  * the run replays: the scorecard's schedule hash equals a --dry-run
    of the same (profile, seed);
  * the consistent-hash evidence rides along: restart stability >= 0.9
    with zero load-bound violations, and the live mid-run LB restart
    (churn scenario) did not collapse the prefix hit rate.
"""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_loadgen_harness_end_to_end(tmp_path):
    report = tmp_path / 'scorecard.json'
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu', 'PYTHONPATH': REPO,
           'SKYTPU_OBSERVE_DB': str(tmp_path / 'observe.db')}
    run = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.loadgen',
         '--seed', '7', '--profile', 'smoke', '--local-stack', '2',
         '--run-dir', str(tmp_path), '--report', str(report)],
        capture_output=True, text=True, env=env, timeout=560)
    assert run.returncode == 0, run.stderr[-2000:]
    card = json.loads(report.read_text())

    # Replay contract: the live run's hash is the dry-run's hash.
    dry = json.loads(subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.loadgen',
         '--seed', '7', '--profile', 'smoke', '--dry-run'],
        capture_output=True, text=True, env=env, check=True).stdout)
    assert card['schedule_hash'] == dry['schedule_hash']

    # Fleet-attributed per-class columns for every offered class.
    offered = card['offered']['by_class']
    fleet = card['fleet']['by_class']
    for cls, truth in offered.items():
        row = fleet[cls]
        assert row['ttft_p95_ms'] > 0, cls
        # Books balance: every offered request finished and was judged.
        assert row['good'] + row['slow'] == truth['requests'], cls
    assert card['client']['errors'] == 0
    assert (sum(r['good'] + r['slow'] for r in fleet.values()) ==
            card['client']['completed'])

    # Burn/state columns agree with the journaled SLO events: any
    # class in a non-ok state must have a matching slo_* event whose
    # payload names it (and vice versa for breach events).
    states = card['slo']['states']
    events = card.get('slo_events') or []
    for kind, state in states.items():
        if state != 'ok':
            assert any(e['data']['kind'] == kind for e in events), kind
    for e in events:
        assert e['data']['kind'] in states

    # Consistent-hash evidence: restart stability with the bound held,
    # and the live LB restart didn't collapse prefix hits (phase 2
    # serves warmed sessions, so its hit rate must not drop below the
    # cold phase's).
    routing = card['routing']
    assert routing['restart_stability'] >= 0.9
    assert routing['bound_violations'] == 0
    churn = routing['live_churn']
    assert churn['phase2']['hit_rate'] >= churn['phase1']['hit_rate']
