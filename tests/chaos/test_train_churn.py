"""Chaos churn suite: elastic training under seeded preemption schedules.

The jobs-plane acceptance contract (docs/ROBUSTNESS.md): under a
deterministic preemption schedule — kills mid-step, kills mid-save,
SIGTERM grace windows — training resumes on a DIFFERENT mesh shape each
time, through the topology-independent checkpoint path, and the stitched
loss trajectory is bit-identical to a run that was never preempted.
Partial checkpoints (a save killed before its manifest commit) must
never be restored; corrupt steps must be refused loudly with fallback
to the newest older complete step; jobs-plane recovery must stay inside
its configured budget with per-attempt journal evidence.

Episodes run tests/chaos/churn_trainer.py as subprocesses (a real kill
needs a real process); the jobs-plane budget/journal tests drive
recovery_strategy in-process with launches stubbed out.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
HARNESS = os.path.join(REPO, 'tests', 'chaos', 'churn_trainer.py')

TOTAL_STEPS = 12


def _env(tmp, failpoints_spec=''):
    env = dict(os.environ)
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=8',
        'PYTHONPATH': REPO,
        'SKYTPU_OBSERVE_DB': os.path.join(str(tmp), 'journal.db'),
        # jax 0.4.37's persistent compile cache SEGFAULTS reloading
        # this suite's program mix (reproduced deterministically with
        # the cache on, clean with it off); the model is tiny, so
        # cold compiles cost ~1s per episode.
        'JAX_ENABLE_COMPILATION_CACHE': 'false',
    })
    env.pop('JAX_COMPILATION_CACHE_DIR', None)
    if failpoints_spec:
        env['SKYTPU_FAILPOINTS'] = failpoints_spec
    else:
        env.pop('SKYTPU_FAILPOINTS', None)
    return env


def _episode(tmp, ckpt_dir, losses, *, mesh, steps=TOTAL_STEPS,
             ckpt_every=1000, failpoints_spec='', devices=0,
             step_seconds=0.0, check=True, timeout=240):
    cmd = [sys.executable, HARNESS, '--ckpt-dir', str(ckpt_dir),
           '--losses', str(losses), '--steps', str(steps),
           '--mesh', mesh, '--ckpt-every', str(ckpt_every)]
    if devices:
        cmd += ['--devices', str(devices)]
    if step_seconds:
        cmd += ['--step-seconds', str(step_seconds)]
    proc = subprocess.run(cmd, env=_env(tmp, failpoints_spec),
                          capture_output=True, text=True, cwd=REPO,
                          timeout=timeout)
    if check:
        assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc


def _read_losses(path):
    """{step: loss}; a step logged twice (an overlap re-run after a
    restore) must be bit-identical both times — diverging duplicates
    mean a partial or stale checkpoint was restored."""
    out = {}
    with open(path, encoding='utf-8') as f:
        for line in f:
            rec = json.loads(line)
            if rec['step'] in out:
                assert out[rec['step']] == rec['loss'], (
                    f'step {rec["step"]} diverged across episodes: '
                    f'{out[rec["step"]]} vs {rec["loss"]} — a resumed '
                    f'episode did not restore the exact saved state')
            out[rec['step']] = rec['loss']
    return out


@pytest.fixture(scope='module')
def reference(tmp_path_factory):
    """The unpreempted ground truth: TOTAL_STEPS straight on a 2x4
    mesh, no churn, no checkpoint interference."""
    tmp = tmp_path_factory.mktemp('ref')
    losses = tmp / 'losses.jsonl'
    _episode(tmp, tmp / 'ckpt', losses, mesh='data=2,fsdp=4')
    ref = _read_losses(losses)
    assert sorted(ref) == list(range(1, TOTAL_STEPS + 1))
    return ref


class TestChurnTrajectory:

    def test_seeded_churn_matches_unpreempted_exactly(self, tmp_path,
                                                      reference):
        """The seeded schedule: failpoint preemption on 2x4 → resume on
        1x8 and die MID-SAVE → resume on 4x2 (from the last complete
        step, never the partial) → finish. Stitched losses must equal
        the unpreempted run bit-for-bit."""
        ckpt = tmp_path / 'ckpt'
        losses = tmp_path / 'losses.jsonl'

        # Episode 1 (mesh 2x4): trainer.preempt fires at step 6 → one
        # final save, clean exit.
        p1 = _episode(tmp_path, ckpt, losses, mesh='data=2,fsdp=4',
                      failpoints_spec='trainer.preempt=every:6')
        assert 'PREEMPTED step=6' in p1.stdout
        assert 'SAVED step=6' in p1.stdout

        # Episode 2 (mesh 1x8): resumes at 6, then ckpt.save fires
        # inside the step-9 cadence save — chunks on disk, no manifest
        # commit — and the process dies mid-save.
        p2 = _episode(tmp_path, ckpt, losses, mesh='data=1,fsdp=8',
                      ckpt_every=3, failpoints_spec='ckpt.save=once',
                      check=False)
        assert p2.returncode != 0, p2.stdout + p2.stderr
        assert 'RESUMED step=6' in p2.stdout
        assert 'SAVING step=9' in p2.stdout
        assert 'SAVED step=9' not in p2.stdout
        assert 'failpoint' in p2.stderr     # the injected fault, loudly

        # The killed save is invisible: no step_00000009, and the
        # in-progress temp dir holds no manifest.
        names = sorted(os.listdir(ckpt))
        assert 'step_00000009' not in names
        partial = [n for n in names if n.startswith('.tmp-')]
        for name in partial:
            assert 'MANIFEST.json' not in os.listdir(ckpt / name)

        # Episode 3 (mesh 4x2): must resume from step 6 — the newest
        # COMPLETE step — never the partial 9; runs to completion.
        p3 = _episode(tmp_path, ckpt, losses, mesh='data=4,fsdp=2')
        assert 'RESUMED step=6' in p3.stdout
        assert 'FINISHED step=12' in p3.stdout

        churn = _read_losses(losses)
        assert sorted(churn) == list(range(1, TOTAL_STEPS + 1))
        for step in range(1, TOTAL_STEPS + 1):
            assert churn[step] == reference[step], (
                f'step {step}: churn {churn[step]!r} != unpreempted '
                f'{reference[step]!r}')

    def test_corrupt_newest_step_refused_with_fallback(self, tmp_path,
                                                       reference):
        """Truncate a chunk of the newest checkpoint: the relaunch must
        refuse it LOUDLY, fall back to the older complete step, and
        still reproduce the reference trajectory."""
        ckpt = tmp_path / 'ckpt'
        losses = tmp_path / 'losses.jsonl'
        _episode(tmp_path, ckpt, losses, mesh='data=2,fsdp=4',
                 steps=8, ckpt_every=4)
        step_dir = ckpt / 'step_00000008'
        chunks = sorted(p for p in (step_dir / 'arrays').iterdir())
        with open(chunks[0], 'r+b') as f:
            f.truncate(64)
        p2 = _episode(tmp_path, ckpt, losses, mesh='data=1,fsdp=8',
                      steps=TOTAL_STEPS)
        assert 'RESUMED step=4' in p2.stdout   # 8 refused, 4 restored
        churn = _read_losses(losses)
        for step in range(1, TOTAL_STEPS + 1):
            assert churn[step] == reference[step]

    def test_sigterm_grace_saves_final_checkpoint(self, tmp_path,
                                                  reference):
        """A real preemption notice: SIGTERM mid-run → final save at
        the interrupted step → resume on a reshaped mesh lands exactly
        there, trajectory intact; a single-host resume restores the
        same step too (the slice shape is gone entirely)."""
        ckpt = tmp_path / 'ckpt'
        losses = tmp_path / 'losses.jsonl'
        env = _env(tmp_path)
        proc = subprocess.Popen(
            [sys.executable, HARNESS, '--ckpt-dir', str(ckpt),
             '--losses', str(losses), '--steps', str(TOTAL_STEPS),
             '--mesh', 'data=2,fsdp=4', '--ckpt-every', '1000',
             '--step-seconds', '0.2'],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO)
        deadline = time.time() + 180
        while time.time() < deadline:
            if losses.exists() and len(losses.read_text().splitlines()) >= 3:
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, out + err
        assert 'PREEMPTED step=' in out
        final_step = int(out.split('PREEMPTED step=')[1].split()[0])
        assert final_step < TOTAL_STEPS  # it really was interrupted

        # Single-host resume (on a COPY, so the main resume below still
        # sees the preemption-time checkpoint): restores the SAME step
        # and continues. Loss comparison is allclose, not bit-equal —
        # a 1-device reduction legitimately reassociates float sums vs
        # the 8-device reference (the bit-exact contract holds across
        # mesh SHAPES at equal device count).
        solo_ckpt = tmp_path / 'solo_ckpt'
        shutil.copytree(ckpt, solo_ckpt)
        solo_losses = tmp_path / 'solo.jsonl'
        p3 = _episode(tmp_path, solo_ckpt, solo_losses,
                      mesh='data=1,fsdp=1', devices=1,
                      steps=final_step + 2)
        assert f'RESUMED step={final_step}' in p3.stdout
        solo = _read_losses(solo_losses)
        assert sorted(solo) == [final_step + 1, final_step + 2]
        for step, loss in solo.items():
            np.testing.assert_allclose(loss, reference[step], rtol=1e-5)

        p2 = _episode(tmp_path, ckpt, losses, mesh='data=4,fsdp=2')
        assert f'RESUMED step={final_step}' in p2.stdout
        churn = _read_losses(losses)
        for step in range(1, TOTAL_STEPS + 1):
            assert churn[step] == reference[step]


class TestJobsPlaneRecovery:

    @pytest.fixture(autouse=True)
    def _observe_db(self, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYTPU_OBSERVE_DB',
                           str(tmp_path / 'journal.db'))
        from skypilot_tpu.utils import failpoints
        yield
        failpoints.reset()

    def _journal_events(self, kind):
        from skypilot_tpu.observe import journal
        return journal.query(kind=kind, limit=1000)

    def _strategy(self, monkeypatch, job_id=7, fail_with=None):
        from skypilot_tpu import exceptions
        from skypilot_tpu.jobs import recovery_strategy

        strategy = recovery_strategy.FailoverStrategyExecutor.__new__(
            recovery_strategy.FailoverStrategyExecutor)
        strategy.cluster_name = 'chaos-train'
        strategy.task = None
        strategy.job_id = job_id
        strategy.handle = None
        attempts = []

        def _launch_once(**kwargs):
            attempts.append(kwargs)
            raise (fail_with or exceptions.ResourcesUnavailableError)(
                'no capacity (stub)')

        monkeypatch.setattr(strategy, '_launch_once', _launch_once)
        monkeypatch.setattr(strategy, 'terminate_cluster',
                            lambda max_retries=3: None)
        monkeypatch.setattr(recovery_strategy.state,
                            'cancel_was_requested', lambda job_id: False)
        return strategy, attempts

    def test_round_budget_bounds_attempts_with_journal(self, tmp_path,
                                                       monkeypatch):
        """max-rounds budget: exactly N journaled attempts, then a
        journaled exhaustion and ManagedJobReachedMaxRetriesError."""
        from skypilot_tpu import exceptions
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_MAX_ROUNDS', '3')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_BASE_SECONDS', '0.01')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_CAP_SECONDS', '0.02')
        strategy, attempts = self._strategy(monkeypatch)
        with pytest.raises(exceptions.ManagedJobReachedMaxRetriesError):
            strategy.recover()
        assert len(attempts) == 3   # one unconstrained try per round
        events = self._journal_events('jobs_recovery_attempt')
        assert len(events) == 3
        for event in events:
            assert event['entity'] == '7'
            assert event['data']['outcome'] == 'no_capacity'
            assert event['data']['phase'] == 'unconstrained'
        exhausted = self._journal_events('jobs_recovery_exhausted')
        assert len(exhausted) == 1
        assert exhausted[0]['data']['max_rounds'] == 3

    def test_wallclock_budget_bounds_recovery(self, tmp_path,
                                              monkeypatch):
        from skypilot_tpu import exceptions
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_MAX_ROUNDS', '10000')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_BUDGET_SECONDS', '0.3')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_BASE_SECONDS', '0.05')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_CAP_SECONDS', '0.1')
        strategy, attempts = self._strategy(monkeypatch)
        t0 = time.monotonic()
        with pytest.raises(exceptions.ManagedJobReachedMaxRetriesError,
                           match='budget'):
            strategy.recover()
        assert time.monotonic() - t0 < 5.0
        assert 1 <= len(attempts) < 100
        exhausted = self._journal_events('jobs_recovery_exhausted')
        assert 'budget' in exhausted[0]['reason']

    def test_injected_launch_fault_classed_and_contained(self, tmp_path,
                                                         monkeypatch):
        """An armed jobs.launch failpoint inside a recovery attempt is
        journaled as outcome=fault and retried like no-capacity — the
        loop, not the caller, owns injected infra faults."""
        from skypilot_tpu import exceptions
        from skypilot_tpu.utils import failpoints
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_MAX_ROUNDS', '2')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_BASE_SECONDS', '0.01')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_CAP_SECONDS', '0.02')
        strategy, attempts = self._strategy(
            monkeypatch, fail_with=lambda msg: failpoints.FailpointError(
                'jobs.launch'))
        with pytest.raises(exceptions.ManagedJobReachedMaxRetriesError):
            strategy.recover()
        events = self._journal_events('jobs_recovery_attempt')
        assert len(events) == 2
        assert all(e['data']['outcome'] == 'fault' for e in events)

    def test_backoff_gaps_grow_and_are_seed_deterministic(self,
                                                          monkeypatch):
        """The recovery loop's sleeps follow the seeded backoff: two
        identical runs sleep identically; gaps grow exponentially."""
        from skypilot_tpu import exceptions
        from skypilot_tpu.jobs import recovery_strategy
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_MAX_ROUNDS', '4')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_BASE_SECONDS', '1')
        monkeypatch.setenv('SKYTPU_JOBS_RECOVERY_CAP_SECONDS', '64')

        def run_once():
            sleeps = []
            monkeypatch.setattr(recovery_strategy.time, 'sleep',
                                sleeps.append)
            strategy, _ = self._strategy(monkeypatch)
            with pytest.raises(
                    exceptions.ManagedJobReachedMaxRetriesError):
                strategy.recover()
            return sleeps

        first, second = run_once(), run_once()
        assert first == second          # per-job seed ⇒ reproducible
        assert len(first) == 4
        # Exponential shape with half-jitter: attempt n in
        # [0.5, 1.0] * 2^n.
        for n, gap in enumerate(first):
            assert 0.5 * 2 ** n <= gap <= 1.0 * 2 ** n

    def test_jobs_preempt_failpoint_short_circuits_liveness(
            self, monkeypatch):
        """An armed jobs.preempt classes the cluster dead BEFORE any
        cloud/state lookup — the controller's recovery arc starts from
        the injection alone."""
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.utils import failpoints
        ctl = controller_lib.JobsController.__new__(
            controller_lib.JobsController)
        ctl.cluster_name = 'chaos-train'
        monkeypatch.setattr(
            controller_lib.global_state, 'get_cluster',
            lambda name: pytest.fail('liveness hit state DB despite '
                                     'injected preemption'))
        with failpoints.armed('jobs.preempt'):
            assert ctl._cluster_alive() is False

    def test_recovery_metrics_registered(self):
        from skypilot_tpu.observe import metrics
        rendered = metrics.render()
        assert 'skytpu_jobs_recovery_attempts_total' in rendered
        assert 'skytpu_jobs_recovery_seconds' in rendered
