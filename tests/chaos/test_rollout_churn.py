"""Chaos proof for the harvested RL plane (ISSUE 14).

The load-bearing claim: rollout workers are PREEMPTIBLE — SIGKILL any
subset mid-generation and the stable GRPO learner provably (a) never
stalls or corrupts, (b) degrades throughput boundedly, (c) recovers
when capacity rejoins, and (d) remains bit-replayable:

  * workers are REAL subprocesses of ``python -m
    skypilot_tpu.train.rollout worker`` SIGKILLed with no goodbye
    under a seeded, step-keyed schedule;
  * every orphaned lease is reaped and reassigned, with journal
    evidence (``rollout_worker_lost`` + ``rollout_lease_reassign``
    naming the lease ids) matching the kill schedule;
  * the learner completes every step — inter-step gaps stay bounded
    by the heartbeat-timeout + regeneration budget, and the
    steady-state tail rate after rejoin recovers toward the pre-kill
    rate (the checked-in RL_HARVEST_LAST_GOOD.json scorecard records
    the measured ≥0.9 recovery ratio from bench.py rl_harvest; this
    test asserts a contention-tolerant floor);
  * a replay run over the journaled trajectory log reproduces the
    learner's loss trajectory BIT-equal — worker churn shifted WHEN
    trajectories arrived, never WHAT the learner trained on.

This extends the churn methodology of test_train_churn.py (mesh
churn) and test_data_service.py (input-worker churn) to the RL plane.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from skypilot_tpu.observe import journal
from skypilot_tpu.train.rollout import harness
from skypilot_tpu.train.rollout import learner as learner_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import framed

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TOTAL_STEPS = 40
KILL_AT = 8
KILL_COUNT = 2
RESPAWN_AT = 10
HEARTBEAT_TIMEOUT = 2.5
LEARNING_RATE = 1e-3


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_OBSERVE_DB', str(tmp_path / 'observe.db'))
    failpoints.reset()
    yield
    failpoints.reset()


class TestRolloutChurn:

    def test_sigkill_two_workers_mid_run_full_arc(self, tmp_path):
        """THE acceptance pin: 3 workers, SIGKILL 2 after step 8,
        respawn 2 fresh ones after step 10 — reassignment journaled
        per kill, bounded degradation, recovery, bit-equal replay."""
        art = harness.run_harvest(
            str(tmp_path), n_workers=3, total_steps=TOTAL_STEPS,
            kill_at_step=KILL_AT, kill_count=KILL_COUNT,
            respawn_at_step=RESPAWN_AT,
            heartbeat_timeout=HEARTBEAT_TIMEOUT, lease_timeout=15.0,
            learning_rate=LEARNING_RATE, tag='churn')

        # (a) The learner completed EVERY step — losing 2/3 of the
        # fleet mid-run slowed it down, never stopped or crashed it.
        assert art['steps'] == TOTAL_STEPS
        assert len(art['killed']) == KILL_COUNT

        # (b) Journal evidence matches the kill schedule: EVERY killed
        # worker was declared lost and had a reassignment sweep
        # journaled (>= rather than ==: a GIL-stalled jax import can
        # cost a worker one pre-kill heartbeat round on a loaded box —
        # a real reap + rejoin, not noise to hide).
        lost = [e['entity'] for e in
                journal.query(kind='rollout_worker_lost', limit=200)]
        reassigns = [e for e in
                     journal.query(kind='rollout_lease_reassign',
                                   limit=200)
                     if e['entity'] in art['killed']]
        for wid in art['killed']:
            assert lost.count(wid) >= 1, (wid, lost)
            assert any(e['entity'] == wid for e in reassigns), wid
        assert len(reassigns) >= KILL_COUNT, reassigns
        for ev in reassigns:
            assert ev['reason'] == 'heartbeat_timeout'

        # (c) Bounded degradation: no inter-step gap beyond the
        # heartbeat-timeout + regeneration budget (pre-containment, a
        # dead worker's lease would hang the stream until the lease
        # timeout at best, forever at worst). The bound carries slack
        # for full-suite CPU contention — the claim is "bounded and
        # far under the 120 s stall budget", not a latency SLO.
        gaps = [rec['sec_per_step'] for rec in art['history'][1:]]
        stall_bound = HEARTBEAT_TIMEOUT * 2 + 40.0
        assert max(gaps) < stall_bound, (
            f'max inter-step gap {max(gaps):.1f}s exceeds the '
            f'{stall_bound:.1f}s reap+regenerate budget')

        # (d) Degradation and recovery are visible in the rate
        # windows: the kill cut throughput, the rejoin restored it.
        # The checked-in RL_HARVEST_LAST_GOOD.json scorecard pins the
        # quiet-box numbers (recovery to ≥0.9 of pre-kill); under
        # full-suite contention this asserts the ORDERING and a
        # contention-tolerant recovery floor on the BEST trailing
        # window after rejoin.
        assert art['pre_kill_sps'] and art['degraded_sps'] and \
            art['best_post_rejoin_sps']
        assert art['degraded_sps'] < art['pre_kill_sps']
        assert art['best_post_rejoin_sps'] >= \
            0.5 * art['pre_kill_sps'], (
                art['pre_kill_sps'], art['best_post_rejoin_sps'])

        # (e) Staleness stayed inside the off-policy window — nothing
        # was trained on that the learner should have dropped.
        assert art['report']['stale_dropped'] == 0 or \
            art['report']['staleness_p95'] is not None

        # (f) REPLAY: consuming the journaled trajectory stream
        # reproduces the live loss trajectory bit-for-bit.
        replayed = learner_lib.replay_losses(
            art['spec'], art['traj_log_dir'],
            learning_rate=LEARNING_RATE, total_steps=TOTAL_STEPS)
        assert replayed == art['losses']
        assert len(replayed) == TOTAL_STEPS

    def test_cli_dispatcher_readiness_and_stats(self, tmp_path):
        """The `python -m skypilot_tpu.train.rollout dispatcher`
        entry: readiness JSON on stdout (scan past log lines — INFO
        goes to stdout), stats answerable over the wire."""
        env = {**os.environ, 'PYTHONPATH': REPO,
               'SKYTPU_OBSERVE_DB': str(tmp_path / 'cli-observe.db')}
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.train.rollout',
             'dispatcher', '--host', '127.0.0.1', '--port', '0',
             '--db', str(tmp_path / 'cli-disp.db')],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        try:
            ready = None
            for _ in range(10):
                line = proc.stdout.readline().strip()
                if line.startswith('{'):
                    ready = json.loads(line)
                    break
            assert ready is not None, 'no readiness JSON on stdout'
            assert ready['role'] == 'dispatcher'
            addr = framed.parse_addr(ready['addr'])
            reply, _ = framed.request(addr, {'op': 'stats'},
                                      timeout=10.0)
            assert reply['ok'] and reply['snapshot_version'] == -1
            # Leases survive a dispatcher restart (WAL sqlite): mint
            # one, restart on the same --db, it is still there.
            framed.request(addr, {'op': 'register',
                                  'worker_id': 'w1'}, timeout=10.0)
            framed.request(addr, {'op': 'lease', 'worker_id': 'w1',
                                  'max_n': 1}, timeout=10.0)
        finally:
            proc.terminate()
            proc.wait(timeout=10)
        proc2 = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.train.rollout',
             'dispatcher', '--host', '127.0.0.1', '--port', '0',
             '--db', str(tmp_path / 'cli-disp.db')],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        try:
            for _ in range(10):
                line = proc2.stdout.readline().strip()
                if line.startswith('{'):
                    addr = framed.parse_addr(json.loads(line)['addr'])
                    break
            reply, _ = framed.request(addr, {'op': 'stats'},
                                      timeout=10.0)
            assert sum(reply['leases'].values()) == 1
            # The restarted reaper's orphan sweep rescues the lease
            # its dead owner (w1 never heartbeat again) stranded.
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                reply, _ = framed.request(addr, {'op': 'stats'},
                                          timeout=10.0)
                if reply['leases'].get('PENDING'):
                    break
                time.sleep(0.2)
            assert reply['leases'].get('PENDING') == 1
        finally:
            proc2.terminate()
            proc2.wait(timeout=10)
