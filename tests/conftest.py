"""Test config: force a virtual 8-device CPU mesh before jax is imported.

This mirrors the reference's `enable_all_clouds` philosophy
(tests/common_test_fixtures.py:176-236): everything runs hermetically with
zero cloud credentials. Compute-path tests get 8 virtual CPU devices so
multi-chip sharding is exercised without TPU hardware.
"""
import os
import sys

# Force, not setdefault: the ambient environment may pin JAX_PLATFORMS to
# the TPU plugin (e.g. 'axon'), which would give the compute tests one real
# chip instead of the 8 virtual CPU devices the sharding tests require —
# and contend with whatever else holds the chip.
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()
os.environ.setdefault('SKYTPU_USER_HASH', 'testhash')
# Persistent XLA compile cache: the compute tests' wall-clock is dominated
# by CPU-XLA compiles; cache them across runs (VERDICT r1 weak item 3).
os.environ.setdefault(
    'JAX_COMPILATION_CACHE_DIR',
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 '.jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES', '-1')
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '0')

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The env var alone is not enough: site hooks (e.g. the 'axon' TPU plugin)
# can force-register their platform at jax import; the config update is the
# only pin that survives that (same trick as __graft_entry__._force_cpu_platform).
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import pytest  # noqa: E402


def pytest_configure(config):
    """Parallel by default, serial as the fallback.

    The old `addopts = "-n 4 --dist loadscope"` made a missing
    pytest-xdist a hard usage error for the whole suite. Instead, when
    the xdist plugin is registered and no -n/--dist was given, set its
    options here — a rootdir conftest's pytest_configure runs before
    xdist's own (hooks fire in reverse registration order), so the
    plugin activates exactly as if the flags were passed. Without
    xdist (or with `-p no:xdist`) this is a no-op and the suite runs
    serially. loadscope keeps module-scoped jit fixtures shared within
    a worker.
    """
    if not config.pluginmanager.hasplugin('xdist'):
        return
    if os.environ.get('PYTEST_XDIST_WORKER'):
        return      # already inside a worker process
    # xdist's own --pdb incompatibility check ran in
    # pytest_cmdline_main, BEFORE this hook — injecting workers now
    # would silently detach breakpoints from the terminal.
    if config.getoption('usepdb', False):
        return
    # Only when neither -n nor --dist was given (numprocesses None is
    # xdist's parser default; an explicit `-n0` arrives as 0 and must
    # stay serial; an explicit --dist choice must not be clobbered).
    if any(str(a).startswith('--dist') for a in
           config.invocation_params.args):
        return
    if getattr(config.option, 'numprocesses', 'absent') is None:
        config.option.numprocesses = 4
        config.option.dist = 'loadscope'


@pytest.fixture
def enable_local_cloud(monkeypatch):
    """Analog of the reference's enable_all_clouds fixture: only the Local
    (fabricated TPU) cloud is enabled, no credential probing, no disk cache."""
    from skypilot_tpu import check as check_lib
    from skypilot_tpu.clouds import local as local_cloud

    monkeypatch.setattr(
        check_lib, 'get_cached_enabled_clouds_or_refresh',
        lambda raise_if_no_cloud_access=False: [local_cloud.Local()])
    yield


@pytest.fixture
def isolated_state(tmp_path, monkeypatch):
    """Point all on-disk state (~/.skytpu) into a temp dir."""
    home = tmp_path / 'home'
    home.mkdir()
    monkeypatch.setenv('HOME', str(home))
    # Modules capture expanded paths at import; patch the key ones.
    from skypilot_tpu.utils import locks
    monkeypatch.setattr(locks, 'LOCK_DIR', str(home / '.skytpu/locks'))
    from skypilot_tpu.clouds import local as local_cloud
    monkeypatch.setattr(local_cloud, 'LOCAL_CLOUD_ROOT',
                        str(home / '.skytpu/local_cloud'))
    yield home
    # A test that fails mid-scenario leaks its detached controller
    # processes (serve/jobs/pool), which then poll forever and starve the
    # CPU for every later test. Reap anything whose pid this HOME's state
    # recorded.
    _reap_controllers(home)


def _reap_controllers(home) -> None:
    import signal
    import sqlite3
    pids = set()
    for db, query in ((home / '.skytpu/serve.db',
                       'SELECT controller_pid FROM services'),
                      (home / '.skytpu/managed_jobs.db',
                       'SELECT controller_pid FROM jobs')):
        try:
            with sqlite3.connect(db) as conn:
                pids.update(p for (p,) in conn.execute(query) if p)
        except sqlite3.Error:
            continue
    # Gang rank processes (slice_driver) run with cwd inside this HOME's
    # fake cloud root; match them by cwd rather than trusting any table.
    home_str = str(home)
    for entry in os.listdir('/proc'):
        if not entry.isdigit():
            continue
        try:
            cwd = os.readlink(f'/proc/{entry}/cwd')
        except OSError:
            continue
        if cwd.startswith(home_str):
            pids.add(int(entry))
    for pid in pids:
        try:
            os.kill(int(pid), signal.SIGKILL)
        except (OSError, ProcessLookupError, ValueError):
            pass
