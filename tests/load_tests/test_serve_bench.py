"""Engine-path serve benchmark plumbing (VERDICT r3 item 3).

`SKYTPU_BENCH_METRIC=serve python bench.py` must spawn the real HTTP
engine, drive concurrent streaming clients, and emit the one-line JSON
with req/s + TTFT p50/p99 + TPOT p50 — the driver runs this against
BASELINE.md's serve rows on TPU; here the whole pipeline is exercised on
CPU with tiny shapes so a broken bench can never reach the driver.
"""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_serve_bench_emits_metrics_line():
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        SKYTPU_BENCH_CHILD='1',
        SKYTPU_BENCH_METRIC='serve',
        SKYTPU_BENCH_SERVE_REQUESTS='6',
        SKYTPU_BENCH_SERVE_CONCURRENCY='4',
        SKYTPU_BENCH_SERVE_PROMPT='8',
        SKYTPU_BENCH_SERVE_NEW_TOKENS='8',
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, 'bench.py')],
        env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    record = json.loads(line)
    assert record['metric'] == 'serve_req_per_s'
    assert record['value'] > 0
    assert record['ttft_ms_p50'] > 0
    assert record['ttft_ms_p99'] >= record['ttft_ms_p50']
    assert record['tpot_ms_p50'] > 0
    assert record['completed'] >= 4
