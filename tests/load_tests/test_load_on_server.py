"""API-server load tests: throughput, latency tails, queue fairness.

Reference analog: tests/load_tests/test_load_on_server.py (N concurrent
requests, latency percentiles) and test_queue_dispatcher.py (dispatcher
throughput). Those run against a live deployment; here the real aiohttp
app + the real Scheduler run in-process with the thread-mode executor
(SKYTPU_EXECUTOR_MODE=thread), so the load path — HTTP → request record →
queue claim → handler → result poll — is exercised hermetically and fast
enough for CI.

What must hold under load:
  - zero request loss: every submission reaches a terminal record;
  - SHORT requests are never starved behind a LONG backlog (separate
    scheduler lanes, executor.py);
  - the dispatcher sustains a sane claim rate (its 0.2s idle backoff must
    not throttle a busy queue).
"""
import asyncio
import os
import statistics
import time

import pytest
from aiohttp.test_utils import TestClient
from aiohttp.test_utils import TestServer as AioTestServer

from skypilot_tpu.server import executor
from skypilot_tpu.server import registry
from skypilot_tpu.server import requests_lib
from skypilot_tpu.server import server as server_lib


@pytest.fixture
def load_env(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVER_DIR', str(tmp_path / 'srv'))
    monkeypatch.delenv('SKYTPU_API_TOKEN', raising=False)
    monkeypatch.setenv(executor.EXECUTOR_MODE_ENV, 'thread')
    sched = executor.Scheduler()
    sched.start()
    yield
    sched.stop()


@pytest.fixture
def injected_handlers(monkeypatch):
    """Test-only request types with controlled service times."""
    def _sleep(payload):
        time.sleep(float(payload.get('t', 0)))
        return {'slept': payload.get('t', 0)}
    monkeypatch.setitem(registry.HANDLERS, 'load_noop',
                        (lambda p: {'ok': True}, requests_lib.SHORT))
    monkeypatch.setitem(registry.HANDLERS, 'load_slow',
                        (_sleep, requests_lib.LONG))
    monkeypatch.setitem(registry.HANDLERS, 'load_quick',
                        (_sleep, requests_lib.SHORT))


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def _submit_and_wait(client, name, payload, timeout=60.0):
    """POST a request, poll to terminal; returns (record, latency_s)."""
    begin = time.monotonic()
    r = await client.post(f'/api/v1/{name}', json=payload)
    assert r.status == 200, await r.text()
    rid = (await r.json())['request_id']
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = await client.get('/api/v1/get', params={'request_id': rid})
        assert r.status == 200
        rec = await r.json()
        if requests_lib.RequestStatus(rec['status']).is_terminal():
            return rec, time.monotonic() - begin
        await asyncio.sleep(0.05)
    raise TimeoutError(f'request {rid} ({name}) not terminal')


@pytest.mark.usefixtures('load_env', 'injected_handlers')
class TestServerLoad:

    def test_no_loss_under_concurrent_shorts(self):
        """60 concurrent SHORT requests: all succeed, tails bounded."""
        n = 60

        async def fn(client):
            results = await asyncio.gather(*[
                _submit_and_wait(client, 'load_noop', {'i': i})
                for i in range(n)])
            return results

        async def run():
            app = server_lib.build_app()
            client = TestClient(AioTestServer(app))
            await client.start_server()
            try:
                return await fn(client)
            finally:
                await client.close()

        results = _run(run())
        assert len(results) == n
        statuses = [r['status'] for r, _ in results]
        assert statuses == ['SUCCEEDED'] * n
        lats = sorted(lat for _, lat in results)
        p50 = lats[n // 2]
        p95 = lats[int(n * 0.95)]
        print(f'\nshort x{n}: p50={p50:.2f}s p95={p95:.2f}s '
              f'max={lats[-1]:.2f}s')
        # Thread-mode handlers are instant; the latency is pure queueing.
        # Generous bounds: this must pass on a loaded 1-core CI box.
        assert p95 < 30.0

    def test_shorts_not_starved_by_long_backlog(self):
        """A LONG backlog (service time >> lane width) must not delay
        SHORT requests — they ride a separate scheduler lane."""
        n_long, long_t, n_short = 8, 2.0, 12

        async def run():
            app = server_lib.build_app()
            client = TestClient(AioTestServer(app))
            await client.start_server()
            try:
                long_tasks = [
                    asyncio.create_task(_submit_and_wait(
                        client, 'load_slow', {'t': long_t}, timeout=120))
                    for _ in range(n_long)]
                await asyncio.sleep(0.3)   # backlog forms
                t0 = time.monotonic()
                shorts = await asyncio.gather(*[
                    _submit_and_wait(client, 'load_noop', {})
                    for _ in range(n_short)])
                short_wall = time.monotonic() - t0
                longs = await asyncio.gather(*long_tasks)
                return shorts, longs, short_wall

            finally:
                await client.close()

        shorts, longs, short_wall = _run(run())
        assert [r['status'] for r, _ in shorts] == ['SUCCEEDED'] * n_short
        assert [r['status'] for r, _ in longs] == ['SUCCEEDED'] * n_long
        # The LONG lane needs >= ceil(8/LONG_PARALLELISM)*2s of wall; the
        # shorts must clear far faster than that backlog.
        long_backlog = (n_long / executor.LONG_PARALLELISM) * long_t
        print(f'\nshorts cleared in {short_wall:.2f}s vs LONG backlog '
              f'{long_backlog:.1f}s')
        assert short_wall < long_backlog

    def test_dispatcher_claim_throughput(self):
        """Queue drain rate: the dispatcher's idle backoff must not
        throttle a busy queue (claims should be back-to-back)."""
        n = 80
        t0 = time.monotonic()
        ids = [requests_lib.create('load_noop', {}, requests_lib.SHORT)
               for _ in range(n)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            recs = [requests_lib.get(rid) for rid in ids]
            if all(requests_lib.RequestStatus(r['status']).is_terminal()
                   for r in recs):
                break
            time.sleep(0.05)
        else:
            raise TimeoutError('queue did not drain')
        wall = time.monotonic() - t0
        rate = n / wall
        assert all(requests_lib.get(rid)['status'] == 'SUCCEEDED'
                   for rid in ids)
        print(f'\ndispatcher: {n} requests in {wall:.2f}s = {rate:.0f}/s')
        # The idle-backoff pacing bug capped a busy queue at exactly 5
        # claims/s; back-to-back claiming lands at >100/s on an idle box.
        # The bound sits above the pacing ceiling but tolerates a CI box
        # saturated by parallel test workers.
        assert rate > 6.5

    def test_cancel_never_targets_the_server_process(self):
        """Thread-mode requests record pid 0: cancelling a RUNNING one
        must refuse (no killable process) rather than SIGTERM the pid in
        the record — which would be the API server itself."""
        from skypilot_tpu.server import executor as executor_lib
        rid = requests_lib.create('load_slow', {'t': 3.0},
                                  requests_lib.LONG)
        deadline = time.monotonic() + 30
        while requests_lib.get(rid)['status'] != 'RUNNING':
            assert time.monotonic() < deadline
            time.sleep(0.05)
        rec = requests_lib.get(rid)
        assert not rec['pid'], rec   # never the server's own pid
        assert executor_lib.cancel_request(rid) is False
        # The request (and this process) survive; it completes normally.
        deadline = time.monotonic() + 30
        while not requests_lib.RequestStatus(
                requests_lib.get(rid)['status']).is_terminal():
            assert time.monotonic() < deadline
            time.sleep(0.1)
        assert requests_lib.get(rid)['status'] == 'SUCCEEDED'
        # A still-queued request cancels fine in thread mode: saturate the
        # LONG lane so at least one stays NEW.
        ids = [requests_lib.create('load_slow', {'t': 5.0},
                                   requests_lib.LONG)
               for _ in range(executor_lib.LONG_PARALLELISM + 1)]
        time.sleep(0.1)
        new_ones = [r for r in ids
                    if requests_lib.get(r)['status'] == 'NEW']
        assert new_ones, [requests_lib.get(r)['status'] for r in ids]
        assert executor_lib.cancel_request(new_ones[0]) is True
        assert requests_lib.get(new_ones[0])['status'] == 'CANCELLED'

    def test_sustained_load_memory_and_record_growth(self):
        """sys_profiling analog (reference tests/load_tests/
        sys_profiling.py monitors API-server memory): three waves of
        requests must not leak — request records are GC-able and the
        process RSS stays bounded (no per-request state retained)."""
        import resource

        def rss_mb():
            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0

        def drain(n):
            ids = [requests_lib.create('load_noop', {},
                                       requests_lib.SHORT)
                   for _ in range(n)]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if all(requests_lib.RequestStatus(
                        requests_lib.get(r)['status']).is_terminal()
                       for r in ids):
                    return
                time.sleep(0.05)
            raise TimeoutError('wave did not drain')

        drain(50)
        base = rss_mb()
        for _ in range(2):
            drain(50)
        growth = rss_mb() - base
        print(f'\nsustained load: peak-RSS growth {growth:.1f} MiB '
              f'over 100 extra requests')
        # Thread-mode handlers hold no per-request state; a leak of even
        # 100 KiB/request would show as >10 MiB here.
        assert growth < 10.0

        # All 150 terminal records are prunable by the GC.
        pruned = requests_lib.gc_requests(max_age_seconds=0.0)
        assert pruned >= 150
        assert len(requests_lib.list_requests(limit=1000)) == 0
