"""Replica lifecycle: launch as clusters, probe readiness, recover failures.

Reference analog: sky/serve/replica_managers.py (`ReplicaManager:626`,
`SkyPilotReplicaManager:680`). Each replica is an ordinary cluster named
`<service>-replica-<id>` launched through execution.launch, so it inherits
provisioning failover; the serve-specific logic here is readiness probing,
failure/preemption classing, and replace-don't-restart recovery.

Replica addressing: the replica task gets `SKYTPU_SERVE_PORT` injected. On
real clouds every replica has its own head IP and the service port is
uniform; on the local fake cloud all replicas share 127.0.0.1, so each gets
base_port + replica_id (that offset is what makes hermetic multi-replica
tests possible on one machine).
"""
from __future__ import annotations

import json
import os
import threading
import typing
from typing import Dict, List, Optional
from urllib import error as urlerror
from urllib import request as urlrequest

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve import spot_placer as spot_placer_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import vclock
from skypilot_tpu.serve.serve_state import ReplicaStatus

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

# A replica whose probe fails this many consecutive times is replaced.
MAX_CONSECUTIVE_PROBE_FAILURES = 3
# Consecutive probe-failure replacements (no READY in between) before the
# service is declared FAILED instead of churning clusters forever.
MAX_REPLACEMENTS_BEFORE_FAILED = 3

# Per-pass probe outcome classing — the reconcile loop's eyes. A rising
# `replaced_*` rate with flat `ready` is the preemption-churn /
# broken-app signature the serve FAILED cap acts on.
_PROBE_OUTCOMES = ('ready', 'miss', 'slow_boot', 'app_exited',
                   'replaced_failed', 'replaced_preempted',
                   'launch_failed')
_PROBE_METRIC = metrics_lib.counter(
    'skytpu_serve_probe_total',
    'Replica probe / liveness classing outcomes per reconcile pass.',
    labels={'outcome': _PROBE_OUTCOMES})

# Graceful drain (docs/ROBUSTNESS.md): a retiring replica stops taking
# traffic (DRAINING — excluded from ready_urls), finishes its in-flight
# requests, then tears down. Observed once per drain, at teardown.
_DRAIN_SECONDS = metrics_lib.histogram(
    'skytpu_serve_drain_seconds',
    'Wall-clock from drain start to teardown eligibility (in-flight '
    'drained, deadline hit, or cluster lost).')

# Default in-flight-completion deadline for a draining replica.
DRAIN_DEADLINE_SECONDS = 120.0


def _drain_deadline_seconds() -> float:
    """Env-tunable (read at call time — the controller is a detached
    process, and tests tighten this to keep drain scenarios fast)."""
    return knobs.get_float('SKYTPU_SERVE_DRAIN_SECONDS')


def _replacement_cap(target: int) -> int:
    """Churn cap before permanent failure. Env-tunable (read at call
    time, not import: the controller is a detached process and tests
    tighten this so FAILED classification needs fewer full
    launch→crash→replace cycles of wall-clock on a saturated box)."""
    env = knobs.get_int('SKYTPU_SERVE_MAX_REPLACEMENTS')
    base = (MAX_REPLACEMENTS_BEFORE_FAILED if env is None
            else max(1, env))
    return max(base, 2 * target)


def _boot_patience_seconds(probe: 'spec_lib.ReadinessProbe') -> float:
    """Extra wall-clock a STARTING replica whose run job is verifiably
    alive gets beyond initial_delay_seconds before probe misses count
    toward replacement.

    Probe classing (slow boot vs dead app): on a saturated box a replica
    can blow through a short grace window while its process is alive and
    still booting; replacing it then just restarts the same slow boot and
    eventually FAILs a healthy service. The patience is bounded so an
    alive-but-never-listening (hung) app is still replaced."""
    env = knobs.get_float('SKYTPU_SERVE_BOOT_PATIENCE')
    if env is not None:
        return env
    return max(60.0, 5.0 * probe.initial_delay_seconds)


def probe_url(url: str, path: str, timeout: float) -> bool:
    try:
        if failpoints.ACTIVE:
            # A firing is classed as a probe miss (the except below):
            # deterministic probe-failure injection for the
            # replacement / NOT_READY paths without killing a replica.
            failpoints.fire('serve.probe')
        with urlrequest.urlopen(url.rstrip('/') + path,
                                timeout=timeout) as resp:
            return 200 <= resp.status < 400
    except (urlerror.URLError, OSError, ValueError,
            failpoints.FailpointError):
        return False


class ReplicaManager:
    """Drives the replica set of one service toward a target count."""

    def __init__(self, service_name: str, task: 'task_lib.Task',
                 spec: spec_lib.ServiceSpec, version: int = 1,
                 update_mode: str = 'rolling',
                 role: Optional[str] = None):
        self.service_name = service_name
        self.task = task
        self.spec = spec
        self.version = version
        self.update_mode = update_mode
        # Disaggregated pool role ('prefill'/'decode'; None =
        # monolithic). The role namespaces CLUSTER NAMES — the durable
        # record that survives controller restarts — so two managers
        # of one service partition the shared replica table by
        # cluster-name prefix, share the service's monotonic replica-id
        # sequence (ids never collide across pools), and inject
        # SKYTPU_ENGINE_ROLE into their replicas.
        self.role = role
        self.backend = slice_backend.TpuSliceBackend()
        self._launch_threads: Dict[int, threading.Thread] = {}
        # One decision for env injection AND probe URLs (they must agree).
        self._local_ports = self._is_local()
        # Consecutive probe-failure replacements with no READY in between:
        # when this passes the cap, the app is broken, not unlucky.
        self._probe_failure_streak = 0
        self.permanently_failed: Optional[str] = None
        # Spot placement: which zone each live replica was placed in, so
        # preemptions can be charged to the right location and new replicas
        # spread away from in-use zones (serve/spot_placer.py).
        self.spot_placer = spot_placer_lib.SpotPlacer.from_task(spec, task)
        self._replica_locations: Dict[int, spot_placer_lib.Location] = {}
        # Which versions the LB may route to (reference:
        # serve_utils.py:566 active_versions): rolling serves mixed
        # versions; blue_green pins traffic to the old set until the new
        # one can carry the full target.
        self.active_versions = {version}
        # replica_id -> drain start time. In-memory: a controller
        # restart restarts the deadline clock (reconcile re-stamps a
        # DRAINING row it has no record of), never un-drains.
        self._drain_started: Dict[int, float] = {}
        # (task, spec, version) before the in-flight update, kept so a
        # rollout whose new version can never pass probes can roll BACK
        # instead of failing the still-serving service.
        self._prev_version_state = None

    def reload(self, task: 'task_lib.Task', spec: spec_lib.ServiceSpec,
               version: int, update_mode: str) -> None:
        """Adopt a new service version (serve update). Running replicas
        keep their launch-time config; reconcile migrates them."""
        self._prev_version_state = (self.task, self.spec, self.version)
        self.task = task
        self.spec = spec
        self.version = version
        self.update_mode = update_mode
        self.spot_placer = spot_placer_lib.SpotPlacer.from_task(spec, task)
        self._probe_failure_streak = 0
        logger.info(f'Service {self.service_name!r} now targets version '
                    f'{version} ({update_mode}).')

    def _rollback_update(self) -> None:
        """Abort an update whose new version cannot come up: restore the
        previous task/spec/version (in memory AND in the service record,
        so a controller restart stays rolled back) and shed any
        new-version replicas. Old replicas never stopped serving."""
        import json as json_lib
        task, spec, version = self._prev_version_state
        failed_version = self.version
        self.task, self.spec, self.version = task, spec, version
        self._prev_version_state = None
        self._probe_failure_streak = 0
        self.active_versions = {version}
        serve_state.update_service(
            self.service_name,
            task_config=json_lib.dumps(task.to_yaml_config()),
            spec=json_lib.dumps(spec.to_yaml_config()),
            version=version)
        for rep in self._my_replicas():
            if (rep.get('version') or 1) >= failed_version:
                self.terminate_replica(rep['replica_id'])
        logger.warning(
            f'Update of {self.service_name!r} to version {failed_version} '
            f'ROLLED BACK: new-version replicas kept failing launch or '
            f'readiness; still serving version {version}.')

    # ------------------------------------------------------------------
    # Launch / terminate
    # ------------------------------------------------------------------
    def _cluster_prefix(self) -> str:
        if self.role:
            return f'{self.service_name}-{self.role}-replica-'
        return f'{self.service_name}-replica-'

    def _cluster_name(self, replica_id: int) -> str:
        return f'{self._cluster_prefix()}{replica_id}'

    def _my_replicas(self) -> List[dict]:
        """This manager's slice of the service's replica table. Pool
        managers (role set) partition by cluster-name prefix —
        ``<svc>-<role>-replica-`` — so two managers of one disagg
        service split the shared table recoverably from the durable
        rows alone after a controller restart. A monolithic manager
        owns the WHOLE table unfiltered (a disagg service never
        instantiates one — the manager set is fixed at startup), so
        rows with legacy or custom cluster names stay managed."""
        if not self.role:
            return serve_state.get_replicas(self.service_name)
        prefix = self._cluster_prefix()
        return [r for r in serve_state.get_replicas(self.service_name)
                if str(r.get('cluster_name') or '').startswith(prefix)]

    def _replica_task(self, replica_id: int) -> 'task_lib.Task':
        from skypilot_tpu import task as task_lib_mod
        cfg = self.task.to_yaml_config()
        cfg.pop('service', None)
        if self.spec.pool:
            # A pool worker is provision+setup only: it idles until a
            # managed job execs onto it (jobs/recovery_strategy.py pool
            # path). A run command here would race the jobs.
            cfg.pop('run', None)
        task = task_lib_mod.Task.from_yaml_config(cfg)
        if not self.spec.pool:
            port = self.spec.port
            envs = {
                'SKYTPU_SERVE_PORT': str(port + replica_id
                                         if self._local_ports else port),
                'SKYTPU_SERVE_REPLICA_ID': str(replica_id),
                'SKYTPU_SERVE_VERSION': str(self.version),
            }
            if self.role:
                # Disagg pool role: the engine reports it on /health
                # and the ops surface; the LB's pool routing derives
                # from the CONTROLLER's manager split, not from this.
                envs['SKYTPU_ENGINE_ROLE'] = self.role
            task.update_envs(envs)
        # Placement was decided in scale_up (single-threaded) — concurrent
        # launch threads reading the placer here would all see the same
        # in-use set and pile into one zone.
        loc = self._replica_locations.get(replica_id)
        if loc is not None:
            task.set_resources_override(loc.to_override())
            logger.info(f'Replica {replica_id} placed at {loc}.')
        return task

    def _is_local(self) -> bool:
        """Will replicas land on the local fake cloud (shared 127.0.0.1)?

        Must be decided BEFORE launch (the port env ships with the task),
        so when the task doesn't pin a cloud, infer from the enabled set:
        only-local-enabled (the hermetic test environment) → local ports.
        A mixed environment where the optimizer still picks local accepts a
        port collision across co-hosted replicas — a documented limit of
        the fake cloud, not of real deployments."""
        from skypilot_tpu import resources as resources_lib
        for res in self.task.resources_list():
            assert isinstance(res, resources_lib.Resources)
            if res.cloud is not None:
                return str(res.cloud).lower() == 'local'
        from skypilot_tpu import check as check_lib
        enabled = check_lib.get_cached_enabled_clouds_or_refresh()
        return len(enabled) == 1 and str(enabled[0]).lower() == 'local'

    def _replica_url(self, replica_id: int,
                     handle: slice_backend.SliceResourceHandle) -> str:
        if self.spec.pool:
            return ''   # workers serve no HTTP endpoint
        info = handle.get_cluster_info()
        head = info.ordered_instances()[0]
        port = self.spec.port
        # Must mirror the SKYTPU_SERVE_PORT decision in _replica_task —
        # the probe has to knock where the app was told to listen.
        if self._local_ports:
            return f'http://127.0.0.1:{port + replica_id}'
        ip = head.external_ip or head.internal_ip
        return f'http://{ip}:{port}'

    def scale_up(self, n: int = 1) -> List[int]:
        """Launch n replicas asynchronously; returns their ids."""
        ids = []
        for _ in range(n):
            rid = serve_state.next_replica_id(self.service_name)
            serve_state.add_replica(
                self.service_name, rid,
                cluster_name=self._cluster_name(rid),
                version=self.version)
            if self.spot_placer is not None:
                loc = self.spot_placer.select_next_location(
                    list(self._replica_locations.values()))
                if loc is not None:
                    self._replica_locations[rid] = loc
            t = threading.Thread(target=self._launch_one, args=(rid,),
                                 daemon=True)
            self._launch_threads[rid] = t
            t.start()
            ids.append(rid)
        return ids

    def _launch_one(self, replica_id: int) -> None:
        from skypilot_tpu import execution
        from skypilot_tpu.observe import spans
        name = self._cluster_name(replica_id)
        try:
            task = self._replica_task(replica_id)
            # Launch threads start with an empty contextvar context, so
            # the span parents via the env carrier (the controller
            # process adopted the `serve up` request's trace/parent) —
            # the replica's provision.attempt child spans then join the
            # same tree. Entity-stamped so /-/lb/trace can expose it.
            with spans.span('serve.replica_launch',
                            entity=f'{self.service_name}/{replica_id}',
                            attrs={'replica': replica_id,
                                   'cluster': name}):
                _, handle = execution.launch(task, cluster_name=name,
                                             detach_run=True)
            assert handle is not None
            # Guarded transition FIRST: if the replica was terminated
            # while we were launching (scale-down, shutdown), the
            # PROVISIONING row is gone or SHUTTING_DOWN and the setter
            # refuses — a stale launch thread must not resurrect it.
            if not serve_state.set_replica_status(
                    self.service_name, replica_id,
                    ReplicaStatus.STARTING):
                logger.info(f'Replica {replica_id} of '
                            f'{self.service_name} disappeared during '
                            f'launch; tearing down {name}.')
                self._teardown_orphan(name)
                return
            serve_state.upsert_replica(
                self.service_name, replica_id, cluster_name=name,
                url=self._replica_url(replica_id, handle))
            logger.info(f'Replica {replica_id} of {self.service_name} '
                        f'provisioned at {name}.')
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Replica {replica_id} launch failed: {e}')
            if not serve_state.set_replica_status(
                    self.service_name, replica_id, ReplicaStatus.FAILED):
                # Row removed mid-launch (scale-down raced us) — but
                # the launch may have registered the cluster before
                # failing a later stage. Nobody else will ever see
                # this replica: tear the cluster down here or it
                # bills forever.
                self._teardown_orphan(name)

    def _teardown_orphan(self, cluster_name: str) -> None:
        """Tear down a cluster whose replica row no longer exists."""
        try:
            record = global_state.get_cluster(cluster_name)
            if record is not None:
                handle = slice_backend.SliceResourceHandle.from_dict(
                    record['handle'])
                self.backend.teardown(handle, terminate=True)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Orphan teardown of {cluster_name} '
                           f'failed: {e}')

    def terminate_replica(self, replica_id: int,
                          status: ReplicaStatus = ReplicaStatus.SHUTTING_DOWN
                          ) -> None:
        serve_state.set_replica_status(self.service_name, replica_id, status)
        name = self._cluster_name(replica_id)
        try:
            record = global_state.get_cluster(name)
            if record is not None:
                handle = slice_backend.SliceResourceHandle.from_dict(
                    record['handle'])
                self.backend.teardown(handle, terminate=True)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Teardown of replica {replica_id} failed: {e}')
        serve_state.remove_replica(self.service_name, replica_id)
        self._replica_locations.pop(replica_id, None)

    def terminate_all(self) -> None:
        for rep in self._my_replicas():
            self.terminate_replica(rep['replica_id'])

    # ------------------------------------------------------------------
    # Graceful drain
    # ------------------------------------------------------------------
    def drain_replica(self, replica_id: int) -> bool:
        """Begin graceful retirement: the guarded DRAINING transition
        pulls the replica out of ready_urls() (the LB stops routing at
        the next reconcile sync), then reconcile tears it down once its
        in-flight requests finish — or the deadline hits. Falls back to
        immediate termination when the transition is refused (the
        replica is not READY/NOT_READY, so there is no accepted traffic
        to protect). Returns True when a drain actually started."""
        if not serve_state.set_replica_status(
                self.service_name, replica_id, ReplicaStatus.DRAINING):
            self.terminate_replica(replica_id)
            return False
        self._drain_started[replica_id] = vclock.now()
        journal_lib.record_event(
            'drain_start', machine='replica',
            entity=f'{self.service_name}/{replica_id}')
        logger.info(f'Replica {replica_id} of {self.service_name} '
                    f'DRAINING (deadline '
                    f'{_drain_deadline_seconds():.0f}s).')
        return True

    def _replica_idle(self, rep: dict) -> Optional[bool]:
        """Does the draining replica report zero in-flight work? The
        engine's /health carries queue_depth + in_flight. None =
        couldn't tell (unreachable / non-engine app) — the deadline
        then decides."""
        url = rep.get('url')
        if not url:
            return True
        probe = self.spec.readiness_probe
        try:
            with urlrequest.urlopen(url.rstrip('/') + '/health',
                                    timeout=probe.timeout_seconds) as r:
                doc = json.loads(r.read().decode())
        except (urlerror.URLError, OSError, ValueError):
            return None
        if not isinstance(doc, dict) or 'in_flight' not in doc:
            # App without drain telemetry: nothing to wait on beyond
            # the reconcile pass that already pulled it from the LB —
            # holding it for the full deadline buys nothing.
            return True
        try:
            return (int(doc.get('in_flight', 0)) == 0 and
                    int(doc.get('queue_depth', 0)) == 0)
        except (TypeError, ValueError):
            return None

    def _reconcile_draining(self, rep: dict, now: float) -> None:
        """One reconcile pass over a DRAINING replica: tear it down
        when its in-flight work is done, the drain deadline passes, or
        the cluster is gone (preempted mid-drain) — otherwise leave it
        finishing. Draining replicas never count toward the target, so
        replacements scale up while they finish."""
        rid = rep['replica_id']
        started = self._drain_started.setdefault(rid, now)
        deadline = _drain_deadline_seconds()
        idle = self._replica_idle(rep)
        if idle is True:
            reason = 'complete'
        elif now - started >= deadline:
            reason = 'deadline'
        elif self._cluster_gone(rid):
            reason = 'lost'
        else:
            return
        elapsed = max(0.0, now - started)
        _DRAIN_SECONDS.observe(elapsed)
        journal_lib.record_event(
            'drain_finish', machine='replica',
            entity=f'{self.service_name}/{rid}', reason=reason,
            data={'seconds': round(elapsed, 3)})
        logger.info(f'Replica {rid} drain finished ({reason}, '
                    f'{elapsed:.1f}s) — tearing down.')
        self._drain_started.pop(rid, None)
        self.terminate_replica(rid)

    def _retire_replica(self, rep: dict) -> None:
        """Retirement entry point for scale-down and updates: replicas
        that may hold accepted traffic DRAIN (kill-mid-stream loses
        requests) — that includes NOT_READY, whose probe blip does not
        evict in-flight generations and whose DRAINING edge the state
        machine declares; everything else (pool workers — no HTTP
        drain signal — and pre-serving replicas) tears down
        immediately."""
        if not self.spec.pool and rep['status'] in (
                ReplicaStatus.READY, ReplicaStatus.NOT_READY):
            self.drain_replica(rep['replica_id'])
        else:
            self.terminate_replica(rep['replica_id'])

    # ------------------------------------------------------------------
    # Probe / reconcile
    # ------------------------------------------------------------------
    def _cluster_gone(self, replica_id: int) -> bool:
        name = self._cluster_name(replica_id)
        record = global_state.get_cluster(name)
        if record is None:
            return True
        handle = slice_backend.SliceResourceHandle.from_dict(
            record['handle'])
        try:
            statuses = provision.query_instances(handle.cloud, handle.region,
                                                 name,
                                                 handle.provider_config)
        except exceptions.ClusterDoesNotExist:
            return True
        except Exception as e:  # pylint: disable=broad-except
            # Transient API error ≠ preemption.
            logger.debug(f'Replica {replica_id} liveness probe failed '
                         f'(assuming alive): {e}')
            return False
        return not statuses or not all(
            s in ('running', 'READY') for s in statuses.values())

    def _replica_app_alive(self, replica_id: int) -> Optional[bool]:
        """Probe classing input: True = the run job is verifiably alive
        (queued/setting up/running); False = it verifiably EXITED; None =
        couldn't determine (transient query error — must neither extend
        boot patience nor trigger immediate replacement)."""
        record = global_state.get_cluster(self._cluster_name(replica_id))
        if record is None:
            # Cluster gone mid-pass (concurrent teardown/preemption):
            # unknown, NOT "app exited" — _cluster_gone owns that classing.
            return None
        try:
            handle = slice_backend.SliceResourceHandle.from_dict(
                record['handle'])
            jobs = self.backend.queue(handle)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'Replica {replica_id} app-liveness query '
                         f'failed (treating as unknown): {e}')
            return None
        if not jobs:
            return None    # job not registered yet (setup still running)
        last = slice_backend.JobStatus(
            max(jobs, key=lambda j: j['job_id'])['status'])
        if not last.is_terminal():
            return True
        # SUCCEEDED is NOT "dead": a run command may daemonize its server
        # and exit 0 — that replica deserves the normal probe-miss budget.
        # Only a crashed/cancelled run can never become ready.
        return None if last is slice_backend.JobStatus.SUCCEEDED else False

    def reconcile(self, target: int) -> None:
        """One control-loop pass: probe replicas, replace the dead, scale
        toward `target`."""
        replicas = self._my_replicas()
        now = vclock.now()
        alive: List[dict] = []
        for rep in replicas:
            rid, status = rep['replica_id'], rep['status']
            if status in (ReplicaStatus.PROVISIONING,
                          ReplicaStatus.SHUTTING_DOWN):
                alive.append(rep)   # in flight; count toward target
                continue
            if status is ReplicaStatus.FAILED:
                # Launch thread already marked it (often with NO
                # cluster record, so this must run BEFORE the
                # cluster-gone probe — a launch failure is not a
                # preemption: it bumps the permanent-failure streak
                # and must not penalize the zone in the spot placer).
                _PROBE_METRIC.inc(outcome='launch_failed')
                self.terminate_replica(rid, ReplicaStatus.FAILED)
                self._probe_failure_streak += 1
                continue
            if status is ReplicaStatus.DRAINING:
                # Not counted toward target: a drain IS the retirement
                # decision, and its replacement (if any) must be free
                # to scale up while in-flight requests finish.
                self._reconcile_draining(rep, now)
                continue
            if self._cluster_gone(rid):
                logger.info(f'Replica {rid} lost (preemption/teardown) — '
                            f'replacing.')
                _PROBE_METRIC.inc(outcome='replaced_preempted')
                if self.spot_placer is not None and \
                        rid in self._replica_locations:
                    self.spot_placer.set_preemptive(
                        self._replica_locations[rid])
                self.terminate_replica(rid, ReplicaStatus.PREEMPTED)
                continue
            if status in (ReplicaStatus.STARTING, ReplicaStatus.READY,
                          ReplicaStatus.NOT_READY):
                if self.spec.pool:
                    # Pool worker readiness IS cluster liveness (checked by
                    # _cluster_gone above) + setup completion (STARTING is
                    # only set once execution.launch returned).
                    if status is not ReplicaStatus.READY:
                        serve_state.set_replica_status(
                            self.service_name, rid, ReplicaStatus.READY)
                        logger.info(f'Worker {rid} is READY.')
                        if self.spot_placer is not None and \
                                rid in self._replica_locations:
                            self.spot_placer.set_active(
                                self._replica_locations[rid])
                    self._probe_failure_streak = 0
                    alive.append(rep)
                    continue
                probe = self.spec.readiness_probe
                in_grace = (status is ReplicaStatus.STARTING and
                            now - (rep['launched_at'] or 0) <
                            probe.initial_delay_seconds)
                if probe_url(rep['url'], probe.path, probe.timeout_seconds):
                    _PROBE_METRIC.inc(outcome='ready')
                    serve_state.reset_replica_failures(self.service_name,
                                                       rid)
                    # Only a CURRENT-version success clears the churn
                    # streak: during an update the healthy old replicas
                    # pass probes every pass, and resetting on those
                    # would make the cap unreachable while a broken new
                    # version churns surge replicas forever.
                    if (rep.get('version') or 1) >= self.version:
                        self._probe_failure_streak = 0
                    if status is not ReplicaStatus.READY:
                        serve_state.set_replica_status(
                            self.service_name, rid, ReplicaStatus.READY)
                        logger.info(f'Replica {rid} is READY.')
                        if self.spot_placer is not None and \
                                rid in self._replica_locations:
                            self.spot_placer.set_active(
                                self._replica_locations[rid])
                elif not in_grace:
                    boot_age = now - (rep['launched_at'] or 0)
                    app_alive = (self._replica_app_alive(rid)
                                 if status is ReplicaStatus.STARTING
                                 else None)
                    if (app_alive is True and
                            boot_age < probe.initial_delay_seconds +
                            _boot_patience_seconds(probe)):
                        # Probe classing: never-READY replica whose run job
                        # is alive — slow boot, not a dead app. Don't count
                        # the miss; the patience bound above keeps a hung
                        # app from stalling the service forever.
                        logger.info(f'Replica {rid} not ready after '
                                    f'{boot_age:.0f}s but its job is alive '
                                    f'— treating as slow boot.')
                        _PROBE_METRIC.inc(outcome='slow_boot')
                        alive.append(rep)
                        continue
                    if app_alive is False:
                        # The run job EXITED without the replica ever
                        # becoming ready — no future probe can succeed.
                        # Replace now instead of waiting out the full
                        # probe-miss budget (keeps broken-app → FAILED
                        # fast even though classing queries add latency).
                        logger.info(f'Replica {rid} run job exited before '
                                    f'readiness — replacing.')
                        _PROBE_METRIC.inc(outcome='app_exited')
                        self.terminate_replica(rid, ReplicaStatus.FAILED)
                        self._probe_failure_streak += 1
                        continue
                    fails = serve_state.bump_replica_failures(
                        self.service_name, rid)
                    if fails >= MAX_CONSECUTIVE_PROBE_FAILURES:
                        logger.info(f'Replica {rid} failed {fails} probes — '
                                    f'replacing.')
                        # replaced_failed subsumes the miss: exactly one
                        # outcome per classing, so outcomes sum to
                        # probes performed.
                        _PROBE_METRIC.inc(outcome='replaced_failed')
                        self.terminate_replica(rid, ReplicaStatus.FAILED)
                        self._probe_failure_streak += 1
                        continue
                    _PROBE_METRIC.inc(outcome='miss')
                    if status is ReplicaStatus.READY:
                        serve_state.set_replica_status(
                            self.service_name, rid, ReplicaStatus.NOT_READY)
                alive.append(rep)
        # A broken app fails probes on every fresh replica: without a cap
        # the loop launches and tears down (billing!) slices forever. The
        # streak resets on any successful probe, so preemption-replacement
        # churn doesn't trip it.
        cap = _replacement_cap(target)
        stale = [r for r in alive if (r.get('version') or 1) < self.version]
        if self._probe_failure_streak >= cap:
            if stale and self._prev_version_state is not None:
                # Mid-update churn: the NEW version can't come up while
                # old replicas are healthy. Roll the update back instead
                # of failing the whole (still-serving) service.
                self._rollback_update()
                return
            self.permanently_failed = (
                f'{self._probe_failure_streak} consecutive replicas failed '
                f'to launch or pass readiness probes; check the resources, '
                f'run command and readiness_probe.')
            return
        if stale:
            self._reconcile_update(alive, stale, target)
            return
        self.active_versions = {self.version}
        # Scale toward target.
        if len(alive) < target:
            self.scale_up(target - len(alive))
        elif len(alive) > target:
            # Prefer shedding not-ready replicas, then (pools) idle workers
            # before ones running a managed job, newest first.
            order = sorted(
                alive,
                key=lambda r: (r['status'] is ReplicaStatus.READY,
                               r.get('job_id') is not None,
                               -r['replica_id']))
            for rep in order[:len(alive) - target]:
                logger.info(f'Scaling down replica {rep["replica_id"]}.')
                self._retire_replica(rep)

    def _reconcile_update(self, alive: List[dict], stale: List[dict],
                          target: int) -> None:
        """Migrate the replica set to self.version (serve update).

        rolling (reference replica_managers rolling update): surge one
        new-version replica at a time; every time one turns READY, retire
        one old replica — capacity never dips below the old READY set.
        Mixed versions serve traffic during the transition.

        blue_green: bring up a full new-version set alongside the old one;
        traffic stays pinned to the old version (active_versions) until
        the new set can carry the whole target, then the old set retires
        and traffic cuts over atomically."""
        fresh = [r for r in alive if (r.get('version') or 1) >= self.version]
        fresh_ready = [r for r in fresh
                       if r['status'] is ReplicaStatus.READY]
        old_versions = {(r.get('version') or 1) for r in stale}
        if self.update_mode == 'blue_green':
            self.active_versions = old_versions
            if len(fresh) < target:
                self.scale_up(target - len(fresh))
            elif len(fresh_ready) >= target:
                for rep in stale:
                    logger.info(f'blue_green cutover: retiring v'
                                f'{rep.get("version") or 1} replica '
                                f'{rep["replica_id"]}.')
                    self._retire_replica(rep)
                self.active_versions = {self.version}
            return
        # rolling: the invariant is READY count never drops below target —
        # a stale replica retires only when the READY set has a surplus
        # (the surged new-version replica turned READY).
        self.active_versions = old_versions | {self.version}
        ready_total = sum(r['status'] is ReplicaStatus.READY for r in alive)
        if ready_total > target and stale:
            oldest = min(stale, key=lambda r: r['replica_id'])
            logger.info(f'rolling update: replica {oldest["replica_id"]} '
                        f'(v{oldest.get("version") or 1}) retired in '
                        f'favor of a v{self.version} replica.')
            self._retire_replica(oldest)
            alive = [r for r in alive if r is not oldest]
        if len(alive) < target + 1 and len(fresh) < target:
            self.scale_up(1)   # surge one new-version replica

    def ready_id_urls(self) -> List[tuple]:
        """(replica_id, url) pairs the LB may route to: READY replicas
        of an active version (blue_green pins this to the old set
        until cutover). THE routable-set filter — ready_urls, the
        weight map and the fleet scraper's target list all derive from
        it, so the scraped set can never drift from the routed set."""
        return [(r['replica_id'], r['url'])
                for r in self._my_replicas()
                if r['status'] is ReplicaStatus.READY and r['url'] and
                (r.get('version') or 1) in self.active_versions]

    def ready_urls(self) -> List[str]:
        """URLs the LB may route to (see ready_id_urls)."""
        return [url for _, url in self.ready_id_urls()]

    def ready_url_weights(self, routable_urls: Optional[List[str]] = None
                          ) -> Dict[str, float]:
        """url → capacity weight (total chips of the replica's launched
        slice; 1.0 when unknown) for instance-aware LB policies — a
        heterogeneous replica set (spot fallback across accelerator
        sizes) should not be loaded uniformly. Same readiness AND
        active-version filter as ready_urls (one source of truth);
        pass ``routable_urls`` from a ready_id_urls() result already
        in hand so one reconcile pass sees ONE consistent snapshot."""
        weights: Dict[str, float] = {}
        routable = set(self.ready_urls() if routable_urls is None
                       else routable_urls)
        for rep in self._my_replicas():
            if rep['url'] not in routable:
                continue
            weight = 1.0
            record = global_state.get_cluster(
                self._cluster_name(rep['replica_id']))
            if record is not None:
                try:
                    handle = slice_backend.SliceResourceHandle.from_dict(
                        record['handle'])
                    tpu = handle.launched_resources_obj().tpu
                    if tpu is not None:
                        weight = float(tpu.total_chips)
                except Exception as e:  # pylint: disable=broad-except
                    logger.debug(f'weight for replica '
                                 f'{rep["replica_id"]} falls back to 1.0 '
                                 f'(handle parse: {e})')
            weights[rep['url']] = weight
        return weights
