"""Native inference engine: HTTP server over the KV-cache decode path.

Reference analog: the reference serves TPU models through external
engines (JetStream/vLLM recipes, examples/tpu/v6e/README.md:119-127);
this framework owns the model code, so the engine is native and ~200
lines: aiohttp front, a dynamic batcher, and models/decode.py underneath.

TPU-first design:
  - **Continuous batching**: a fixed pool of MAX_BATCH cache slots is
    stepped token by token (fused into MAX_STEP_CHUNK-step device calls
    while nothing is queued); a request arriving mid-generation is
    prefilled into a free slot and joins after at most one in-flight
    fused call — it never waits for earlier requests to drain. Static shapes
    rule on TPU, so the step always runs at batch MAX_BATCH (inactive
    slots are masked) and prompts prefill per power-of-two length bucket
    — a bounded set of compiled programs, cached by jax forever after.
    Sampling params are PER-ROW runtime arrays (decode.select_token_per
    _row), so mixed temperature/top_k/top_p requests share one step and
    client-supplied values can never trigger a recompile.
  - **Byte-level text mode**: POST {'text': ...} uses the hermetic
    byte tokenizer (data/loader.py), so the engine serves text without
    downloads; token mode ({'tokens': [...]}) is the raw interface.
  - **Checkpoint loading**: --ckpt-dir restores trainer checkpoints
    (orbax, train/checkpoints.py) so `skytpu jobs launch` training and
    `skytpu serve up` serving share weights end-to-end.

Run: python -m skypilot_tpu.serve.engine --model llama-1b --port 8000
(the serve plane sets $SKYTPU_SERVE_PORT; see examples/serve-llama-1b).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

MAX_BATCH = int(os.environ.get('SKYTPU_ENGINE_MAX_BATCH', '8'))
# Max decode steps fused into one device call when no request is waiting.
MAX_STEP_CHUNK = int(os.environ.get('SKYTPU_ENGINE_STEP_CHUNK', '8'))


def _parse_sampling(body, default_temperature: float = 0.0):
    """(temperature, top_k, top_p) from an untrusted request body —
    shared by /generate and /v1/completions. Raises ValueError/TypeError
    on garbage (NaN, out-of-range)."""
    import math
    temperature = float(body.get('temperature', default_temperature))
    if not math.isfinite(temperature):    # json accepts NaN/Infinity
        raise ValueError(f'temperature {temperature} not finite')
    temperature = max(temperature, 0.0)
    top_k = body.get('top_k')
    top_k = max(int(top_k), 0) if top_k is not None else None
    top_p = body.get('top_p')
    top_p = float(top_p) if top_p is not None else None
    if top_p is not None and not 0.0 <= top_p <= 1.0:
        raise ValueError(f'top_p {top_p} outside [0, 1]')
    return temperature, top_k, top_p


def _bytes_to_text(tokens) -> str:
    """Byte-level detokenize (data/loader.py's hermetic tokenizer)."""
    return bytes(t for t in tokens if t < 256).decode('utf-8',
                                                      errors='replace')


def _bucket(n: int, floor: int = 16) -> int:
    """Round up to a power of two (bounded compile count)."""
    b = floor
    while b < n:
        b *= 2
    return b


class InferenceEngine:
    """Owns params + the batched generate loop."""

    def __init__(self, model: str, ckpt_dir: Optional[str] = None,
                 max_len: Optional[int] = None,
                 quantize: Optional[str] = None):
        import jax
        import jax.numpy as jnp
        from skypilot_tpu.models import decode as decode_lib
        from skypilot_tpu.models import get_config, mla, module_for
        self._jnp = jnp
        self.cfg = get_config(model)
        # MLA models generate over the latent cache (models/mla.py);
        # everything else over the K/V cache. Same call surface.
        self._decode = (mla if isinstance(self.cfg, mla.MLAConfig)
                        else decode_lib)
        self.max_len = max_len or min(self.cfg.max_seq_len, 2048)
        if ckpt_dir:
            from skypilot_tpu.parallel import MeshSpec, build_mesh
            from skypilot_tpu.train import checkpoints, train_lib
            mesh = build_mesh(MeshSpec())
            tx = train_lib.default_optimizer(learning_rate=1e-4,
                                             warmup_steps=1, total_steps=2)
            with checkpoints.Checkpointer(ckpt_dir) as ckpt:
                state = ckpt.restore(self.cfg, mesh, tx)
                if state is None:
                    raise FileNotFoundError(
                        f'No checkpoint under {ckpt_dir!r}.')
                params = state.params
            logger.info(f'Restored checkpoint step {int(state.step)} '
                        f'from {ckpt_dir}.')
        else:
            mod = module_for(self.cfg)
            params = jax.jit(lambda r: mod.init_params(r, self.cfg))(
                jax.random.PRNGKey(0))
            logger.info('No --ckpt-dir: serving randomly-initialized '
                        'params (benchmark/demo mode).')
        self.params = decode_lib.cast_params_for_decode(
            params, self.cfg, quantize=quantize)
        if quantize:
            logger.info(f'Serving with weight-only {quantize} '
                        f'quantization (decode is HBM-bound: ~2x fewer '
                        f'weight bytes per token).')
        # Created by start() on the SERVING event loop: an asyncio.Queue
        # binds to the loop that first awaits it, and the engine object
        # may outlive a loop (tests; server restarts).
        self._queue: Optional[asyncio.Queue] = None
        self._state_ready = False
        self.warm = False
        self.step_count = 0          # observability + tests

    def start(self) -> None:
        """Bind the batcher to the current event loop (call at server
        startup)."""
        self._queue = asyncio.Queue()
        asyncio.create_task(self.batch_loop())

    # -- device state ------------------------------------------------------
    def _reset_device_state(self) -> None:
        """(Re)build the slot pool + cache. Called at startup AND after a
        step/admit execution failure: the failed call was DONATED the old
        cache buffer (jax invalidates it even on error), so continuing
        with the old self.cache would poison every later request while
        /health still says ok."""
        import jax
        import numpy as np
        self.cache = self._decode.init_cache(self.cfg, MAX_BATCH,
                                             self.max_len)
        self.rng = jax.random.PRNGKey(int(time.time_ns()) % (2**31))
        self.slots: List[Optional[Dict[str, Any]]] = [None] * MAX_BATCH
        self.last = np.zeros(MAX_BATCH, np.int32)
        self.temp = np.zeros(MAX_BATCH, np.float32)
        self.topk = np.zeros(MAX_BATCH, np.int32)
        self.topp = np.zeros(MAX_BATCH, np.float32)

    def _ensure_state(self) -> None:
        """Jitted step/admit closures, built once (after any test-time cfg
        overrides — rebuilding them would recompile)."""
        if self._state_ready:
            return
        import functools
        import jax
        jnp = self._jnp
        cfg, dec, max_len = self.cfg, self._decode, self.max_len
        from skypilot_tpu.models import decode as decode_lib

        self._reset_device_state()

        def step_k(k):
            """k decode steps in ONE device call (host-loop dispatch cost
            amortized when no request is waiting to join). Compiled per
            distinct k — bounded by MAX_STEP_CHUNK."""

            @functools.partial(jax.jit, donate_argnums=(1,))
            def run(params, cache, last, temp, topk, topp, rng, active):
                def body(carry, _):
                    last_t, cache_t, rng_t = carry
                    logits, cache_t = dec.decode_step(params, last_t,
                                                      cache_t, cfg,
                                                      active=active)
                    rng_t, sub = jax.random.split(rng_t)
                    nxt = decode_lib.select_token_per_row(
                        logits, temp, topk, topp, sub)
                    nxt = jnp.where(active, nxt, last_t)
                    return (nxt, cache_t, rng_t), nxt
                (last_f, cache_f, rng_f), toks = jax.lax.scan(
                    body, (last, cache, rng), None, length=k)
                del last_f
                return toks, cache_f, rng_f
            return run

        self._step_k_jits = {}

        def step(params, last, cache, temp, topk, topp, rng, active, k=1):
            if k not in self._step_k_jits:
                self._step_k_jits[k] = step_k(k)
            return self._step_k_jits[k](params, cache, last, temp, topk,
                                        topp, rng, active)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def admit(params, cache, tokens, length, slot, temp, topk, topp,
                  rng):
            """Prefill one prompt (bucketed [1, S]) into cache row `slot`
            and sample its first token. One compile per prompt bucket."""
            logits, row = dec.prefill(params, tokens, cfg, max_len,
                                      lengths=length[None])

            def write(big, one):
                if big.ndim == 1:               # the per-row length vector
                    return big.at[slot].set(one[0])
                return big.at[:, slot].set(one[:, 0])

            cache = jax.tree.map(write, cache, row)
            rng, sub = jax.random.split(rng)
            first = decode_lib.select_token_per_row(
                logits[None] if logits.ndim == 1 else logits,
                temp[None], topk[None], topp[None], sub)[0]
            return first, cache, rng

        self._step_jit = step
        self._admit_jit = admit
        self._state_ready = True

    def warmup(self) -> None:
        """Compile the admit (16-bucket) + BOTH step programs (k=1 and
        k=MAX_STEP_CHUNK) through the real code path, then free the
        warmup slot; /health flips only after — no client request may
        ever hit a fresh XLA compile."""
        self._ensure_state()
        self._admit((list(range(1, 9)), MAX_STEP_CHUNK + 2, 0.0, None,
                     None, None))
        self._step_once()      # k = MAX_STEP_CHUNK (remaining is large)
        self._step_once()      # k = 1 (remaining == 1)
        self.slots = [None] * MAX_BATCH
        self.warm = True
        logger.info('Engine warm (admit + step programs compiled).')

    # -- continuous batching ----------------------------------------------
    async def submit(self, tokens: List[int], max_new: int,
                     temperature: float, top_k: Optional[int],
                     top_p: Optional[float]) -> List[int]:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((tokens, max_new, temperature, top_k, top_p,
                               fut))
        return await fut

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit(self, item) -> None:
        """Prefill a request into a free slot (device work: call off-loop)."""
        jnp = self._jnp
        tokens, max_new, temperature, top_k, top_p, fut = item
        slot = self._free_slot()
        assert slot is not None
        s = _bucket(len(tokens))
        padded = jnp.asarray([tokens + [0] * (s - len(tokens))], jnp.int32)
        self.temp[slot] = max(float(temperature), 0.0)
        self.topk[slot] = int(top_k) if top_k else 0
        self.topp[slot] = float(top_p) if top_p else 0.0
        first, self.cache, self.rng = self._admit_jit(
            self.params, self.cache, padded,
            jnp.int32(len(tokens)), jnp.int32(slot),
            jnp.float32(self.temp[slot]), jnp.int32(self.topk[slot]),
            jnp.float32(self.topp[slot]), self.rng)
        first = int(first)
        self.last[slot] = first
        self.slots[slot] = {'fut': fut, 'want': max_new, 'out': [first]}

    def _step_once(self) -> None:
        """Decode step(s) over the whole slot pool (device work).

        Steps MAX_STEP_CHUNK tokens per device call when nothing is
        waiting to join (the per-call host dispatch is the continuous
        batcher's overhead); drops back to single steps under admission
        pressure. A request arriving mid-call therefore waits at most one
        in-flight fused call (up to MAX_STEP_CHUNK steps) to join."""
        import jax
        jnp = self._jnp
        remaining = [s['want'] - len(s['out']) for s in self.slots
                     if s is not None]
        # k ∈ {1, MAX_STEP_CHUNK} ONLY: exactly two compiled step
        # programs, both built in warmup — a client-chosen max_new must
        # not be able to trigger a fresh XLA compile via tail-chunk sizes.
        k = 1
        if (remaining and min(remaining) >= MAX_STEP_CHUNK and
                (self._queue is None or self._queue.empty())):
            k = MAX_STEP_CHUNK
        active = jnp.asarray([s is not None for s in self.slots])
        toks, self.cache, self.rng = self._step_jit(
            self.params, jnp.asarray(self.last), self.cache,
            jnp.asarray(self.temp), jnp.asarray(self.topk),
            jnp.asarray(self.topp), self.rng, active, k=k)
        toks = jax.device_get(toks)              # [k, B]
        self.step_count += k
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            for t in range(k):
                if len(s['out']) < s['want']:
                    s['out'].append(int(toks[t][i]))
                    self.last[i] = int(toks[t][i])

    def _finish_done(self) -> None:
        """Resolve futures of slots that produced all they asked for (runs
        on the event loop)."""
        for i, s in enumerate(self.slots):
            if s is not None and len(s['out']) >= s['want']:
                fut = s['fut']
                if fut is not None and not fut.done():
                    fut.set_result(s['out'][:s['want']])
                self.slots[i] = None

    async def batch_loop(self) -> None:
        """Continuous scheduler: admit whenever a slot is free, step while
        anything is active. A late request joins after at most one
        in-flight fused call — it never waits for earlier requests to
        drain."""
        self._ensure_state()
        while True:
            busy = any(s is not None for s in self.slots)
            if not busy:
                item = await self._queue.get()
                try:
                    await asyncio.to_thread(self._admit, item)
                except Exception as e:  # pylint: disable=broad-except
                    self._fail_all(e, extra=item)
                self._finish_done()     # want==1 resolves without a step
                continue
            while self._free_slot() is not None and not self._queue.empty():
                item = self._queue.get_nowait()
                try:
                    await asyncio.to_thread(self._admit, item)
                except Exception as e:  # pylint: disable=broad-except
                    self._fail_all(e, extra=item)
            try:
                await asyncio.to_thread(self._step_once)
            except Exception as e:  # pylint: disable=broad-except
                self._fail_all(e)
                continue
            self._finish_done()

    def _fail_all(self, e: Exception, extra=None) -> None:
        """Fail every in-flight request and rebuild the device state: the
        failed jit call was donated the cache buffer, so the whole pool is
        unusable (see _reset_device_state)."""
        logger.warning(f'Engine step/admit failed; resetting slot pool: '
                       f'{e}')
        if extra is not None and extra[-1] is not None \
                and not extra[-1].done():
            extra[-1].set_exception(e)
        for s in self.slots:
            if s is not None and s['fut'] is not None \
                    and not s['fut'].done():
                s['fut'].set_exception(e)
        self._reset_device_state()


def build_app(engine: InferenceEngine):
    from aiohttp import web

    async def health(request):
        del request
        if not engine.warm:
            return web.json_response({'status': 'warming'}, status=503)
        return web.json_response({'status': 'ok'})

    async def generate(request):
        body = await request.json()
        if 'text' in body:
            from skypilot_tpu.data import loader as loader_lib
            tokens = [int(t) for t in
                      loader_lib.tokenize_text(body['text'])]
        else:
            tokens = [int(t) for t in body['tokens']]
        if not tokens:
            return web.json_response({'error': 'empty prompt'}, status=400)
        max_new = int(body.get('max_new_tokens', 64))
        if max_new < 1:
            return web.json_response({'error': 'max_new_tokens < 1'},
                                     status=400)
        # The batcher pads prompts up to a power-of-two bucket; admission
        # is checked against the bucketed length so a grouped request can
        # always be served in full.
        if _bucket(len(tokens)) + max_new > engine.max_len:
            return web.json_response(
                {'error': f'bucketed prompt ({_bucket(len(tokens))}) + '
                          f'max_new_tokens exceeds max_len '
                          f'{engine.max_len}'}, status=400)
        # Sampling params are validated/clamped at admission and passed as
        # PER-ROW runtime arrays — untrusted values can neither trigger a
        # recompile nor fail the whole batch (top_k is further clamped to
        # vocab inside decode.select_token_per_row).
        try:
            temperature, top_k, top_p = _parse_sampling(body)
        except (TypeError, ValueError) as e:
            return web.json_response({'error': f'bad sampling params: {e}'},
                                     status=400)
        out = await engine.submit(tokens, max_new, temperature, top_k,
                                  top_p)
        resp: Dict[str, Any] = {'tokens': out}
        if 'text' in body:
            resp['text'] = _bytes_to_text(out)
        return web.json_response(resp)

    async def openai_completions(request):
        """OpenAI-compatible completions (reference users serve through
        vLLM's OpenAI server — llm/qwen, llm/mixtral recipes curl
        /v1/completions; non-streaming clients work against this engine
        unchanged). Byte-level tokenizer; single choice; token-id list
        prompts honored; stream rejected loudly."""

        def bad(msg, status=400):
            return web.json_response(
                {'error': {'message': msg,
                           'type': 'invalid_request_error'}}, status=status)

        body = await request.json()
        if not isinstance(body, dict):
            return bad('request body must be a JSON object')
        if body.get('stream'):
            return bad('streaming is not supported; use stream=false')
        prompt = body.get('prompt', '')
        try:
            if isinstance(prompt, list) and prompt and all(
                    isinstance(t, int) for t in prompt):
                tokens = [int(t) for t in prompt]   # token-id prompt
            elif isinstance(prompt, list):
                if len(prompt) != 1:
                    return bad('only a single prompt per request is '
                               'supported')
                prompt = prompt[0]
                from skypilot_tpu.data import loader as loader_lib
                tokens = [int(t)
                          for t in loader_lib.tokenize_text(str(prompt))]
            else:
                from skypilot_tpu.data import loader as loader_lib
                tokens = [int(t)
                          for t in loader_lib.tokenize_text(str(prompt))]
            if not tokens:
                return bad('empty prompt')
            max_new = int(body.get('max_tokens', 16))
            if max_new < 1:
                raise ValueError('max_tokens must be >= 1')
            temperature, top_k, top_p = _parse_sampling(
                body, default_temperature=1.0)
        except (TypeError, ValueError) as e:
            return bad(f'invalid request: {e}')
        if _bucket(len(tokens)) + max_new > engine.max_len:
            return bad(f'prompt + max_tokens exceeds max_len '
                       f'{engine.max_len}')
        out = await engine.submit(tokens, max_new, temperature, top_k,
                                  top_p)
        return web.json_response({
            'id': f'cmpl-{time.time_ns()}',
            'object': 'text_completion',
            'created': int(time.time()),
            'model': body.get('model', 'skytpu'),
            'choices': [{'text': _bytes_to_text(out), 'index': 0,
                         'logprobs': None, 'finish_reason': 'length'}],
            'usage': {'prompt_tokens': len(tokens),
                      'completion_tokens': len(out),
                      'total_tokens': len(tokens) + len(out)},
        })

    async def openai_models(request):
        del request
        return web.json_response({
            'object': 'list',
            'data': [{'id': 'skytpu', 'object': 'model',
                      'owned_by': 'skytpu'}],
        })

    app = web.Application()
    app.router.add_get('/health', health)
    app.router.add_get('/', health)
    app.router.add_post('/generate', generate)
    app.router.add_post('/v1/completions', openai_completions)
    app.router.add_get('/v1/models', openai_models)

    async def _start(app_):
        del app_
        engine.start()

    app.on_startup.append(_start)
    return app


def main() -> None:
    from aiohttp import web
    parser = argparse.ArgumentParser(prog='skytpu-engine')
    parser.add_argument('--model', default='llama-1b')
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--max-len', type=int, default=None)
    parser.add_argument('--quantize', choices=['int8'], default=None,
                        help='Weight-only quantization for serving '
                             '(dense Llama-family models).')
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYTPU_SERVE_PORT',
                                                   '8000')))
    parser.add_argument('--host', default='0.0.0.0')
    args = parser.parse_args()
    engine = InferenceEngine(args.model, ckpt_dir=args.ckpt_dir,
                             max_len=args.max_len, quantize=args.quantize)
    engine.warmup()   # readiness flips only once serving is fast
    web.run_app(build_app(engine), host=args.host, port=args.port,
                print=None)


if __name__ == '__main__':
    main()
