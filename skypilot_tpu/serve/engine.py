"""Native inference engine: HTTP server over the KV-cache decode path.

Reference analog: the reference serves TPU models through external
engines (JetStream/vLLM recipes, examples/tpu/v6e/README.md:119-127,
llm/qwen/README.md:60 — an OpenAI-compatible server over HF
checkpoints); this framework owns the model code, so the engine is
native: aiohttp front, a dynamic batcher, and models/decode.py
underneath.

TPU-first design:
  - **Continuous batching**: a fixed pool of MAX_BATCH cache slots is
    stepped token by token (fused into MAX_STEP_CHUNK-step device calls
    while nothing is queued); a request arriving mid-generation is
    prefilled into a free slot and joins after at most the in-flight
    fused call(s) drain (≤ two when the pipeline is looking ahead) —
    it never waits for earlier requests to drain. Static shapes
    rule on TPU, so the step always runs at batch MAX_BATCH (inactive
    slots are masked) and prompts prefill per power-of-two length bucket
    — a bounded set of compiled programs, cached by jax forever after.
    Sampling params are PER-ROW runtime arrays (decode.select_token_per
    _row), so mixed temperature/top_k/top_p requests share one step and
    client-supplied values can never trigger a recompile.
  - **Overlapped decode pipeline** (docs/ENGINE.md): the fused step is
    split into a dispatch half (enqueue the device call; the per-slot
    previous token `last` is DEVICE-RESIDENT and carried through the
    jit, so no host value is needed to start step N+1) and a collect
    half (device→host transfer + Python bookkeeping). While traffic is
    steady — nothing queued, no cancels pending — the batch loop keeps
    one fused call in flight: step N+1 is dispatched before step N's
    results are consumed, so the TPU never waits on Python. Admission,
    cancellation, speculation and failure resets happen only at
    drained points (collect always precedes slot/buffer reuse).
  - **Real checkpoints**: --hf-dir points at an HF checkpoint directory
    (safetensors + tokenizer.json) and serves it with the real
    tokenizer, per-family chat template, and EOS stop handling
    (models/hf_import.py, data/tokenizer.py). Without it, the hermetic
    byte-level tokenizer serves text with zero downloads.
  - **Streaming**: /v1/completions and /v1/chat/completions support
    SSE (stream=true) with UTF-8-safe incremental detokenization.
  - **Backpressure**: the admission queue is BOUNDED; overflow returns
    429 immediately (the serve LB's least-load policy needs replicas
    that reject, not replicas that silently queue into SLO death).
    /metrics exposes queue depth / in-flight / step counters.
  - **Checkpoint loading**: --ckpt-dir restores trainer checkpoints
    (train/checkpoints.py) so `skytpu jobs launch` training and
    `skytpu serve up` serving share weights end-to-end.

Run: python -m skypilot_tpu.serve.engine --model llama-1b --port 8000
or:  python -m skypilot_tpu.serve.engine --hf-dir ~/ckpts/Llama-3.2-1B
(the serve plane sets $SKYTPU_SERVE_PORT; see examples/serve-llama-1b).
"""
from __future__ import annotations

import argparse
import asyncio
import json as json_lib
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import flight as flight_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import request_class
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.utils import failpoints as failpoints_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

# Engine observability (docs/OBSERVABILITY.md catalog, rendered by the
# /metrics endpoint). Histograms capture the decode pipeline's
# before/after: dispatch time is host work per device call, collect is
# the bookkeeping half, host_sync is the time the event-loop's worker
# thread actually BLOCKS on device→host transfers — the quantity the
# double-buffered pipeline exists to hide.
_M_STEP_SECONDS = metrics_lib.histogram(
    'skytpu_engine_step_seconds',
    'Decode-step latency by pipeline phase (dispatch = host time to '
    'enqueue the fused device call, collect = transfer + bookkeeping)',
    labels={'phase': ('dispatch', 'collect')})
_M_ADMIT_SECONDS = metrics_lib.histogram(
    'skytpu_engine_admit_seconds',
    'Grouped-prefill admission latency (one device call per group)')
_M_HOST_SYNC_SECONDS = metrics_lib.histogram(
    'skytpu_engine_host_sync_seconds',
    'Time the decode loop blocks on device→host transfers')
_M_QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_engine_queue_depth', 'Requests waiting in the admission '
    'queue')
_M_IN_FLIGHT = metrics_lib.gauge(
    'skytpu_engine_in_flight', 'Requests occupying decode slots')
_M_STEPS = metrics_lib.counter(
    'skytpu_engine_steps_total', 'Decode steps executed (fused steps '
    'count each token)')
_M_TOKENS = metrics_lib.counter(
    'skytpu_engine_tokens_total', 'Tokens generated and delivered to '
    'requests')
_M_REQUESTS = metrics_lib.counter(
    'skytpu_engine_requests_total', 'Requests accepted into the '
    'admission queue')
_M_REJECTED = metrics_lib.counter(
    'skytpu_engine_rejected_total', 'Requests rejected with 429 '
    '(admission queue full)')
_M_RESURRECTED = metrics_lib.counter(
    'skytpu_engine_resurrected_total',
    'Requests internally resubmitted after a device failure reset '
    '(they had not sampled a token, so nothing was lost)')
_M_PREFIX = metrics_lib.counter(
    'skytpu_engine_prefix_requests_total',
    'Prefix (system-prompt) cache lookups at admission',
    labels={'outcome': ('hit', 'miss')})
_M_PREFIX_HITS = metrics_lib.counter(
    'skytpu_engine_prefix_hits_total', 'Prefix-cache hits (suffix-only '
    'prefills)')
_M_SPEC_ROUNDS = metrics_lib.counter(
    'skytpu_engine_spec_rounds_total', 'Speculative verify rounds')
_M_SPEC_PROPOSED = metrics_lib.counter(
    'skytpu_engine_spec_proposed_total', 'Draft tokens proposed to the '
    'verifier')
_M_SPEC_ACCEPTED = metrics_lib.counter(
    'skytpu_engine_spec_accepted_total', 'Draft tokens accepted by the '
    'verifier')
# Request-level serving latency, derived from flight-ring-aligned host
# timestamps at admit/publish time — never from per-token telemetry on
# the decode loop (observe/flight.py). TTFT = submit → first token
# (queue wait + prefill); TPOT = mean inter-token time after the
# first. The quantities BASELINE.md's serve rows and the LB's SLOs are
# written in.
_M_TTFT = metrics_lib.histogram(
    'skytpu_engine_ttft_seconds',
    'Time to first token: request submit to first sampled token '
    '(queue wait + prefill)',
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))
_M_TPOT = metrics_lib.histogram(
    'skytpu_engine_tpot_seconds',
    'Time per output token after the first (mean per request)',
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
# Per-class serving latency + goodput (observe/request_class.py): the
# same publish-time observation as _M_TTFT/_M_TPOT, labeled by the
# request's DECLARED class (clamped through the closed registry — the
# LB stamps X-Skytpu-Class, submit_nowait normalizes again). Buckets
# match the unlabeled families exactly so fleet merges and windowed
# SLO deltas share one layout. Goodput counts a request 'good' only
# when it completed within its class's latency objective
# (request_class.OBJECTIVES) — the honest per-class unit the loadgen
# scorecard and the per-class SLO burn rates are written in.
_M_CLASS_TTFT = metrics_lib.histogram(
    'skytpu_engine_class_ttft_seconds',
    'Time to first token by request class (declared via '
    'X-Skytpu-Class, clamped to the closed class registry)',
    labels={'cls': request_class.CLASSES},
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))
_M_CLASS_TPOT = metrics_lib.histogram(
    'skytpu_engine_class_tpot_seconds',
    'Time per output token after the first by request class',
    labels={'cls': request_class.CLASSES},
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))
_M_GOODPUT = metrics_lib.counter(
    'skytpu_engine_goodput_total',
    'Finished requests by class and whether they met their class\'s '
    'latency objective (good = TTFT and TPOT at/under the '
    'request_class.OBJECTIVES bounds; slow = completed but missed '
    'them)',
    labels={'cls': request_class.CLASSES, 'outcome': ('good', 'slow')})
# Block-paged KV cache (models/paging.py; docs/ENGINE.md): queueing vs
# memory pressure must be distinguishable at /metrics — free/used page
# gauges are sampled at scrape, the alloc counter splits admissions
# that found pages from admissions that had to wait, and the wait
# histogram is the submit→admit delta (the quantity the mixed-length
# bench scenario tracks pre/post paging).
_M_PAGES_FREE = metrics_lib.gauge(
    'skytpu_engine_kv_pages_free', 'Free KV cache pages in the pool '
    '(paged mode; excludes the trash page)')
_M_PAGES_USED = metrics_lib.gauge(
    'skytpu_engine_kv_pages_used', 'KV cache pages held by live '
    'requests and shared prefix entries (paged mode)')
_M_PAGE_ALLOC = metrics_lib.counter(
    'skytpu_engine_kv_page_alloc_total',
    'Page-reservation attempts at admission: ok = pages granted, '
    'wait = the request stayed queued for lack of free pages',
    labels={'outcome': ('ok', 'wait')})
_M_ADMIT_WAIT = metrics_lib.histogram(
    'skytpu_engine_admission_wait_seconds',
    'Request submit to admission (queue wait, incl. waiting on free '
    'KV pages)',
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
# Disaggregated prefill/decode serving (serve/disagg/handoff.py;
# docs/serving.md): the three handoff stages this replica can play a
# part in — exporting a prefilled row's pages (prefill role), shipping
# them over the framed-TCP transport (prefill role), and adopting
# received pages into the local pool (decode role). Errors here are
# the disagg plane's primary health signal; the staged gauge is the
# decode-side host-memory backlog (pages are NOT held while staged).
_M_HANDOFF = metrics_lib.counter(
    'skytpu_engine_handoff_total',
    'KV page handoff operations by stage (export = gather+device_get '
    'of a prefilled row, send = framed-TCP ship to the decode '
    'replica, adopt = scatter into the local page pool) and outcome.',
    labels={'stage': ('export', 'send', 'adopt'),
            'outcome': ('ok', 'error')})
_M_HANDOFF_STAGED = metrics_lib.gauge(
    'skytpu_engine_handoff_staged',
    'Handoffs received and staged (host memory) but not yet continued '
    'by a /disagg/continue call (decode role; sampled at scrape).')
# In-place paged attention (ops/paged_attention.py; docs/ENGINE.md):
# the backend info-gauge makes "which attention path is this replica
# serving" a scrape-able fact, and the cache-traffic counters are a
# SHAPE-DERIVED proxy (bytes the step/verify programs move through the
# KV cache, computed host-side from static shapes — never a device
# sync) that makes the gather-vs-fused win visible at /metrics:
# the gather baseline's extra view materialization + scatter-back
# shows up as ~2 extra full-cache traversals per fused k-step call.
_M_ATTN_BACKEND = metrics_lib.gauge(
    'skytpu_engine_attn_backend',
    'Info gauge: 1 on the attention backend this replica serves the '
    'paged hot path with (SKYTPU_ENGINE_ATTN), 0 elsewhere',
    labels={'backend': ('fused', 'pallas', 'gather')})
_M_CACHE_READ = metrics_lib.counter(
    'skytpu_engine_cache_bytes_read_total',
    'KV-cache bytes read by the decode step/verify programs '
    '(shape-derived proxy: attention reads of the [B, max_len] span '
    'plus, on the gather baseline, the view materialization and '
    'scatter-back reads)')
_M_CACHE_WRITTEN = metrics_lib.counter(
    'skytpu_engine_cache_bytes_written_total',
    'KV-cache bytes written by the decode step/verify programs '
    '(shape-derived proxy: the new token positions plus, on the '
    'gather baseline, the materialized contiguous view)')

# KV memory hierarchy (serve/host_store.py; docs/ENGINE.md): the
# spilled gauge is the host tier's device-pages-worth of parked KV
# (sampled at scrape from the store), the quantized gauge publishes
# how many device pool pages hold int8 codes (pool size minus trash
# when SKYTPU_ENGINE_KV_QUANT=int8, 0 on fp pools — an info gauge a
# dashboard can pivot capacity math on), and the two histograms time
# the host halves of the tier moves: spill = export + device_get +
# framed encode, wake = decode + page alloc + scatter-in. Both run at
# drained points only, so they bound the admission-latency cost of
# the hierarchy directly.
_M_KV_SPILLED = metrics_lib.gauge(
    'skytpu_engine_kv_pages_spilled',
    'KV pages\' worth of cache parked in the host-RAM spill tier '
    '(SKYTPU_ENGINE_KV_HOST_MB; sampled at scrape)')
_M_KV_QUANTIZED = metrics_lib.gauge(
    'skytpu_engine_kv_pages_quantized',
    'Device pool pages holding int8-quantized KV '
    '(SKYTPU_ENGINE_KV_QUANT=int8; 0 on fp pools)')
_M_SPILL_SECONDS = metrics_lib.histogram(
    'skytpu_engine_spill_seconds',
    'Host time to spill one prefix entry to the host tier (page '
    'export + device_get + framed encode)')
_M_WAKE_SECONDS = metrics_lib.histogram(
    'skytpu_engine_wake_seconds',
    'Host time to wake one spilled prefix entry (framed decode + '
    'page alloc + scatter into fresh pages)')
_M_KV_SESSIONS_PEAK = metrics_lib.gauge(
    'skytpu_engine_kv_sessions_peak',
    'Peak count of session prefix entries resident in the KV '
    'hierarchy (device prefix store + host spill tier) since the '
    'last reset — the concurrent-sessions capacity the KV-hierarchy '
    'bench scores')

_ENGINE_METRICS = (
    _M_STEP_SECONDS, _M_ADMIT_SECONDS, _M_HOST_SYNC_SECONDS,
    _M_QUEUE_DEPTH, _M_IN_FLIGHT, _M_STEPS, _M_TOKENS, _M_REQUESTS,
    _M_REJECTED, _M_PREFIX, _M_PREFIX_HITS, _M_SPEC_ROUNDS,
    _M_SPEC_PROPOSED, _M_SPEC_ACCEPTED, _M_TTFT, _M_TPOT,
    _M_CLASS_TTFT, _M_CLASS_TPOT, _M_GOODPUT,
    _M_PAGES_FREE, _M_PAGES_USED, _M_PAGE_ALLOC, _M_ADMIT_WAIT,
    _M_HANDOFF, _M_HANDOFF_STAGED, _M_ATTN_BACKEND, _M_CACHE_READ,
    _M_CACHE_WRITTEN, _M_KV_SPILLED, _M_KV_QUANTIZED,
    _M_SPILL_SECONDS, _M_WAKE_SECONDS, _M_KV_SESSIONS_PEAK)


def _seed_counter_zeros() -> None:
    """Make every counter series render a zero sample from birth (the
    pre-registry /metrics always emitted 0s; Prometheus rate()/absent()
    alerts rely on the series existing before its first event). Called
    at import and again after warmup's metric reset."""
    for metric in (_M_STEPS, _M_TOKENS, _M_REQUESTS, _M_REJECTED,
                   _M_PREFIX_HITS, _M_SPEC_ROUNDS, _M_SPEC_PROPOSED,
                   _M_SPEC_ACCEPTED):
        metric.inc(0)
    _M_PREFIX.inc(0, outcome='hit')
    _M_PREFIX.inc(0, outcome='miss')
    _M_PAGE_ALLOC.inc(0, outcome='ok')
    _M_PAGE_ALLOC.inc(0, outcome='wait')
    _M_CACHE_READ.inc(0)
    _M_CACHE_WRITTEN.inc(0)
    for cls in request_class.CLASSES:
        _M_GOODPUT.inc(0, cls=cls, outcome='good')
        _M_GOODPUT.inc(0, cls=cls, outcome='slow')


_seed_counter_zeros()


def _set_attn_backend_gauge(backend: str) -> None:
    """Publish the active attention backend as an info gauge (1 on the
    serving backend, 0 on the others — every series exists, so a
    dashboard can pivot on it without absent-series special cases)."""
    for b in ('fused', 'pallas', 'gather'):
        _M_ATTN_BACKEND.set(1.0 if b == backend else 0.0, backend=b)


MAX_BATCH = knobs.get_int('SKYTPU_ENGINE_MAX_BATCH')
# Max decode steps fused into one device call when no request is waiting.
MAX_STEP_CHUNK = knobs.get_int('SKYTPU_ENGINE_STEP_CHUNK')
# Bounded admission queue: overflow => 429 (backpressure the LB can see).
MAX_QUEUE = knobs.get_int('SKYTPU_ENGINE_MAX_QUEUE')
# Prefix (system-prompt) KV cache: LRU entry count, 0 disables. A hit
# prefills only the new tokens (decode.prefill_extend) — the TTFT win
# for chat traffic re-sending system prompt + history every turn.
PREFIX_CACHE_ENTRIES = knobs.get_int('SKYTPU_ENGINE_PREFIX_CACHE')
# Prompts shorter than this are never snapshotted (the prefill they'd
# save is too small to matter; powers of two only).
PREFIX_MIN_TOKENS = 64
# Top-N alternative logprobs computed per token (OpenAI `logprobs=N` /
# chat `top_logprobs`). The STEP/VERIFY programs compute (and transfer)
# the [.., K] top-k tensors only in their want_tops=True variants —
# selected iff some active slot requested logprobs — so the common
# steady-state path transfers just tokens + chosen logprobs. Admit
# programs keep it always-on: one lax.top_k per REQUEST (not per
# token) is negligible, and gating it there would double the
# (#buckets × group sizes) admit-compile matrix for nothing.
TOP_LOGPROBS_K = 5
# Speculative decoding: propose this many tokens per verify round via
# prompt-lookup self-drafting (0 disables). One K-wide verify_step
# costs about one decode step (HBM weight reads dominate), so every
# accepted token is a nearly-free TPOT win; outputs stay EXACTLY the
# greedy decode's (the speculative guarantee — pin-tested).
SPEC_K = knobs.get_int('SKYTPU_ENGINE_SPEC_K')
# Longest n-gram matched against the row's own context when drafting.
SPEC_NGRAM = 3
# Only the trailing window of a row's context is scanned for draft
# matches — the scan is host-side Python on the latency-critical loop.
SPEC_LOOKUP_WINDOW = 512
# Adaptive backoff: when a round's accept fraction drops below
# SPEC_MIN_ACCEPT, speculation pauses for SPEC_COOLDOWN rounds (the
# fused-chunk path amortizes dispatch better when drafts keep missing),
# then re-probes — traffic whose text stops repeating stops paying for
# speculation automatically.
SPEC_MIN_ACCEPT = 0.25
SPEC_COOLDOWN = knobs.get_int('SKYTPU_ENGINE_SPEC_COOLDOWN')
# When a speculation probe finds NO draft on any row (or a row lacks
# verify headroom), speculation pauses this many steps and the overlap
# PIPELINE owns the pool — probing every round would both starve the
# pipeline for non-repetitive greedy traffic and pay the host-side
# draft scan for nothing. The cooldown ticks at collect, so the pool
# is re-scanned a few tokens later when drafts may have appeared.
SPEC_NO_DRAFT_COOLDOWN = 4
# --- Block-paged KV cache (models/paging.py; docs/ENGINE.md) ---------
# Paged mode is the default: the cache is a pool of fixed-size pages,
# per-request page tables ride the jits as fixed-shape int32 arrays,
# finished rows release pages at collect time, and long prompts
# prefill in chunks interleaved with decode rounds. PAGED=0 restores
# the contiguous per-slot layout (the bucket-admission baseline the
# CPU equality test and the mixed-length bench compare against).
PAGED = knobs.get_bool('SKYTPU_ENGINE_PAGED')
# Tokens per KV page. Must be a power of two dividing
# PREFIX_MIN_TOKENS (64) so power-of-two prefix snapshots land on page
# boundaries and share zero-copy.
PAGE_SIZE = knobs.get_int('SKYTPU_ENGINE_PAGE_SIZE')
# Total pool pages (including the reserved trash page). 0 = auto:
# enough for every slot's worst case plus prefix-cache headroom — no
# capacity regression vs the contiguous layout. Shrink it to
# oversubscribe memory; admission then waits on free pages (visible
# in skytpu_engine_kv_page_alloc_total{outcome="wait"}).
KV_PAGES = knobs.get_int('SKYTPU_ENGINE_KV_PAGES')
# Chunked prefill: prompts whose bucket exceeds this prefill in
# PREFILL_CHUNK-token pieces interleaved with decode rounds at drained
# points, so a long prompt no longer blocks the pool for one giant
# prefill call and short requests keep streaming. Power of two >= 16.
PREFILL_CHUNK = knobs.get_int('SKYTPU_ENGINE_PREFILL_CHUNK')
# --- KV memory hierarchy (serve/host_store.py; docs/ENGINE.md) -------
# Device page representation: 'int8' stores per-vector int8 codes with
# float32 scale sidecars (models/paging.py scale pools) — ~2x pages
# per HBM byte; decode stays allclose to the fp path and is gated by
# the pinned quality eval (QUALITY_LAST_GOOD.json). 'none' (default)
# keeps the fp pools and every bit-identity gate unchanged.
KV_QUANT = knobs.get_enum('SKYTPU_ENGINE_KV_QUANT')
# Prefix-store entries idle this long spill to the host tier at the
# batch loop's drained points (0 disables the idle sweep; page
# PRESSURE still spills evictions whenever the host tier is on).
KV_IDLE_SPILL_S = knobs.get_float('SKYTPU_ENGINE_KV_IDLE_SPILL_S')
# Host-RAM spill tier byte budget (0 disables the tier: evicted
# prefix entries just drop, yesterday's behavior).
KV_HOST_MB = knobs.get_int('SKYTPU_ENGINE_KV_HOST_MB')
# In-place paged attention backend (SKYTPU_ENGINE_ATTN, parsed and
# validated by ops.paged_attention.backend_from_env at engine init):
# 'fused' (default — pages indexed inside the step/verify/chunk
# attention, no view materialization), 'pallas' (the table-driven TPU
# kernel for the dense family; falls back to fused off-TPU and for
# MLA), or 'gather' (yesterday's gather_view → contiguous math →
# scatter programs, kept compiled as the regression baseline). Only
# meaningful in paged mode.
# Request resurrection (docs/ROBUSTNESS.md): after a device-step
# failure resets the pool, requests that never sampled a token are
# resubmitted internally instead of failed. Each request is resurrected
# at most this many times — a request whose ADMISSION deterministically
# faults must eventually surface an error, not loop forever.
RESURRECT_MAX = knobs.get_int('SKYTPU_ENGINE_RESURRECT_MAX')


class EngineOverloaded(Exception):
    """Admission queue full — surfaced as HTTP 429."""


class EngineResetError(Exception):
    """A device step/admit serving this request failed and the slot
    pool was rebuilt (_reset_device_state). STRUCTURED and RETRIABLE:
    the request's KV state is gone, but the engine is healthy again —
    a client (or the serve LB) may safely resubmit. ``tokens_emitted``
    tells a streaming client how many tokens it already received, so
    it can decide between resume-by-truncation and full retry.
    Surfaced as HTTP 503 with ``type: engine_reset_error`` and
    ``retriable: true`` (docs/ROBUSTNESS.md)."""

    def __init__(self, msg: str, tokens_emitted: int = 0):
        super().__init__(msg)
        self.tokens_emitted = tokens_emitted
        self.retriable = True


def parse_mesh_arg(mesh: str):
    """'tensor=8' / 'data=2,tensor=4' → MeshSpec (the --mesh flag).

    Axis names are the standard mesh axes (parallel/mesh.MESH_AXES); the
    reference's serve replicas are 8-chip TP instances (vLLM/JetStream
    on v5e-8, examples/tpu/v6e/README.md:119) — the equivalent here is
    --mesh tensor=8."""
    from skypilot_tpu.parallel import MeshSpec
    kwargs = {}
    for part in mesh.split(','):
        if not part:
            continue
        if '=' not in part:
            raise ValueError(f"--mesh entries are axis=N, got {part!r}")
        k, v = part.split('=', 1)
        kwargs[k.strip()] = int(v)
    try:
        return MeshSpec(**kwargs)
    except TypeError as e:
        raise ValueError(f'bad --mesh axis name: {e}') from None


def _parse_sampling(body, default_temperature: float = 0.0):
    """(temperature, top_k, top_p, presence_penalty, frequency_penalty)
    from an untrusted request body —
    shared by /generate and the /v1 endpoints. Raises ValueError/TypeError
    on garbage (NaN, out-of-range)."""
    import math
    temperature = float(body.get('temperature', default_temperature))
    if not math.isfinite(temperature):    # json accepts NaN/Infinity
        raise ValueError(f'temperature {temperature} not finite')
    temperature = max(temperature, 0.0)
    top_k = body.get('top_k')
    top_k = max(int(top_k), 0) if top_k is not None else None
    top_p = body.get('top_p')
    top_p = float(top_p) if top_p is not None else None
    if top_p is not None and not 0.0 <= top_p <= 1.0:
        raise ValueError(f'top_p {top_p} outside [0, 1]')
    penalties = []
    for field in ('presence_penalty', 'frequency_penalty'):
        val = float(body.get(field) or 0.0)
        if not math.isfinite(val) or not -2.0 <= val <= 2.0:
            raise ValueError(f'{field} {val} outside [-2, 2]')
        penalties.append(val)
    return (temperature, top_k, top_p, *penalties)


def _parse_logprobs(body, chat: bool = False) -> Tuple[bool, int]:
    """OpenAI logprobs params → (want_logprobs, top_n).

    Completions: `logprobs: N` (0..TOP_LOGPROBS_K) — chosen-token
    logprobs plus N alternatives per position. Chat: `logprobs: true`
    (+ optional `top_logprobs: N`). Logprobs report the UNPENALIZED
    model distribution and work with stream=true (per-token chunks)."""
    lp = body.get('logprobs')
    if lp is None or lp is False:
        if chat and int(body.get('top_logprobs') or 0) > 0:
            raise ValueError('top_logprobs requires logprobs=true')
        return False, 0
    if chat:
        if lp is not True:
            raise ValueError('chat logprobs must be a boolean')
        top_n = int(body.get('top_logprobs') or 0)
    else:
        # Completions semantics: logprobs=N → chosen-token logprobs AND
        # the top-N list per position; N=0 (or boolean true, the legacy
        # extension) → chosen only.
        top_n = 0 if lp is True else int(lp)
    if top_n < 0:
        raise ValueError('logprobs/top_logprobs must be >= 0')
    if top_n > TOP_LOGPROBS_K:
        raise ValueError(f'top logprobs > {TOP_LOGPROBS_K} is not '
                         f'supported (the engine computes a fixed top-'
                         f'{TOP_LOGPROBS_K} per token)')
    return True, top_n


def _completion_logprobs(tokenizer, out, lps, text, tops=None):
    """OpenAI completions logprobs object, ALIGNED with the returned
    text: parallel tokens / token_logprobs / text_offset arrays, trimmed
    when a stop string truncated the text (entries for text that was
    never returned would violate the parallel-array contract eval
    harnesses rely on). Pieces come from INCREMENTAL detokenization
    (prefix decodes, the StreamDecoder strategy) — per-token decodes can
    disagree with the joint text when a multi-byte char spans tokens,
    drifting text_offset. `tops` (optional, per-token
    [(token_id, logprob), ...]) fills OpenAI's top_logprobs dicts."""
    from skypilot_tpu.data.tokenizer import StreamDecoder
    dec = StreamDecoder(tokenizer)
    # StreamDecoder holds back an incomplete multi-byte tail (U+FFFD)
    # until the next token completes it — bare prefix decodes are NOT
    # prefixes of each other across a split char, which would leak
    # replacement chars into pieces and drift the offsets.
    all_pieces = [dec.feed([t]) for t in out]
    if all_pieces:
        all_pieces[-1] += dec.flush()
    pieces, offsets, kept, top_out = [], [], [], []
    pos = 0
    for i, v in enumerate(lps):
        if pos >= len(text):
            break    # text fully covered (or cut to nothing)
        piece = all_pieces[i]
        pieces.append(piece)
        offsets.append(pos)
        kept.append(round(v, 6))
        if tops is not None:
            top_out.append({tokenizer.decode([tid]): round(tv, 6)
                            for tid, tv in tops[i]})
        pos += len(piece)
    return {'tokens': pieces, 'token_logprobs': kept,
            'top_logprobs': top_out if tops is not None else None,
            'text_offset': offsets}


def _parse_stop_ids(body, tokenizer) -> Tuple[int, ...]:
    """Stop-token ids for a /v1 request: the tokenizer's EOS set plus any
    client-supplied stop_token_ids. ignore_eos=true disables all
    (benchmark clients measure fixed-length decode)."""
    if body.get('ignore_eos'):
        return ()
    ids = list(tokenizer.eos_ids)
    extra = body.get('stop_token_ids')
    if extra is not None:
        if (not isinstance(extra, list) or
                not all(isinstance(i, int) for i in extra)):
            raise ValueError('stop_token_ids must be a list of ints')
        ids.extend(int(i) for i in extra)
    return tuple(ids)


def _parse_n(body) -> Tuple[int, int]:
    """OpenAI `n` / `best_of`: n samples returned; best_of generated and
    ranked by mean token logprob (completions only). Bounded by the slot
    pool size — candidates continuous-batch into the same pool."""
    n = body.get('n')
    n = 1 if n is None else int(n)     # `or` would swallow n=0
    best_of = body.get('best_of')
    best_of = n if best_of is None else int(best_of)
    if not 1 <= n <= MAX_BATCH:
        raise ValueError(f'n must be in [1, {MAX_BATCH}]')
    if not n <= best_of <= MAX_BATCH:
        raise ValueError(f'best_of must be in [n, {MAX_BATCH}]')
    return n, best_of


def _record_request_spans(engine: InferenceEngine, headers, futs) -> None:
    """Record each finished request's engine-side span decomposition
    (engine.request → queue wait → prefill → decode) from the timing
    the batch loop stashed at publish (pop_timing). Called by the HTTP
    handlers AFTER the request resolves — NEVER from the batch loop
    (span-discipline: the hot path records flight-ring tuples only).

    Parentage comes from the forwarded carriers the serve LB stamps on
    its upstream call (X-Skytpu-Trace-Id / X-Skytpu-Parent-Span /
    X-Skytpu-Entity), so these spans nest under lb.upstream in
    ``/v1/traces/<id>`` and — carrying the LB's entity — fall inside
    ``/-/lb/trace/<id>``'s entity scope when the replica shares the
    journal DB. With no well-formed trace offered, nobody upstream is
    tracing this request and nothing is recorded (the histograms
    already got the data)."""
    tid = headers.get('X-Skytpu-Trace-Id', '')
    if not trace_lib.is_valid_trace_id(tid):
        return
    parent = headers.get('X-Skytpu-Parent-Span', '')
    parent = parent if trace_lib.is_valid_trace_id(parent) else None
    entity = headers.get('X-Skytpu-Entity', '').strip()[:128] or None
    for fut in futs:
        t = engine.pop_timing(fut)
        if t is None or t.get('submit_wall') is None:
            continue
        attrs: Dict[str, Any] = {'tokens': t['tokens'],
                                 'finish': t['finish'],
                                 'ttft_s': round(t['ttft_s'], 6)}
        if t['tpot_s'] is not None:
            attrs['tpot_s'] = round(t['tpot_s'], 6)
        total = t['queue_s'] + t['prefill_s'] + t['decode_s']
        rid = spans_lib.record('engine.request',
                               start_wall=t['submit_wall'],
                               duration=total, trace_id=tid,
                               parent_id=parent, entity=entity,
                               attrs=attrs)
        w = t['submit_wall']
        for name, dur in (('engine.queue', t['queue_s']),
                          ('engine.prefill', t['prefill_s']),
                          ('engine.decode', t['decode_s'])):
            spans_lib.record(name, start_wall=w, duration=dur,
                             trace_id=tid, parent_id=rid, entity=entity)
            w += dur


async def _submit_many(engine: InferenceEngine, prompts, max_new,
                       sampling, stop_ids, n: int, best_of: int,
                       want_tops: bool = False, headers=None):
    """Fan out prompts × best_of into the continuous batcher, rank each
    prompt's candidates by mean logprob, keep n per prompt (OpenAI
    n/best_of + batched-prompt semantics in one place).

    Enqueue is ALL-OR-NOTHING: submit_nowait is synchronous, so on a
    mid-fan-out EngineOverloaded every already-enqueued sibling is
    cancelled (queued items are skipped at admission; admitted ones are
    cut via engine.cancel) — a 429'd request must not leave orphans
    decoding to max_tokens with no consumer."""
    temperature, top_k, top_p, pres, freq = sampling
    cls = (request_class.from_headers(headers)
           if headers is not None else request_class.DEFAULT_CLASS)
    futs = []
    try:
        for t in prompts:
            for _ in range(best_of):
                futs.append(engine.submit_nowait(
                    t, max_new, temperature, top_k, top_p, pres, freq,
                    stop_ids=stop_ids, want_tops=want_tops, cls=cls))
    except EngineOverloaded:
        for f in futs:
            engine.cancel(f)
            f.cancel()
        raise
    try:
        all_res = await asyncio.gather(*futs)
    except EngineResetError:
        # One sibling died in a device reset and the handler is about
        # to return a 503 — siblings that were RESURRECTED must not
        # keep decoding to max_tokens with no consumer.
        for f in futs:
            if not f.done():
                engine.cancel(f)
                f.cancel()
        raise
    if headers is not None:
        _record_request_spans(engine, headers, futs)
    # usage must count EVERY generated token, including discarded
    # best_of candidates (OpenAI semantics; quota accounting reads it).
    generated = sum(len(r[0]) for r in all_res)
    results = []
    for p in range(len(prompts)):
        cand = list(all_res[p * best_of:(p + 1) * best_of])
        if best_of > n:
            cand.sort(key=lambda r: -(sum(r[2]) / max(len(r[2]), 1)))
        results.extend(cand[:n])
    return results, generated


def _stop_scan(text: str, stops: List[str]) -> Optional[int]:
    """Earliest stop-string match index in `text`, or None — the ONE
    scan both the stream (holdback) and non-stream paths use."""
    cut = None
    for s in stops:
        i = text.find(s)
        if i >= 0 and (cut is None or i < cut):
            cut = i
    return cut


def _truncate_at_stop_strings(text: str, stop) -> Tuple[str, bool]:
    """OpenAI `stop` strings: cut at the earliest occurrence."""
    if stop is None:
        return text, False
    stops = [stop] if isinstance(stop, str) else list(stop)
    for s in stops:
        if not isinstance(s, str) or not s:
            raise ValueError('stop must be a string or list of strings')
    cut = _stop_scan(text, stops)
    if cut is None:
        return text, False
    return text[:cut], True


def _tops_list(ti, tv) -> list:
    """Device top-K rows ([K] ids, [K] logprobs) → host-side
    [(token_id, logprob), ...] stored per emitted token."""
    return [(int(i), float(v)) for i, v in zip(ti, tv)]


def _lookup_draft(ctx: List[int], k: int) -> Optional[List[int]]:
    """Prompt-lookup drafting (the self-draft in speculative decoding):
    find the most recent earlier occurrence of the context's trailing
    n-gram and propose the tokens that followed it. Free (host-side, no
    draft model), and strong exactly where speculation pays — chat/RAG/
    summarization traffic that re-states its own context. Returns up to
    k proposals, or None when the context never repeats."""
    if len(ctx) > SPEC_LOOKUP_WINDOW:
        ctx = ctx[-SPEC_LOOKUP_WINDOW:]
    for n in (SPEC_NGRAM, 2):
        if len(ctx) < n + 1:
            continue
        key = ctx[-n:]
        # Most recent prior occurrence (scan backwards, skip the
        # trailing match itself).
        for i in range(len(ctx) - n - 1, -1, -1):
            if ctx[i:i + n] == key:
                cont = ctx[i + n:i + n + k]
                if cont:
                    return cont
                break
    return None


def _bucket(n: int, floor: int = 16) -> int:
    """Round up to a power of two (bounded compile count; shared
    contract lives in models/decode.bucket_size)."""
    from skypilot_tpu.models import decode as decode_lib
    return decode_lib.bucket_size(n, floor)


class _InFlightStep:
    """Host handle for a dispatched-but-uncollected fused step: the
    device output arrays (futures until the device finishes) plus the
    static facts the collect half needs. `tis`/`tvs` are None in the
    want_tops=False variant — the [k, B, K] top-k tensors were never
    computed, let alone transferred."""

    __slots__ = ('k', 'want_tops', 'toks', 'lps', 'tis', 'tvs')

    def __init__(self, k: int, want_tops: bool, toks, lps, tis=None,
                 tvs=None):
        self.k = k
        self.want_tops = want_tops
        self.toks = toks
        self.lps = lps
        self.tis = tis
        self.tvs = tvs


class InferenceEngine:
    """Owns params + tokenizer + the batched generate loop."""

    def __init__(self, model: Optional[str] = 'llama-1b',
                 ckpt_dir: Optional[str] = None,
                 hf_dir: Optional[str] = None,
                 tokenizer_path: Optional[str] = None,
                 max_len: Optional[int] = None,
                 quantize: Optional[str] = None,
                 mesh: Optional[Any] = None,
                 seed: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        from skypilot_tpu.data import tokenizer as tokenizer_lib
        from skypilot_tpu.models import decode as decode_lib
        from skypilot_tpu.models import get_config, mla, module_for
        self._jnp = jnp
        if hf_dir:
            from skypilot_tpu.models import hf_import
            self.cfg, params = hf_import.load_hf_checkpoint(hf_dir)
            self.model_name = os.path.basename(
                os.path.normpath(os.path.expanduser(hf_dir)))
        else:
            if model is None:
                raise ValueError('need --model or --hf-dir')
            self.cfg = get_config(model)
            self.model_name = model
        # MLA models generate over the latent cache (models/mla.py);
        # everything else over the K/V cache. Same call surface.
        self._decode = (mla if isinstance(self.cfg, mla.MLAConfig)
                        else decode_lib)
        self.max_len = max_len or min(self.cfg.max_seq_len, 2048)
        if ckpt_dir and hf_dir:
            raise ValueError('--ckpt-dir and --hf-dir are exclusive')
        if ckpt_dir:
            from skypilot_tpu.parallel import MeshSpec, build_mesh
            from skypilot_tpu.train import checkpoints, train_lib
            mesh = build_mesh(MeshSpec())
            tx = train_lib.default_optimizer(learning_rate=1e-4,
                                             warmup_steps=1, total_steps=2)
            with checkpoints.Checkpointer(ckpt_dir) as ckpt:
                # restore() raises FileNotFoundError when the directory
                # holds no complete step.
                state, step = ckpt.restore(self.cfg, mesh, tx)
                params = state.params
            logger.info(f'Restored checkpoint step {step} '
                        f'from {ckpt_dir}.')
        elif not hf_dir:
            mod = module_for(self.cfg)
            params = jax.jit(lambda r: mod.init_params(r, self.cfg))(
                jax.random.PRNGKey(0))
            logger.info('No --ckpt-dir/--hf-dir: serving randomly-'
                        'initialized params (benchmark/demo mode).')
        self.params = decode_lib.cast_params_for_decode(
            params, self.cfg, quantize=quantize)
        if quantize:
            logger.info(f'Serving with weight-only {quantize} '
                        f'quantization (decode is HBM-bound: ~2x fewer '
                        f'weight bytes per token).')
        # Multi-chip serving: shard params/cache over a named mesh and let
        # GSPMD insert the TP/DP collectives inside the jitted step/admit
        # programs (the reference's serve replicas are 8-chip TP
        # instances: vLLM/JetStream on v5e-8,
        # examples/tpu/v6e/README.md:119-127).
        self.mesh = None
        if mesh is not None:
            self._setup_mesh(mesh, quantize)
        # Tokenizer: explicit path > the HF checkpoint's tokenizer.json >
        # hermetic byte-level (vocab 256) default.
        if tokenizer_path:
            self.tokenizer = tokenizer_lib.load_tokenizer(tokenizer_path)
        elif hf_dir:
            # No silent byte-level fallback here: serving a 128k-vocab
            # checkpoint through the 256-vocab ByteTokenizer would return
            # mojibake with HTTP 200. load_tokenizer raises loudly (with
            # conversion instructions) when tokenizer.json is missing.
            from skypilot_tpu.models import hf_import
            self.tokenizer = tokenizer_lib.load_tokenizer(
                hf_dir, eos_extra=hf_import.hf_eos_ids(hf_dir))
            logger.info(f'Loaded tokenizer.json from {hf_dir} '
                        f'(chat family: {self.tokenizer.chat_family}, '
                        f'eos ids: {self.tokenizer.eos_ids}).')
        else:
            self.tokenizer = tokenizer_lib.ByteTokenizer()
        # Created by start() on the SERVING event loop: an asyncio.Queue
        # binds to the loop that first awaits it, and the engine object
        # may outlive a loop (tests; server restarts).
        self._queue: Optional[asyncio.Queue] = None
        self._state_ready = False
        self.warm = False
        self.step_count = 0          # observability + tests
        self.tokens_generated = 0
        self.requests_total = 0
        self.rejected_total = 0
        # Speculative decoding (prompt-lookup self-draft + K-wide
        # verify). Greedy non-MoE rows only: the exactness guarantee
        # needs verify_step ≡ sequential decode (MoE capacity grouping
        # breaks that; sampling rows would need rejection sampling).
        # Both cache families have a verify_step (decode.verify_step /
        # mla.verify_step) — dense GQA AND the MLA/DeepSeek latents
        # speculate.
        from skypilot_tpu.models import moe as moe_lib
        self.spec_k = (0 if isinstance(self.cfg, (moe_lib.MoEConfig,
                                                  mla.DeepSeekMoEConfig))
                       else SPEC_K)
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._spec_cool = 0
        # Multi-host mirroring (serve/multihost.py): the leader
        # broadcasts device-touching ops here; None everywhere else.
        # `seed` pins the sampling RNG — REQUIRED for multi-host (every
        # process must draw identical samples) and handy for tests.
        self._ctrl = None
        self._seed = seed
        self._resets = 0
        self.resurrected_total = 0
        # id(fut) -> times this request was internally resubmitted
        # after a failure reset (bounded by RESURRECT_MAX; entries
        # cleared when the request resolves).
        self._resurrect_counts: Dict[int, int] = {}
        self._pending_cancels: List[Any] = []
        # Flight recorder (observe/flight.py): the hot loop's only
        # telemetry — dispatch/collect/admit/finish events as
        # preallocated ring tuples (no sqlite, no spans, no device
        # sync). /debug/flight dumps it; failure resets snapshot it
        # into the journal. Followers record into their own ring at
        # the mirrored op-stream points.
        self.flight = flight_lib.FlightRecorder()
        # Request-timing sidecars, keyed by id(future) so the item
        # tuple (and the multi-host admit protocol built on its shape)
        # stays untouched. _submit_meta: (monotonic_ns, wall,
        # normalized class) captured at enqueue; _timings: the
        # finished request's decomposition,
        # picked up by the HTTP handlers (engine.pop_timing) which
        # record the engine spans OFF the batch loop. Both bounded:
        # entries whose handler never collects them (failed or
        # abandoned requests) age out by insertion order.
        import collections as _collections
        self._submit_meta: Dict[int, tuple] = {}
        self._timings: '_collections.OrderedDict' = \
            _collections.OrderedDict()
        # Dispatched-but-uncollected fused steps (oldest first). The
        # leader keeps at most one outstanding across its broadcast
        # points; followers mirror via the ('step',)/('collect',) ops.
        self._inflight: List['_InFlightStep'] = []
        # Block-paged KV cache (models/paging.py). Instance attributes
        # (not module reads) so tests can override before warmup.
        self.paged = PAGED
        self.page_size = PAGE_SIZE
        self.prefill_chunk = PREFILL_CHUNK
        self.kv_pages = KV_PAGES
        # Attention backend for the paged hot path — an instance
        # attribute (tests override it before warmup), parsed/validated
        # by THE one env reader (garbage fails engine construction
        # loudly, never silently serves the slow baseline).
        from skypilot_tpu.ops import paged_attention as pa_lib
        self.attn_backend = pa_lib.backend_from_env()
        # KV memory hierarchy — instance attributes (tests override
        # before warmup) validated here so a bad combination fails
        # engine construction loudly, never serves silently degraded.
        self.kv_quant = KV_QUANT
        self.kv_idle_spill_s = KV_IDLE_SPILL_S
        self.kv_host_mb = KV_HOST_MB
        if self.kv_quant != 'none':
            if not self.paged:
                raise ValueError(
                    'SKYTPU_ENGINE_KV_QUANT needs paged mode '
                    '(SKYTPU_ENGINE_PAGED=1): the contiguous layout '
                    'has no quantized pool variant')
            if self.attn_backend == 'gather':
                # The gather baseline materializes the raw pool into a
                # contiguous view — int8 codes without their scales
                # would silently attend garbage. The fused/pallas
                # paths dequantize inside the step programs.
                raise ValueError(
                    'SKYTPU_ENGINE_KV_QUANT=int8 is incompatible with '
                    'SKYTPU_ENGINE_ATTN=gather (the view baseline '
                    'cannot carry the scale sidecars); use fused')
        # Host spill tier (serve/host_store.py), (re)built by
        # _reset_device_state — a poisoned-state reset distrusts the
        # parked blobs along with everything else.
        self.host_store = None
        if self.paged:
            if (self.page_size & (self.page_size - 1) or
                    PREFIX_MIN_TOKENS % self.page_size):
                raise ValueError(
                    f'SKYTPU_ENGINE_PAGE_SIZE must be a power of two '
                    f'dividing {PREFIX_MIN_TOKENS}, got '
                    f'{self.page_size}')
            if (self.prefill_chunk < 16 or
                    self.prefill_chunk & (self.prefill_chunk - 1)):
                raise ValueError(
                    f'SKYTPU_ENGINE_PREFILL_CHUNK must be a power of '
                    f'two >= 16, got {self.prefill_chunk}')
        # Host-side paging state, (re)built by _reset_device_state:
        # the refcounted free-list allocator, the numpy mirror of the
        # device page table, and the shared-prefix page store. The
        # device table is refreshed lazily (_table_dirty) at the next
        # drained device call after any host-side alloc/free.
        self.alloc = None
        self._table_np = None
        self._table_dirty = False
        # Page-gated admission: items popped from the queue that could
        # not reserve pages wait here (FIFO — later arrivals never jump
        # a held request, or a flood of shorts would starve a long
        # prompt forever). _hold_waited: items already counted in the
        # kv_page_alloc_total{outcome="wait"} counter (once per
        # request, not once per retry round).
        self._hold: List[tuple] = []
        self._hold_waited: set = set()
        # Chunked-prefill scheduler state: slots mid-prefill round-robin
        # one chunk per drained round (the interleave that lets short
        # requests stream while a long prompt fills).
        self._chunk_rr = 0
        # Disaggregated prefill/decode serving (serve/disagg): request
        # markers keyed by id(future) — the item TUPLE (and the
        # multi-host admit protocol built on its shape) stays
        # untouched. {'mode': 'export'} turns an admission into a
        # prefill-only request (KV pages exported, no decode);
        # {'mode': 'adopt', 'meta':…, 'arrays':…} admits a handed-off
        # request by scattering received pages instead of prefilling.
        # Marks pop on successful admission; a resurrected item keeps
        # its mark (same future). Bounded like _submit_meta.
        self._disagg_marks: Dict[int, Dict[str, Any]] = {}
        # Export blobs stashed at admission, popped once by the
        # /disagg/prefill handler that owns the future.
        self._exports: '_collections.OrderedDict' = \
            _collections.OrderedDict()
        # Decode-side handoff plumbing, started by build_app when
        # handoff_port is set: the framed-TCP receiver and the staged
        # (meta, arrays) store. Host memory only — device pages are
        # reserved at adoption time, through the normal allocator.
        self.role = knobs.get_enum('SKYTPU_ENGINE_ROLE')
        self.handoff_port: Optional[int] = None
        self.handoff_store = None
        self._handoff_receiver = None

    def _setup_mesh(self, mesh, quantize: Optional[str]) -> None:
        """Place params on a named mesh with the family's sharding rules;
        GSPMD then inserts TP collectives inside the step/admit jits (the
        cache is sharded by _reset_device_state: batch over data/fsdp,
        kv-heads over tensor — the same layout training uses, so decode
        collectives ride ICI exactly like the training step's).

        Every serving family shards: dense/GQA and MoE through the
        training rule table; MLA/DeepSeek (heads over 'tensor', the
        shared latent replicated — models/mla.py param_specs) so
        deepseek-v2/kimi-k2-class geometries serve under --mesh like the
        reference's 8-chip TP vLLM replicas do
        (reference llm/deepseek-r1/README.md, examples/tpu/v6e/README.md:
        119-127); int8 QuantizedWeight trees shard too (the int8 tensor
        and its per-channel scale take the fp weight's spec — reference
        replicas quantize AND shard, vLLM defaults)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from skypilot_tpu.models import module_for
        from skypilot_tpu.models.decode import QuantizedWeight
        from skypilot_tpu.parallel import MeshSpec, build_mesh
        from skypilot_tpu.parallel import sharding as sharding_lib
        if isinstance(mesh, str):
            mesh = parse_mesh_arg(mesh)
        if isinstance(mesh, MeshSpec):
            mesh = build_mesh(mesh)
        self.mesh = mesh
        shape = dict(mesh.shape)
        mod = module_for(self.cfg)
        mod.validate_divisibility(self.cfg, shape)
        dp = shape.get('data', 1) * shape.get('fsdp', 1)
        if MAX_BATCH % dp != 0:
            raise ValueError(f'engine batch {MAX_BATCH} not divisible by '
                             f'data*fsdp={dp} (set SKYTPU_ENGINE_MAX_BATCH '
                             f'to a multiple)')
        rules = sharding_lib.Rules()
        specs = mod.param_specs(self.cfg, rules)

        def leaf_sharding(param, spec):
            if isinstance(param, QuantizedWeight):
                # The int8 tensor takes the fp weight's spec verbatim;
                # the per-channel scale broadcasts over the reduced
                # (second-to-last) dim, so any mesh axis on a size-1
                # scale dim is dropped — per-shard dequant then needs no
                # collective.
                q_sh = NamedSharding(mesh, spec)
                entries = list(spec) + [None] * (param.q.ndim - len(spec))
                s_spec = PartitionSpec(*[
                    e if param.scale.shape[i] > 1 else None
                    for i, e in enumerate(entries)])
                return QuantizedWeight(q=q_sh,
                                       scale=NamedSharding(mesh, s_spec))
            return NamedSharding(mesh, spec)

        self.params = jax.device_put(
            self.params,
            jax.tree.map(leaf_sharding, self.params, specs,
                         is_leaf=lambda x: isinstance(x, QuantizedWeight)))
        logger.info(f'Serving on mesh {shape} '
                    f'({mesh.devices.size} devices)'
                    + (' [int8 weights sharded]' if quantize else '') + '.')

    def start(self) -> None:
        """Bind the batcher to the current event loop (call at server
        startup)."""
        self._queue = asyncio.Queue(maxsize=MAX_QUEUE)
        asyncio.create_task(self.batch_loop())

    # -- observability -----------------------------------------------------
    def queue_depth(self) -> int:
        # Held items (popped, waiting on free KV pages) are still
        # queued work — the LB's least-load policy must see them.
        return ((self._queue.qsize() if self._queue is not None else 0)
                + len(self._hold))

    def in_flight(self) -> int:
        return sum(1 for s in getattr(self, 'slots', []) if s is not None)

    def cache_family(self) -> str:
        """'paged_kv' (dense/GQA/MoE) or 'paged_latent' (MLA) — the
        handoff-meta family tag a decode replica validates against its
        own pool (paged mode only)."""
        from skypilot_tpu.models import mla
        return ('paged_latent' if isinstance(self.cfg, mla.MLAConfig)
                else 'paged_kv')

    # -- device state ------------------------------------------------------
    def _reset_device_state(self, reason: Optional[str] = None) -> None:
        """(Re)build the slot pool + cache. Called at startup AND after a
        step/admit execution failure: the failed call was DONATED the old
        cache buffer (jax invalidates it even on error), so continuing
        with the old self.cache would poison every later request while
        /health still says ok.

        Every reset snapshots the flight ring into the event journal
        first (kind=flight_snapshot): an engine failure ships the hot
        loop's last ~64k events with it, post-mortem-ready, whether or
        not anyone scraped /debug/flight in time. The startup call is a
        no-op snapshot (empty ring)."""
        import jax
        import numpy as np
        # Snapshot BEFORE the reset marker: the journal gets the hot
        # loop's history as it stood at failure (an empty ring — the
        # startup call — writes nothing), then the marker opens the new
        # buffer generation's era in the ring.
        flight_lib.snapshot_to_journal(
            self.flight, reason=reason or 'device state reset',
            entity=f'engine/{self.model_name}')
        self.flight.record(flight_lib.RESET, 0, self._resets)
        if self.paged:
            # Page pool instead of contiguous rows: MAXP table entries
            # cover max_len positions; the default pool matches the
            # contiguous layout's worst case (every slot full) plus
            # prefix-cache headroom, so default capacity never
            # regresses — SKYTPU_ENGINE_KV_PAGES shrinks it to
            # oversubscribe.
            from skypilot_tpu.models import paging
            psz = self.page_size
            self._max_pages = paging.pages_for(self.max_len, psz)
            n_pages = self.kv_pages
            if n_pages <= 0:
                n_pages = (MAX_BATCH + min(PREFIX_CACHE_ENTRIES,
                                           MAX_BATCH)) \
                    * self._max_pages + 1
            if self.mesh is not None:
                # The page axis shards over data/fsdp: keep it
                # divisible (pages are fungible; a few extra are free).
                shape = dict(self.mesh.shape)
                dp = shape.get('data', 1) * shape.get('fsdp', 1)
                n_pages += (-n_pages) % dp
            if n_pages < self._max_pages + 1:
                raise ValueError(
                    f'SKYTPU_ENGINE_KV_PAGES={n_pages} cannot hold one '
                    f'full-length request ({self._max_pages} pages + '
                    f'trash)')
            self.n_pages = n_pages
            self.alloc = paging.PageAllocator(n_pages)
            self._table_np = np.zeros((MAX_BATCH, self._max_pages),
                                      np.int32)
            self._table_dirty = True
            self.cache = self._decode.init_page_pool(
                self.cfg, n_pages, psz, MAX_BATCH, self._max_pages,
                quant=self.kv_quant)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                self.cache = jax.device_put(
                    self.cache,
                    jax.tree.map(
                        lambda s: NamedSharding(self.mesh, s),
                        self._decode.paged_pspecs(self.cfg,
                                                  quant=self.kv_quant),
                        is_leaf=lambda x: isinstance(
                            x, PartitionSpec)))
            # Host spill tier: rebuilt fresh (not cleared) each reset —
            # a poisoned-state reset must distrust the parked blobs
            # exactly like the prefix store's device snapshots.
            self.host_store = None
            if self.kv_host_mb > 0:
                from skypilot_tpu.serve.host_store import HostPageStore
                self.host_store = HostPageStore(self.kv_host_mb)
            _M_KV_QUANTIZED.set(
                n_pages - 1 if self.kv_quant == 'int8' else 0)
            _M_KV_SPILLED.set(0)
        else:
            self.cache = self._decode.init_cache(self.cfg, MAX_BATCH,
                                                 self.max_len)
            if self.mesh is not None:
                # Each decode family owns its cache layout AND its mesh
                # layout: cache_pspecs lives next to init_cache
                # (models/decode.py for KVCache, models/mla.py for
                # LatentCache), so a new serving family adds one
                # function there instead of a branch here.
                from jax.sharding import NamedSharding, PartitionSpec
                self.cache = jax.device_put(
                    self.cache,
                    jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                                 self._decode.cache_pspecs(self.cfg),
                                 is_leaf=lambda x: isinstance(
                                     x, PartitionSpec)))
        # Shape-derived cache-traffic proxy inputs (the
        # skytpu_engine_cache_bytes_* counters; no device sync —
        # pure host arithmetic on static shapes). token bytes = one
        # position's cache footprint across layers; view bytes = the
        # full [B, max_len] span's.
        pools = ([self.cache.k, self.cache.v]
                 if hasattr(self.cache, 'k')
                 else [self.cache.c_kv, self.cache.k_rope])
        self._tok_bytes = sum(
            a.shape[0] * int(np.prod(a.shape[3:])) * a.dtype.itemsize
            for a in pools)
        self._view_bytes = MAX_BATCH * self.max_len * self._tok_bytes
        base = (self._seed if self._seed is not None
                else int(time.time_ns()) % (2**31))
        self.rng = jax.random.PRNGKey((base + self._resets) % (2**31))
        self._resets += 1
        # Rebuilding device state invalidates any in-flight lookahead
        # call (its donated inputs/outputs belong to the poisoned
        # buffer generation): drop the handles so a later collect can
        # never consume stale outputs into the fresh pool.
        self._inflight.clear()
        self.slots: List[Optional[Dict[str, Any]]] = [None] * MAX_BATCH
        # Per-slot previous token, DEVICE-resident (carried through the
        # step jits so a lookahead step can be dispatched without any
        # host sync) — self.last is its host MIRROR, maintained at
        # collect/admit time for stop/length accounting and the
        # speculative draft feed.
        import jax.numpy as _jnp
        self.last_dev = _jnp.zeros(MAX_BATCH, _jnp.int32)
        self.last = np.zeros(MAX_BATCH, np.int32)
        self.temp = np.zeros(MAX_BATCH, np.float32)
        self.topk = np.zeros(MAX_BATCH, np.int32)
        self.topp = np.zeros(MAX_BATCH, np.float32)
        self.pres = np.zeros(MAX_BATCH, np.float32)
        self.freq = np.zeros(MAX_BATCH, np.float32)
        # Generated-token counts per slot (OpenAI presence/frequency
        # penalties); [B, V] int32 rides the step jits like the cache.
        import jax.numpy as jnp
        self.counts = jnp.zeros((MAX_BATCH, self.cfg.vocab_size),
                                jnp.int32)
        # Prefix snapshots live OUTSIDE the donated cache buffer (their
        # slices own their storage), so they survive resets — but wipe
        # them anyway: after a poisoned-state reset nothing device-side
        # should be trusted.
        import collections
        self._prefix_store: 'collections.OrderedDict' = \
            collections.OrderedDict()
        # key -> last time.monotonic() the entry was captured or hit;
        # the idle-spill sweep's clock (leader-private — followers
        # spill via the explicit ('spill', key, fp) op).
        self._prefix_last_used: Dict[tuple, float] = {}
        self.prefix_hits = 0
        self._kv_sessions_peak = 0
        _M_KV_SESSIONS_PEAK.set(0)

    # -- block-paged KV cache: host-side state (models/paging.py) -------
    @staticmethod
    def _row_active(s: Optional[Dict[str, Any]]) -> bool:
        """A slot that should be stepped: occupied, unfinished, and not
        mid-chunked-prefill (a prefilling row holds pages and a slot
        but produces no tokens until its final chunk samples)."""
        return (s is not None and s['finish'] is None and
                s.get('prefill') is None)

    def _count_cache_traffic(self, n_attend: int, n_tokens: int) -> None:
        """Account one hot-path device call's KV-cache traffic into the
        skytpu_engine_cache_bytes_* counters — a SHAPE-DERIVED proxy
        (host ints only, never a device sync). ``n_attend`` = times the
        call attends the full [B, max_len] span (k for a fused k-step,
        1 for a K-wide verify), ``n_tokens`` = new positions written
        per row. The gather baseline additionally pays the view
        materialization (pool read + view write) and the scatter-back
        (view read + pool write) — the ~2 extra full-cache traversals
        per call the fused path eliminates."""
        read = n_attend * self._view_bytes
        written = n_tokens * MAX_BATCH * self._tok_bytes
        if self.paged and self.attn_backend == 'gather':
            read += self._view_bytes + written
            written += self._view_bytes
        _M_CACHE_READ.inc(read)
        _M_CACHE_WRITTEN.inc(written)

    def _refresh_table(self) -> None:
        """Push the host page-table mirror to the device cache if any
        alloc/free dirtied it since the last device call. The table is
        runtime DATA to every jit ([B, max_pages] int32 — page COUNT is
        data, not shape), so this replaces one tiny leaf of the cache
        pytree and can never recompile anything."""
        if not self.paged or not self._table_dirty:
            return
        import dataclasses as _dc
        # COPY, not asarray: on CPU jax an asarray of a numpy array can
        # alias its buffer zero-copy, and the step/extend jits DONATE
        # the cache pytree — XLA would then scribble output data over
        # the host mirror itself (observed: token garbage in the table
        # → phantom page ids → double frees).
        table = self._jnp.array(self._table_np, copy=True)
        self.cache = _dc.replace(self.cache, table=table)
        self._table_dirty = False

    def _pages_needed(self, item) -> int:
        """Worst-case pages a request must reserve: bucketed prompt +
        max_new + speculative verify headroom (verify_step writes
        [length, length+K) on every row), capped at the table's
        coverage. Conservative w.r.t. prefix sharing — a hit then needs
        fewer OWN pages, never more."""
        from skypilot_tpu.models import paging
        tokens, max_new = item[0], item[1]
        spec = self.spec_k if self.spec_k > 0 else 0
        want = min(_bucket(len(tokens)) + max_new + spec,
                   self._max_pages * self.page_size)
        return paging.pages_for(want, self.page_size)

    def _evictable_pages(self) -> int:
        """Pages the prefix store would return to the free list if
        evicted now (only entries no live request still shares)."""
        if not self.paged:
            return 0
        n = 0
        for pids in self._prefix_store.values():
            n += sum(1 for pid in pids if self.alloc.refcount(pid) == 1)
        return n

    def _alloc_pages(self, n: int) -> List[int]:
        """Reserve n pages, evicting prefix-store LRU entries as needed
        (a cached prefix is worth less than an admitted request).
        Deterministic — multi-host followers replaying the same admit
        op from the same mirrored state make the identical evictions
        and draw the identical page ids (FIFO free list); the admit op
        additionally carries the leader's allocator fingerprint so any
        drift fails loudly instead of corrupting KV."""
        spills = []
        while not self.alloc.can_fit(n) and self._prefix_store:
            key, pids = self._prefix_store.popitem(last=False)
            # Pressure spill: with the host tier on, the evicted
            # entry's contents park host-side instead of dropping —
            # same page ids freed either way, so follower replay of
            # this deterministic loop stays in lockstep.
            info = self._spill_entry(key, pids)
            if info is not None:
                spills.append(info)
        self._journal_spill(spills)
        pids = self.alloc.alloc(n)
        _M_PAGE_ALLOC.inc(outcome='ok')
        return pids

    def _reserve_slot_pages(self, slot: int, pids: List[int]) -> None:
        """Point slot's table row at exactly `pids` (zeroing the tail)
        and mark the device table stale — the ONE place the
        table-mirror/allocator contract is written (see
        _release_slot_pages for the inverse)."""
        self._table_np[slot, :len(pids)] = pids
        self._table_np[slot, len(pids):] = 0
        self._table_dirty = True

    def _release_slot_pages(self, i: int) -> None:
        """Return slot i's pages at finish time (publish — the mirrored
        reap point, directly after every collect), NOT at slot reuse:
        a finished row's memory is admissible again at the very next
        drained round. Shared prefix pages just drop one ref; they free
        when their last holder (store entry or sharer) lets go."""
        if not self.paged:
            return
        pids = [int(p) for p in self._table_np[i] if p]
        if pids:
            self.alloc.unref_all(pids)
            self._table_np[i] = 0
            self._table_dirty = True

    def _drop_all_slots(self) -> None:
        """Warmup-only slot wipe that returns pages too (the plain
        `slots = [None]*B` wipe would leak every warmup admission's
        pages into the allocator forever)."""
        for i in range(MAX_BATCH):
            if self.slots[i] is not None:
                self._release_slot_pages(i)
                self.slots[i] = None

    def _clear_prefix_store(self) -> None:
        """Empty the prefix store, returning its page refs in paged
        mode (reset paths rebuild the allocator first and use plain
        .clear() — stale ids must not be unref'd into a fresh pool)."""
        if self.paged:
            while self._prefix_store:
                _, pids = self._prefix_store.popitem(last=False)
                self.alloc.unref_all(pids)
        else:
            self._prefix_store.clear()
        self._prefix_last_used.clear()
        if self.host_store is not None:
            self.host_store.clear()

    # -- KV memory hierarchy: host-RAM spill tier (host_store.py) -------
    def _spill_entry(self, key, pids) -> Optional[Tuple[int, bool]]:
        """Spill one prefix-store entry the caller already popped:
        export its pages to the host tier (when on), then free the
        device refs. Prefix pages are read-only after capture, so the
        exported contents are frozen even while a live request still
        shares them. Runs at drained points only (admission paths and
        the idle sweep). ``kv.spill`` is the chaos window between
        'entry chosen' and 'pages parked' (docs/ROBUSTNESS.md).
        Returns (pages, stored) for the caller's _journal_spill batch —
        spill runs journal once, never per entry inside the loop."""
        if self.host_store is not None:
            import jax
            import numpy as np
            if failpoints_lib.ACTIVE and self.warm:
                failpoints_lib.fire('kv.spill')
            t0 = time.perf_counter()
            out = self._spill_jit(len(pids))(
                self.cache, self._jnp.asarray(pids, self._jnp.int32))
            arrays = {name: np.asarray(jax.device_get(a))
                      for name, a in out.items()}
            ok = self.host_store.put(key, arrays, n_pages=len(pids))
            _M_SPILL_SECONDS.observe(time.perf_counter() - t0)
            spilled = (len(pids), bool(ok))
        else:
            spilled = None
        self.alloc.unref_all(pids)
        self._prefix_last_used.pop(key, None)
        return spilled

    def _journal_spill(self, spills: List[Tuple[int, bool]]) -> None:
        """One kv_spill journal event summarizing a whole spill run
        (a pressure eviction, an LRU overflow, or one idle sweep).
        The eviction loops accumulate (pages, stored) tuples and this
        straight-line point writes — sqlite INSERTs stay off the
        per-iteration path (span-discipline)."""
        if not spills or self.host_store is None:
            return
        from skypilot_tpu.observe import journal as journal_lib
        journal_lib.record_event(
            'kv_spill', entity=f'engine/{self.model_name}',
            data={'entries': len(spills),
                  'pages': sum(p for p, _ in spills),
                  'stored': sum(1 for _, ok in spills if ok),
                  'host_pages': self.host_store.pages_spilled()})

    def _spill_key(self, key) -> Optional[Tuple[int, bool]]:
        """Spill the named prefix-store entry — the replayable half of
        the idle sweep (multi-host followers run this for each
        ('spill', key, fp) op; clocks are leader-private). Returns the
        (pages, stored) tuple for the caller's _journal_spill batch."""
        pids = self._prefix_store.pop(key, None)
        if pids is None:
            return None
        return self._spill_entry(key, pids)

    def _wake_prefix_entry(self, key) -> None:
        """Re-admit a spilled entry to the device tier: fresh pages
        from the allocator, blob contents scattered back in, entry
        restored to the prefix store (newest — the caller is about to
        hit it). One copy lives at a time: waking pops the host blob.
        Deterministic given mirrored host stores, so followers replay
        it inside the same admit op the leader ran it in. ``kv.wake``
        fires before the device work — an injected failure propagates
        out of the admission path into _fail_all, which resurrects the
        interrupted request (docs/ROBUSTNESS.md)."""
        jnp = self._jnp
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('kv.wake')
        t0 = time.perf_counter()
        arrays = self.host_store.pop(key)
        if arrays is None:       # raced an eviction; caller re-checks
            return
        n = len(key) // self.page_size
        pids = self._alloc_pages(n)
        # Device-side dict built once up front: its key set is fixed
        # by the pool family, so the trace cache keys stably per n.
        device = {name: jnp.asarray(a) for name, a in arrays.items()}
        self.cache = self._wake_jit(n)(
            self.cache, device, jnp.asarray(pids, jnp.int32))
        self._prefix_store[key] = pids
        self._prefix_last_used[key] = time.monotonic()
        _M_WAKE_SECONDS.observe(time.perf_counter() - t0)
        from skypilot_tpu.observe import journal as journal_lib
        journal_lib.record_event(
            'kv_wake', entity=f'engine/{self.model_name}',
            data={'pages': n,
                  'host_pages': self.host_store.pages_spilled()})

    def _note_kv_residency(self) -> None:
        """High-water mark of sessions resident in the KV hierarchy
        (device prefix entries + host-tier entries). Called wherever a
        new entry lands in the device store; the gauge is what the
        fleet scrape sums into the scorecard's
        concurrent_sessions_peak column."""
        resident = len(self._prefix_store)
        if self.host_store is not None:
            resident += len(self.host_store)
        if resident > self._kv_sessions_peak:
            self._kv_sessions_peak = resident
            _M_KV_SESSIONS_PEAK.set(resident)

    def _sweep_due(self) -> bool:
        """Cheap event-loop precheck for the idle sweep: True when at
        least one prefix entry has idled past the spill threshold (the
        batch loop pays the off-loop thread hop only then)."""
        if (not self.paged or self.host_store is None or
                self.kv_idle_spill_s <= 0 or not self._prefix_store):
            return False
        now = time.monotonic()
        return any(now - ts >= self.kv_idle_spill_s
                   for ts in self._prefix_last_used.values())

    def _sweep_idle_prefixes(self) -> None:
        """Leader-side idle sweep (batch-loop drained points): spill
        prefix entries untouched for SKYTPU_ENGINE_KV_IDLE_SPILL_S.
        Clock reads are leader-private, so each spill is broadcast as
        an explicit ('spill', key, fp) op before execution — followers
        replay _spill_key at the same op-stream point."""
        if (not self.paged or self.host_store is None or
                self.kv_idle_spill_s <= 0 or not self._prefix_store):
            return
        now = time.monotonic()
        spills = []
        for key in list(self._prefix_store):
            ts = self._prefix_last_used.get(key)
            if ts is not None and now - ts >= self.kv_idle_spill_s:
                self._bcast(('spill', key, self._page_fp()))
                info = self._spill_key(key)
                if info is not None:
                    spills.append(info)
        self._journal_spill(spills)

    def _page_fp(self) -> Optional[tuple]:
        """Allocator fingerprint shipped with admit/chunkstart ops —
        the multi-host cross-check that page-alloc replay stayed in
        lockstep."""
        if not self.paged or self.alloc is None:
            return None
        return self.alloc.fingerprint()

    def _check_page_fp(self, fp: Optional[tuple]) -> None:
        """Follower side: compare the leader's allocator fingerprint
        with ours BEFORE replaying the op. A mismatch means page
        assignments have diverged — KV corruption, not recoverable by
        retrying — so raise (the follower loop treats a failed op as
        divergence and exits the gang loudly)."""
        if fp is None or not self.paged:
            return
        mine = self._page_fp()
        if mine != fp:
            raise RuntimeError(
                f'page allocator diverged from leader: leader {fp}, '
                f'local {mine}')

    def _ensure_state(self) -> None:
        """Jitted step/admit closures, built once (after any test-time cfg
        overrides — rebuilding them would recompile)."""
        if self._state_ready:
            return
        import functools
        import jax
        jnp = self._jnp
        cfg, dec, max_len = self.cfg, self._decode, self.max_len
        from skypilot_tpu.models import decode as decode_lib

        self._reset_device_state()

        def top5(logits):
            """Top-K alternative logprobs of the UNPENALIZED model
            distribution (decode.top_k_logprobs): [.., V] fp32 logits →
            (values [.., K] fp32, ids [.., K] i32)."""
            return decode_lib.top_k_logprobs(logits, TOP_LOGPROBS_K)

        if self.mesh is not None:
            # Host-read outputs (tokens/logprobs/top-K) replicate over
            # the mesh: on a MULTI-HOST mesh a partially-sharded output
            # is not fully addressable, so device_get would fail —
            # and every process must read identical values to keep the
            # mirrored host state in lockstep. Tiny arrays; free.
            from jax.sharding import NamedSharding, PartitionSpec
            _repl_sh = NamedSharding(self.mesh, PartitionSpec())

            def repl(x):
                return jax.lax.with_sharding_constraint(x, _repl_sh)
        else:
            def repl(x):
                return x

        paged = self.paged
        attn = self.attn_backend
        # The fused in-place paged path is the default; 'gather' keeps
        # yesterday's gather_view → contiguous math → scatter programs
        # compiled as the regression baseline (their jits carry the
        # *_gather naming skylint's paged-view-materialization checker
        # sanctions).
        fused_paged = paged and attn != 'gather'
        from skypilot_tpu.models import paging as paging_lib
        if paged:
            # Contiguous (PAGED=0) replicas don't publish the gauge:
            # no paged attention path is serving, and a 'fused' series
            # there would mislabel the replica on backend dashboards.
            _set_attn_backend_gauge(attn)

        def step_k(k, use_pen, want_tops):
            """k decode steps in ONE device call (host-loop dispatch cost
            amortized when no request is waiting to join). Compiled per
            (k, penalties-active, want_tops) — the common un-penalized
            path never pays the [B,V] counts carry/scatter or the
            penalty math, and the [k,B,K] top-k logprob tensors are
            computed (and transferred) only when some active slot asked
            for logprobs.

            `last` [B] i32 is a DEVICE-RESIDENT carry (in and out):
            dispatching step N+1 needs only step N's output arrays, so
            the batch loop can keep a call in flight with no host
            sync between steps.

            Paged mode (fused, the default): the page pool ITSELF is
            the scan carry — each step's attention indexes
            pool[table[b, p // psz], p % psz] per layer inside the
            computation and writes its token straight into the pages
            (inactive rows' writes route to the trash page so a freed
            page can never be corrupted by a stale step). No
            contiguous view is materialized and nothing scatters back:
            the ~2/k extra full-cache traversals the gather baseline
            pays per token are gone, and the token stream is
            bit-identical by construction (ops/paged_attention.py)."""

            def sample(logits, last_t, counts_t, rng_t, temp, topk,
                       topp, pres, freq, active):
                rng_t, sub = jax.random.split(rng_t)
                nxt = decode_lib.select_token_per_row(
                    logits, temp, topk, topp, sub,
                    counts=counts_t if use_pen else None,
                    presence=pres if use_pen else None,
                    frequency=freq if use_pen else None)
                nxt = jnp.where(active, nxt, last_t)
                # logprobs report the UNPENALIZED model distribution.
                lp = decode_lib.chosen_logprob(logits, nxt)
                if use_pen:
                    rows = jnp.arange(nxt.shape[0])
                    counts_t = counts_t.at[rows, nxt].add(
                        active.astype(jnp.int32))
                return nxt, lp, counts_t, rng_t

            def finish(outs, last_f, cache_f, counts_f, rng_f):
                if want_tops:
                    toks, lps, tis, tvs = outs
                    return (repl(toks), repl(lps), repl(tis), repl(tvs),
                            repl(last_f), cache_f, counts_f, rng_f)
                toks, lps = outs
                return (repl(toks), repl(lps), repl(last_f), cache_f,
                        counts_f, rng_f)

            if fused_paged:
                @functools.partial(jax.jit, donate_argnums=(1, 2))
                def run(params, cache, counts, last, temp, topk, topp,
                        pres, freq, rng, active):
                    def body(carry, _):
                        last_t, cache_t, counts_t, rng_t = carry
                        logits, cache_t = dec.paged_decode_step(
                            params, last_t, cache_t, cfg,
                            max_len=max_len, active=active, attn=attn)
                        nxt, lp, counts_t, rng_t = sample(
                            logits, last_t, counts_t, rng_t, temp,
                            topk, topp, pres, freq, active)
                        if want_tops:
                            tv, ti = top5(logits)
                            return ((nxt, cache_t, counts_t, rng_t),
                                    (nxt, lp, ti, tv))
                        return ((nxt, cache_t, counts_t, rng_t),
                                (nxt, lp))
                    (last_f, cache_f, counts_f, rng_f), outs = \
                        jax.lax.scan(body, (last, cache, counts, rng),
                                     None, length=k)
                    return finish(outs, last_f, cache_f, counts_f,
                                  rng_f)
                return run

            @functools.partial(jax.jit, donate_argnums=(1, 2))
            def run_gather(params, cache, counts, last, temp, topk,
                           topp, pres, freq, rng, active):
                # Baseline formulation (SKYTPU_ENGINE_ATTN=gather, and
                # the contiguous PAGED=0 layout): materialize the
                # per-row view, run the contiguous step math, scatter
                # the k written positions back.
                if paged:
                    start = cache.length
                    view0 = paging_lib.gather_view(cache, max_len)
                else:
                    view0 = cache

                def body(carry, _):
                    last_t, cache_t, counts_t, rng_t = carry
                    logits, cache_t = dec.decode_step(params, last_t,
                                                      cache_t, cfg,
                                                      active=active)
                    nxt, lp, counts_t, rng_t = sample(
                        logits, last_t, counts_t, rng_t, temp, topk,
                        topp, pres, freq, active)
                    if want_tops:
                        tv, ti = top5(logits)
                        return ((nxt, cache_t, counts_t, rng_t),
                                (nxt, lp, ti, tv))
                    return (nxt, cache_t, counts_t, rng_t), (nxt, lp)
                (last_f, view_f, counts_f, rng_f), outs = \
                    jax.lax.scan(body, (last, view0, counts, rng), None,
                                 length=k)
                if paged:
                    cache_f = paging_lib.scatter_steps(cache, view_f,
                                                       start, k, active)
                else:
                    cache_f = view_f
                return finish(outs, last_f, cache_f, counts_f, rng_f)
            return run_gather

        self._step_k_jits = {}

        def step(params, cache, counts, last, temp, topk, topp, pres,
                 freq, rng, active, k=1, use_pen=False,
                 want_tops=False):
            key = (k, use_pen, want_tops)
            if key not in self._step_k_jits:
                self._step_k_jits[key] = step_k(k, use_pen, want_tops)
            return self._step_k_jits[key](params, cache, counts, last,
                                          temp, topk, topp, pres, freq,
                                          rng, active)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def admit(params, cache, last, tokens, lengths, slots, temps,
                  topks, topps, rng):
            """Prefill a GROUP of same-bucket prompts ([N, S]) into
            cache rows `slots` ([N], distinct) and sample each first
            token. One compile per (prompt bucket, group size) pair —
            a concurrency burst pays ONE prefill device call instead of
            N serial ones (the TTFT-dominant cost at high load). The
            device-resident `last` carry picks up each admitted row's
            first token here, so the next step needs no host upload.
            Paged mode scatters the S prefilled positions into the
            pages each row's table covers instead of writing whole
            contiguous rows."""
            logits, rows = dec.prefill(params, tokens, cfg, max_len,
                                       lengths=lengths)

            if paged:
                cache = paging_lib.scatter_prefill(
                    cache, rows, slots, tokens.shape[1], lengths)
            else:
                def write(big, group):
                    if big.ndim == 1:           # the per-row length vector
                        return big.at[slots].set(group)
                    return big.at[:, slots].set(group)

                cache = jax.tree.map(write, cache, rows)
            rng, sub = jax.random.split(rng)
            # prefill keeps the batch dim: logits [N, V].
            first = decode_lib.select_token_per_row(
                logits, temps, topks, topps, sub)
            first_lp = decode_lib.chosen_logprob(logits, first)
            tv, ti = top5(logits)
            last = last.at[slots].set(first)
            return (repl(first), repl(first_lp), repl(ti), repl(tv),
                    cache, repl(last), rng)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def admit_extend(params, cache, last, prefix_a, prefix_b,
                         tokens, length, slot, temp, topk, topp, rng):
            """Prefix-cache admit (single request): prefill only the
            SUFFIX over a stored prefix snapshot — (k, v) rows for the
            KVCache families (dense AND MoE: decode.prefill_extend
            routes the FFN through the expert path), (c_kv, k_rope)
            latents for MLA (mla.prefill_extend). One compile per
            (prefix length, suffix bucket) pair — prefixes are
            snapshotted at power-of-two lengths."""
            logits, row = dec.prefill_extend(
                params, tokens, cfg, max_len, prefix_a[:, None],
                prefix_b[:, None], lengths=length[None])

            def write(big, one):
                if big.ndim == 1:
                    return big.at[slot].set(one[0])
                return big.at[:, slot].set(one[:, 0])

            cache = jax.tree.map(write, cache, row)
            rng, sub = jax.random.split(rng)
            first = decode_lib.select_token_per_row(
                logits, temp[None], topk[None], topp[None], sub)
            first_lp = decode_lib.chosen_logprob(logits, first)
            tv, ti = top5(logits)
            last = last.at[slot].set(first[0])
            return (repl(first[0]), repl(first_lp[0]), repl(ti[0]),
                    repl(tv[0]), cache, repl(last), rng)

        def spec_outputs(logits, want_tops, cache2):
            """Shared verify post-processing: greedy token + its
            logprob per position (and the top-5 tensors in the
            want_tops variant)."""
            logits = logits.astype(jnp.float32)          # [B, K, V]
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            lp = (jnp.take_along_axis(logits, greedy[..., None],
                                      axis=-1)[..., 0] - lse)
            if not want_tops:
                return repl(greedy), repl(lp), cache2
            tv, ti = top5(logits)
            return repl(greedy), repl(lp), repl(ti), repl(tv), cache2

        # One K-wide speculative verify over the WHOLE slot pool:
        # fed [B, K] = per-row [last, d1..d_{K-1}]. Returns the
        # target's greedy token + logprob (and top-5 in the
        # want_tops=True variant) at every position; KV for the fed
        # tokens is written at each row's offset but `length` does NOT
        # advance — the host commits the accepted run (+1 correction)
        # by bumping length, so rollback is free (verify_step's
        # contract). ``active`` [B] bool: in paged mode inactive rows'
        # K-wide writes route to the trash page (their pages may be
        # freed); the contiguous path ignores it (stale writes land on
        # the frozen row the next admission overwrites, as before).
        if fused_paged:
            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(4,))
            def spec_verify(params, cache, fed, active, want_tops):
                # Fused: the K fed positions write straight into the
                # pool and attention indexes the pages in place — no
                # view, no scatter-back (ops/paged_attention.py).
                logits, cache2 = dec.paged_verify_step(
                    params, fed, cache, cfg, max_len=max_len,
                    active=active, attn=attn)
                return spec_outputs(logits, want_tops, cache2)
        else:
            @functools.partial(jax.jit, donate_argnums=(1,),
                               static_argnums=(4,))
            def spec_verify_gather(params, cache, fed, active,
                                   want_tops):
                # Baseline: gather the view, run the contiguous
                # verify, scatter exactly the [length, length+K)
                # positions back.
                if paged:
                    start = cache.length
                    view0 = paging_lib.gather_view(cache, max_len)
                else:
                    view0 = cache
                logits, view2 = dec.verify_step(params, fed, view0, cfg)
                if paged:
                    cache2 = paging_lib.scatter_steps(
                        cache, view2, start, fed.shape[1], active)
                else:
                    cache2 = view2
                return spec_outputs(logits, want_tops, cache2)
            spec_verify = spec_verify_gather

        def make_extend(p, s2, sample):
            """Paged extend program: prefill an [1, s2] suffix over the
            p tokens row `slot` already holds — the ONE program shape
            serving both prefix-cache hits (the prefix lives in SHARED
            pages; only table entries were copied) and chunked prefill
            (the prefix is the row's own earlier chunks). Compiled per
            (p, s2 bucket, sample); `sample` is False for non-final
            chunks, which also leave rng and the device `last`
            untouched so a chunked admission consumes exactly the same
            RNG stream as a contiguous one. Fused default: the prefix
            is gathered per layer from the (possibly shared) pages
            inside the attention and the suffix K/V lands straight in
            the row's own pages — no [L, 1, p] prefix materialization,
            no scatter_suffix."""

            def sample_tail(logits, cache2, last, slot, temp, topk,
                            topp, rng):
                rng, sub = jax.random.split(rng)
                first = decode_lib.select_token_per_row(
                    logits, temp[None], topk[None], topp[None], sub)
                first_lp = decode_lib.chosen_logprob(logits, first)
                tv, ti = top5(logits)
                last = last.at[slot].set(first[0])
                return (repl(first[0]), repl(first_lp[0]), repl(ti[0]),
                        repl(tv[0]), cache2, repl(last), rng)

            if fused_paged:
                @functools.partial(jax.jit, donate_argnums=(1,))
                def run(params, cache, last, tokens, length_s, slot,
                        temp, topk, topp, rng):
                    logits, cache2 = dec.paged_prefill_extend(
                        params, tokens, cache, cfg, slot=slot, p=p,
                        lengths=length_s, attn=attn)
                    if not sample:
                        return cache2
                    return sample_tail(logits, cache2, last, slot,
                                       temp, topk, topp, rng)
                return run

            @functools.partial(jax.jit, donate_argnums=(1,))
            def run_gather(params, cache, last, tokens, length_s, slot,
                           temp, topk, topp, rng):
                pa, pb = paging_lib.gather_prefix(cache, slot, p)
                # Intermediates sized p+s2, not engine max_len: a chunk
                # call materializes only the row it extends.
                logits, row = dec.prefill_extend(
                    params, tokens, cfg, p + s2, pa, pb,
                    lengths=length_s[None])
                cache2 = paging_lib.scatter_suffix(
                    cache, row, slot, p, s2, p + length_s)
                if not sample:
                    return cache2
                return sample_tail(logits, cache2, last, slot, temp,
                                   topk, topp, rng)
            return run_gather

        self._extend_jits: Dict[Tuple[int, int, bool], Any] = {}

        def extend_jit(p, s2, sample):
            key = (p, s2, bool(sample))
            if key not in self._extend_jits:
                self._extend_jits[key] = make_extend(*key)
            return self._extend_jits[key]

        self._extend_jit = extend_jit

        # --- disaggregated serving: page export / adopt programs ------
        # Export gathers the first p token positions of one row out of
        # the page pool as contiguous [L, 1, p, ...] arrays (the
        # gather_prefix order both families' prefill_extend consumes);
        # adopt is its exact inverse, scattering shipped rows into the
        # pages the ADOPTING allocator reserved and re-pinning the
        # device `last` carry to the prefill-sampled first token. Both
        # compile per prompt BUCKET (powers of two — the same grid as
        # admission), so a client-chosen prompt length can never mint
        # a fresh program shape.
        def make_export(p):
            @jax.jit
            def run(cache, slot):
                a, b = paging_lib.gather_prefix(cache, slot, p)
                return repl(a), repl(b)
            return run

        self._export_jits: Dict[int, Any] = {}

        def export_jit(p):
            if p not in self._export_jits:
                self._export_jits[p] = make_export(p)
            return self._export_jits[p]

        self._export_jit = export_jit

        def make_adopt(s):
            @functools.partial(jax.jit, donate_argnums=(0,))
            def run(cache, a, b, slot, length, last, first):
                cache2 = paging_lib.adopt_rows(cache, a, b, slot, s,
                                               length)
                return cache2, repl(last.at[slot].set(first))
            return run

        self._adopt_jits: Dict[int, Any] = {}

        def adopt_jit(s):
            if s not in self._adopt_jits:
                self._adopt_jits[s] = make_adopt(s)
            return self._adopt_jits[s]

        self._adopt_jit = adopt_jit

        # KV memory hierarchy (host_store.py): spill gathers one
        # prefix entry's pages (all pool fields + scale sidecars) for
        # device_get; wake scatters a decoded blob into freshly
        # allocated pages. Both compile per page COUNT — prefix
        # entries hold pow2-many tokens, so the shape set is
        # log2-bounded like the bucket grid. Wake donates the cache
        # (in-place page writes, nothing else references the buffer
        # at a drained point).
        def make_spill(n):
            @jax.jit
            def run(cache, page_ids):
                out = paging_lib.export_pages(cache, page_ids)
                return {name: repl(a) for name, a in out.items()}
            return run

        self._spill_jits: Dict[int, Any] = {}

        def spill_jit(n):
            if n not in self._spill_jits:
                self._spill_jits[n] = make_spill(n)
            return self._spill_jits[n]

        self._spill_jit = spill_jit

        def make_wake(n):
            @functools.partial(jax.jit, donate_argnums=(0,))
            def run(cache, arrays, page_ids):
                return paging_lib.import_pages(cache, arrays, page_ids)
            return run

        self._wake_jits: Dict[int, Any] = {}

        def wake_jit(n):
            if n not in self._wake_jits:
                self._wake_jits[n] = make_wake(n)
            return self._wake_jits[n]

        self._wake_jit = wake_jit

        @jax.jit
        def fix_last(last, mask, vals):
            """Re-sync the device-resident `last` with the host mirror
            on `mask` rows ([B] bool): a row that stops or length-caps
            mid-chunk (or a speculative commit) leaves the device carry
            at the chunk's final token while the host mirror holds the
            stop-point token — this pins the invariant device last ==
            host mirror for every occupied slot after each collect.
            One tiny [B] program, SPMD-safe on every mesh (an eager
            scatter would fail on a non-addressable multi-host
            array)."""
            return repl(jnp.where(mask, vals, last))

        self._step_jit = step
        self._admit_jit = admit
        self._admit_extend_jit = admit_extend
        self._spec_jit = spec_verify
        self._fix_last_jit = fix_last
        self._state_ready = True

    @staticmethod
    def _group_sizes() -> List[int]:
        sizes, s = [], 1
        while s <= MAX_BATCH:
            sizes.append(s)
            s *= 2
        return sizes

    def warmup(self, buckets: Optional[List[int]] = None) -> None:
        """Compile the FULL step-variant matrix — k ∈ {1,
        MAX_STEP_CHUNK} × use_pen × want_tops, the only programs
        _dispatch_step can ever select — plus the admit programs (every
        power-of-two GROUP SIZE per prompt bucket in `buckets`; default
        the 16-token bucket), the speculative-verify variants, and the
        last-resync program, all through the real code path; then free
        the warmup slots. /health flips only after. Step programs never
        recompile after this; admit compiles once per (prompt bucket,
        group size) — warm the buckets your traffic uses
        (--warm-buckets all) to guarantee no client request ever hits a
        fresh XLA compile."""
        self._ensure_state()
        jnp = self._jnp
        warm_item = (list(range(1, 9)), 4 * MAX_STEP_CHUNK + 8, 0.0,
                     None, None, 0.0, 0.0, (), False, None, None)
        self._admit(warm_item)
        for want_tops in (False, True):
            for use_pen in (False, True):
                self.pres[:] = 1.0 if use_pen else 0.0
                for k in (MAX_STEP_CHUNK, 1):
                    self._step_once(k_force=k,
                                    want_tops_force=want_tops)
        self.pres[:] = 0.0
        if self.spec_k > 0:
            # Compile BOTH speculative verify variants (garbage fed/KV
            # is fine: length does not advance, and every later step
            # overwrites its own slot before attending it; in paged
            # mode the all-False active mask routes the garbage writes
            # to the trash page).
            self._refresh_table()
            fed = jnp.zeros((MAX_BATCH, self.spec_k), jnp.int32)
            no_rows = jnp.zeros((MAX_BATCH,), bool)
            for want_tops in (False, True):
                *_, self.cache = self._spec_jit(self.params, self.cache,
                                                fed, no_rows, want_tops)
        # The device-last resync program (mid-chunk stop/length
        # finishes and speculative commits re-pin device == mirror).
        self.last_dev = self._fix_last_jit(
            self.last_dev, jnp.zeros((MAX_BATCH,), bool),
            jnp.asarray(self.last))
        self._drop_all_slots()

        def _fits_warm(item, size: int) -> bool:
            # An oversubscribed pool (small SKYTPU_ENGINE_KV_PAGES)
            # cannot hold every warm group; admission gating will never
            # select those group sizes either, so skip their compiles
            # (they fall back to on-demand if a smaller-reservation mix
            # ever selects them).
            return (not self.paged or
                    size * self._pages_needed(item) <=
                    self.alloc.free_count)

        for size in self._group_sizes()[1:]:
            if not _fits_warm(warm_item, size):
                continue
            self._admit_group([warm_item] * size)
            self._drop_all_slots()
        for b in (buckets or []):
            # b == max_len is unreachable by traffic (_check_len needs
            # bucket + max_new <= max_len with max_new >= 1) — don't pay
            # an XLA compile for it. Paged mode: prompts longer than
            # PREFILL_CHUNK admit via the chunked-extend programs (the
            # grid below), never the grouped-prefill ones — skip those
            # buckets here.
            if b <= 16 or b >= self.max_len:
                continue
            if self.paged and b > self.prefill_chunk:
                continue
            item_b = (list(range(1, b + 1)), 1, 0.0, None, None, 0.0,
                      0.0, (), False, None, None)
            for size in self._group_sizes():
                if not _fits_warm(item_b, size):
                    continue
                self._admit_group([item_b] * size)
                self._drop_all_slots()
        if self.paged and buckets:
            self._warm_chunk_grid()
        if self.paged and knobs.get_bool('SKYTPU_ENGINE_WARM_DISAGG'):
            # Disagg pools opt in (the serve controller / LocalStack
            # set this on pool replicas): compile the page
            # export/adopt programs for every warm bucket so a
            # handoff can never hit a fresh XLA compile at a drained
            # point mid-traffic.
            self._warm_disagg_grid(buckets or [])
        self.last[:] = 0
        self.last_dev = jnp.zeros(MAX_BATCH, jnp.int32)
        # Warmup admits must not pollute the served-token/step metrics
        # (/metrics feeds dashboards; phantom warmup tokens would skew
        # tokens-per-request forever — and warmup COMPILE times would
        # wreck the latency histograms) — nor the prefix store (fake
        # warmup prompts must never match real traffic).
        self.step_count = 0
        self.tokens_generated = 0
        self._clear_prefix_store()
        self.prefix_hits = 0
        for metric in _ENGINE_METRICS:
            metric.reset()
        _seed_counter_zeros()
        if self.paged:
            _set_attn_backend_gauge(self.attn_backend)
            # The metric wipe above also cleared the KV-hierarchy
            # gauges; re-seed them from live state (and zero the
            # sessions high-water mark — warmup's synthetic prefix
            # captures must not inflate the served peak).
            _M_KV_QUANTIZED.set(
                (self.alloc.n_pages - 1)
                if self.kv_quant == 'int8' else 0)
            _M_KV_SPILLED.set(
                self.host_store.pages_spilled()
                if self.host_store is not None else 0)
            self._kv_sessions_peak = 0
            _M_KV_SESSIONS_PEAK.set(0)
        # Warmup's synthetic admits/steps must not pollute the flight
        # ring (a /debug/flight dump should start at real traffic) or
        # leak timing sidecar entries for futures that never existed.
        self.flight.clear()
        self._submit_meta.clear()
        self._timings.clear()
        self.warm = True
        logger.info('Engine warm (step variants k x use_pen x want_tops '
                    '+ grouped-admit programs compiled; buckets: '
                    f'{sorted(set([16] + list(buckets or [])))}, '
                    f'group sizes: {self._group_sizes()}).')

    def _warm_disagg_grid(self, buckets: List[int]) -> None:
        """Compile the export (gather) and adopt (scatter) programs
        per prompt bucket through the REAL code path: reserve a warm
        slot's pages, adopt zero rows into them, export them back,
        release. Garbage KV is fine — the slot is never activated and
        its pages free right here."""
        import jax
        from skypilot_tpu.models import paging as paging_lib
        jnp = self._jnp
        pools = [self.cache.k, self.cache.v] \
            if hasattr(self.cache, 'k') \
            else [self.cache.c_kv, self.cache.k_rope]
        for b in sorted({_bucket(b) for b in buckets
                         if 16 <= b < self.max_len}):
            need = paging_lib.pages_for(b, self.page_size)
            if not self.alloc.can_fit(need):
                continue
            slot = self._free_slot()
            if slot is None:
                continue
            self._reserve_slot_pages(slot, self._alloc_pages(need))
            self._refresh_table()
            a = jnp.zeros((pools[0].shape[0], 1, b,
                           *pools[0].shape[3:]), pools[0].dtype)
            bb = jnp.zeros((pools[1].shape[0], 1, b,
                            *pools[1].shape[3:]), pools[1].dtype)
            self.cache, self.last_dev = self._adopt_jit(b)(
                self.cache, a, bb, jnp.int32(slot), jnp.int32(b),
                self.last_dev, jnp.int32(0))
            out = self._export_jit(b)(self.cache, jnp.int32(slot))
            jax.device_get(out)
            self._release_slot_pages(slot)

    def _warm_chunk_grid(self) -> None:
        """Compile every chunked-prefill extend program traffic can
        select (paged mode): non-final chunks at (p = i·C, s2 = C,
        sample=False) and final chunks at (p = i·C ≥ C, s2 = any tail
        bucket ≤ C, sample=True) — p is always a multiple of
        PREFILL_CHUNK because only prefix-MISS prompts chunk (hits ride
        the on-demand prefix-extend programs, as before). Executed with
        zero tokens against the zeroed table, so every write lands on
        the trash page and no pages are consumed."""
        jnp = self._jnp
        self._refresh_table()
        c = self.prefill_chunk
        zero = jnp.float32(0.0)
        zk = jnp.int32(0)
        slot0 = jnp.int32(0)

        def tails() -> List[int]:
            out, b = [], 16
            while b <= c:
                out.append(b)
                b *= 2
            return out

        p = 0
        while p + c < self.max_len:
            run = self._extend_jit(p, c, False)
            self.cache = run(self.params, self.cache, self.last_dev,
                             jnp.zeros((1, c), jnp.int32), jnp.int32(c),
                             slot0, zero, zk, zero, self.rng)
            p += c
        p = c
        while p < self.max_len:
            for b in tails():
                if p + b >= self.max_len:
                    continue
                run = self._extend_jit(p, b, True)
                (_f, _lp, _ti, _tv, self.cache, self.last_dev,
                 self.rng) = run(
                    self.params, self.cache, self.last_dev,
                    jnp.zeros((1, b), jnp.int32), jnp.int32(b), slot0,
                    zero, zk, zero, self.rng)
            p += c
        # The sampled warm calls touched slot 0's device `last`;
        # warmup re-zeros both carries right after this returns.

    def all_buckets(self) -> List[int]:
        """Every admissible prompt bucket (for --warm-buckets all) —
        strictly below max_len: a bucket-sized prompt still needs room
        for at least one generated token."""
        out, b = [], 16
        while b < self.max_len:
            out.append(b)
            b *= 2
        return out

    # -- continuous batching ----------------------------------------------
    def submit_nowait(self, tokens: List[int], max_new: int,
                      temperature: float, top_k: Optional[int],
                      top_p: Optional[float],
                      presence_penalty: float = 0.0,
                      frequency_penalty: float = 0.0,
                      stop_ids: Tuple[int, ...] = (),
                      want_tops: bool = False,
                      stream_q: Optional[asyncio.Queue] = None,
                      cls: str = request_class.DEFAULT_CLASS
                      ) -> asyncio.Future:
        """Enqueue a request; returns the future resolving to
        (tokens, finish_reason, chosen_token_logprobs). Raises
        EngineOverloaded when the bounded admission queue is full
        (surfaced as 429) — the queue never grows without limit under
        overload. `want_tops`: the request asked for top-N alternative
        logprobs, so steps serving it must run the want_tops compiled
        variant (chosen-token logprobs are always recorded). `cls`:
        the request's declared class — clamped here through the closed
        registry even though the LB already clamped the header, so a
        replica addressed directly can never mint a label value."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        try:
            self._queue.put_nowait((tokens, max_new, temperature, top_k,
                                    top_p, presence_penalty,
                                    frequency_penalty, stop_ids,
                                    bool(want_tops), stream_q, fut))
        except asyncio.QueueFull:
            self.rejected_total += 1
            _M_REJECTED.inc()
            raise EngineOverloaded(
                f'admission queue full ({MAX_QUEUE} waiting)') from None
        # Submit timestamp pair: the monotonic ns aligns with the flight
        # ring's clock (queue-wait/TTFT deltas), the wall clock anchors
        # the recorded spans cross-process; the normalized class rides
        # along to the slot entry for publish-time per-class telemetry.
        # Bounded: a queued item whose future is cancelled before
        # admission never pops its entry.
        self._submit_meta[id(fut)] = (time.monotonic_ns(), time.time(),
                                      request_class.normalize(cls))
        while len(self._submit_meta) > 4096:
            self._submit_meta.pop(next(iter(self._submit_meta)))
        self.requests_total += 1
        _M_REQUESTS.inc()
        _M_QUEUE_DEPTH.set(self.queue_depth())
        return fut

    async def submit(self, tokens: List[int], max_new: int,
                     temperature: float, top_k: Optional[int],
                     top_p: Optional[float],
                     presence_penalty: float = 0.0,
                     frequency_penalty: float = 0.0,
                     stop_ids: Tuple[int, ...] = ()):
        fut = self.submit_nowait(tokens, max_new, temperature, top_k,
                                 top_p, presence_penalty,
                                 frequency_penalty, stop_ids=stop_ids)
        return await fut

    # -- disaggregated serving (serve/disagg; docs/serving.md) ----------
    def mark_prefill_export(self, fut) -> None:
        """Turn the queued request owning ``fut`` into a PREFILL-ONLY
        admission: it prefills (grouped or chunked, prefix hits
        included) and samples its first token exactly like any other
        request, then — instead of converting to a decoding slot — its
        KV pages are exported host-side, the slot finishes with reason
        ``'handoff'``, and the pages free at the very next publish.
        The export blob waits in :meth:`pop_export` for the
        /disagg/prefill handler."""
        self._mark(fut, {'mode': 'export'})

    def submit_adopted(self, meta: Dict[str, Any],
                       arrays: Dict[str, Any],
                       stream_q: Optional[asyncio.Queue] = None):
        """Enqueue a HANDED-OFF request (decode role): admission
        scatters the shipped page contents into locally-reserved pages
        (paging.adopt_rows) instead of prefilling, seeds the sampler
        state from ``meta``, and decode continues token-for-token as
        if this replica had prefilled the prompt itself (greedy
        outputs are bit-identical to a monolithic run — pin-tested).
        Same backpressure surface as submit_nowait: EngineOverloaded
        on a full queue."""
        fut = self.submit_nowait(
            list(meta['tokens']), int(meta['max_new']),
            float(meta['temperature']),
            int(meta['top_k']) or None,
            float(meta['top_p']) or None,
            float(meta['presence_penalty']),
            float(meta['frequency_penalty']),
            stop_ids=tuple(int(i) for i in meta['stop_ids']),
            want_tops=bool(meta['want_tops']), stream_q=stream_q,
            cls=str(meta.get('cls', request_class.DEFAULT_CLASS)))
        self._mark(fut, {'mode': 'adopt', 'meta': meta,
                         'arrays': arrays})
        return fut

    def _mark(self, fut, mark: Dict[str, Any]) -> None:
        self._disagg_marks[id(fut)] = mark
        while len(self._disagg_marks) > 4096:
            self._disagg_marks.pop(next(iter(self._disagg_marks)))

    def _mode_of(self, item) -> Optional[str]:
        fut = item[-1]
        if fut is None:
            return None
        mark = self._disagg_marks.get(id(fut))
        return mark.get('mode') if mark else None

    def pop_export(self, fut) -> Optional[Dict[str, Any]]:
        """The prefill-only request's exported pages + geometry,
        consumed ONCE by the /disagg/prefill handler owning ``fut``.
        None when the request completed outright at admission (first
        token hit a stop id, or max_new == 1) — no decode phase
        remains, so nothing ships."""
        return self._exports.pop(id(fut), None)

    def handoff_validate(self, meta: Dict[str, Any]) -> Optional[str]:
        """Receiver-side compatibility check (serve/disagg/handoff.py
        calls this BEFORE staging): a prefill pool paired with an
        incompatible decode pool must refuse loudly (kind 'spec'),
        never adopt garbage. Deep shape skew the cheap checks miss
        still fails contained at the adopt device call (_fail_all →
        structured retriable 503)."""
        if not self.paged:
            return 'decode replica is not in paged mode (disagg ' \
                   'requires SKYTPU_ENGINE_PAGED=1)'
        from skypilot_tpu.models import paging as paging_lib
        family = ('paged_kv' if isinstance(self.cache, paging_lib.PagedKV)
                  else 'paged_latent')
        if meta['family'] != family:
            return (f'cache family mismatch: handoff {meta["family"]}, '
                    f'replica {family}')
        if int(meta['vocab_size']) != self.cfg.vocab_size:
            return (f'vocab mismatch: handoff {meta["vocab_size"]}, '
                    f'replica {self.cfg.vocab_size}')
        if str(meta['model']) != self.model_name:
            return (f'model mismatch: handoff {meta["model"]!r}, '
                    f'replica {self.model_name!r}')
        n = len(meta['tokens'])
        if n < 1:
            return 'handoff with empty prompt'
        if int(meta['bucket']) != _bucket(n):
            return (f'bucket mismatch: handoff {meta["bucket"]}, '
                    f'replica computes {_bucket(n)} for {n} tokens')
        if _bucket(n) + int(meta['max_new']) > self.max_len:
            return (f'bucketed prompt ({_bucket(n)}) + max_new '
                    f'({meta["max_new"]}) exceeds replica max_len '
                    f'{self.max_len}')
        return None

    def _export_slot(self, slot: int, tokens) -> Dict[str, Any]:
        """Gather the freshly-prefilled row's first bucket-many token
        positions out of the page pool into host arrays (the handoff
        payload). Runs inside the admit call, at a drained point, on
        the fresh cache the prefill just produced. ``prefill.flush``
        is the chaos window between 'prefill done' and 'pages
        exported' (docs/ROBUSTNESS.md)."""
        import jax
        import numpy as np
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('prefill.flush')
        p = _bucket(len(tokens))
        try:
            a, b = self._export_jit(p)(self.cache,
                                       self._jnp.int32(slot))
            t_sync = time.perf_counter()
            a = np.asarray(jax.device_get(a))
            b = np.asarray(jax.device_get(b))
            _M_HOST_SYNC_SECONDS.observe(time.perf_counter() - t_sync)
        except BaseException:
            _M_HANDOFF.inc(stage='export', outcome='error')
            raise
        _M_HANDOFF.inc(stage='export', outcome='ok')
        return {'a': a, 'b': b, 'bucket': p, 'length': len(tokens)}

    def _admit_adopted(self, item) -> int:
        """Admit one handed-off request (drained points only): reserve
        worst-case pages through the LOCAL allocator, scatter the
        shipped page contents in, seed sampler state + penalty counts
        + the device `last` carry from the handoff meta, and convert
        straight to a decoding slot via _finish_admit — the first
        token (sampled on the prefill replica) streams at the next
        publish and decode proceeds on the standard step path."""
        assert not self._inflight, \
            'adopt while a step is in flight (collect must precede ' \
            'slot reuse)'
        fut = item[-1]
        mark = self._disagg_marks.get(id(fut)) or {}
        meta, arrays = mark.get('meta'), mark.get('arrays')
        if meta is None:
            # Mark aged out of the bounded dict (pathological backlog):
            # the decode replica is a full engine — prefill locally
            # instead of failing the request. Greedy outputs are
            # identical either way.
            logger.warning('adopt mark lost; falling back to a local '
                           'prefill admission')
            self._admit_group([item])
            return -1
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('engine.admit')
        t0 = time.perf_counter()
        jnp = self._jnp
        tokens, max_new, temperature, top_k, top_p, pres, freq = item[:7]
        slot = self._free_slot()
        assert slot is not None
        self.temp[slot] = max(float(temperature), 0.0)
        self.topk[slot] = int(top_k) if top_k else 0
        self.topp[slot] = float(top_p) if top_p else 0.0
        self.pres[slot] = float(pres or 0.0)
        self.freq[slot] = float(freq or 0.0)
        self._reserve_slot_pages(
            slot, self._alloc_pages(self._pages_needed(item)))
        self._refresh_table()
        s = _bucket(len(tokens))
        first = int(meta['first_token'])
        try:
            self.cache, self.last_dev = self._adopt_jit(s)(
                self.cache, jnp.asarray(arrays['a']),
                jnp.asarray(arrays['b']), jnp.int32(slot),
                jnp.int32(len(tokens)), self.last_dev,
                jnp.int32(first))
        except BaseException:
            _M_HANDOFF.inc(stage='adopt', outcome='error')
            raise
        self.counts = self.counts.at[slot].set(0).at[slot, first].add(1)
        # Admission anchor: adoption IS this replica's prefill phase.
        self._admit_t0_ns = time.monotonic_ns()
        self._finish_admit(item, slot, first, float(meta['first_lp']),
                           list(meta.get('first_tops') or []))
        self._disagg_marks.pop(id(fut), None)
        self.flight.record(flight_lib.ADMIT, slot, s)
        _M_HANDOFF.inc(stage='adopt', outcome='ok')
        _M_ADMIT_SECONDS.observe(time.perf_counter() - t0)
        return slot

    def _bcast(self, op) -> None:
        """Leader→follower control broadcast (multi-host serving);
        no-op everywhere else. Sent BEFORE the leader executes the op
        so every process enters the same collective in the same
        order."""
        if self._ctrl is not None:
            self._ctrl.send(op)

    def cancel(self, fut) -> None:
        """Abort the in-flight request owning `fut` (the SSE path cuts
        generation short when a stop STRING matches mid-stream —
        without this the slot would decode to max_tokens after the
        client stopped listening). DEFERRED: the batch loop applies
        cancels at its loop top, so the state mutation lands at a
        well-defined point between device calls — never racing the
        in-flight step thread, and broadcast to multi-host followers in
        op order. No-op if the request is still queued or already
        done."""
        self._pending_cancels.append(fut)

    def _process_cancels(self) -> None:
        """Apply deferred cancels (batch-loop top: between device ops).
        Marks only — the slot frees at the NEXT _publish, the same
        point in the op stream where followers reap."""
        if not self._pending_cancels:
            return
        for fut in self._pending_cancels:
            for i, s in enumerate(self.slots):
                if s is not None and s['fut'] is fut:
                    if s['finish'] is None:
                        s['finish'] = 'stop'
                        self.flight.record(flight_lib.CANCEL, i)
                        self._bcast(('cancel', i))
                    break
        self._pending_cancels.clear()

    def _free_slot(self) -> Optional[int]:
        return self._free_slot_excluding(())

    def _admit(self, item) -> None:
        """Back-compat single admit (warmup + tests)."""
        self._admit_group([item])

    # -- prefix (system-prompt) KV cache -------------------------------
    def _prefix_match(self, tokens) -> Optional[int]:
        """Longest snapshotted power-of-two prefix of `tokens` (strict:
        at least one suffix token must remain, and the prefix + the
        bucketed suffix must still fit max_len — p + bucket(len-p) can
        exceed bucket(len) for non-power-of-two --max-len, and an
        overflow inside the admit jit would fail the whole pool), or
        None (→ full prefill)."""
        has_host = self.host_store is not None and len(self.host_store)
        if not self._prefix_store and not has_host:
            return None
        p = PREFIX_MIN_TOKENS
        best = None
        while p < len(tokens):
            key = tuple(tokens[:p])
            # A spilled entry counts as a hit: _admit_with_prefix
            # wakes it back into the device tier before extending.
            if ((key in self._prefix_store or
                 (has_host and key in self.host_store)) and
                    p + _bucket(len(tokens) - p) <= self.max_len):
                best = p
            p *= 2
        return best

    def _prefix_capture(self, tokens, slot) -> None:
        """Snapshot this slot's first pow2-many cache rows under the
        token prefix key (device-side slice — owns its buffer, so later
        cache donation can't invalidate it). The snapshot pair is
        (k, v) for KVCache families, (c_kv, k_rope) latents for MLA —
        whatever the family's prefill_extend takes."""
        if (PREFIX_CACHE_ENTRIES <= 0 or
                len(tokens) < PREFIX_MIN_TOKENS):
            return
        p = PREFIX_MIN_TOKENS
        while p * 2 <= len(tokens):
            p *= 2
        key = tuple(tokens[:p])
        if key in self._prefix_store:
            self._prefix_store.move_to_end(key)
            self._prefix_last_used[key] = time.monotonic()
            return
        if self.paged:
            # A snapshot is p/page_size REFS on the slot's prefix pages
            # — page-table entries, not HBM. The slot keeps decoding
            # into its own pages at positions ≥ len(tokens) ≥ p, so
            # the shared pages stay read-only for everyone.
            n = p // self.page_size
            pids = [int(x) for x in self._table_np[slot, :n]]
            if not pids or 0 in pids:
                return        # row reserved fewer pages than p (never
                #               happens for admitted traffic; guard)
            for pid in pids:
                self.alloc.ref(pid)
            self._prefix_store[key] = pids
            self._prefix_last_used[key] = time.monotonic()
        elif hasattr(self.cache, 'k'):
            self._prefix_store[key] = (self.cache.k[:, slot, :p],
                                       self.cache.v[:, slot, :p])
        else:
            self._prefix_store[key] = (self.cache.c_kv[:, slot, :p],
                                       self.cache.k_rope[:, slot, :p])
        spills = []
        while len(self._prefix_store) > PREFIX_CACHE_ENTRIES:
            old_key, old = self._prefix_store.popitem(last=False)
            if self.paged:
                # LRU overflow spills instead of dropping when the
                # host tier is on — entry-count pressure is the churn
                # profile's main spill trigger.
                info = self._spill_entry(old_key, old)
                if info is not None:
                    spills.append(info)
        self._journal_spill(spills)
        self._note_kv_residency()

    @timeline.event
    def _admit_with_prefix(self, item, p: int) -> int:
        """Admit one request over a stored prefix; returns the slot."""
        jnp = self._jnp
        (tokens, _, temperature, top_k, top_p, pres, freq,
         *_rest) = item
        slot = self._free_slot()
        assert slot is not None
        suffix = tokens[p:]
        s2 = _bucket(len(suffix))
        padded = jnp.asarray([suffix + [0] * (s2 - len(suffix))],
                             jnp.int32)
        self.temp[slot] = max(float(temperature), 0.0)
        self.topk[slot] = int(top_k) if top_k else 0
        self.topp[slot] = float(top_p) if top_p else 0.0
        self.pres[slot] = float(pres or 0.0)
        self.freq[slot] = float(freq or 0.0)
        key = tuple(tokens[:p])
        if self.paged:
            # Zero-copy sharing: the hit's table points at the SAME
            # pages the store entry holds (one ref each); only the
            # suffix gets own pages, and the extend program gathers the
            # prefix from the shared pages every other holder reads.
            # p is a power of two ≥ PREFIX_MIN_TOKENS and page_size
            # divides PREFIX_MIN_TOKENS, so the suffix starts exactly
            # on a page boundary — a sharer can never write a shared
            # page.
            if (key not in self._prefix_store and
                    self.host_store is not None):
                # Host-tier hit: wake the spilled entry back into the
                # device tier first. A wake failure (chaos kv.wake, a
                # corrupt blob) propagates to _fail_all, which
                # resurrects this not-yet-sampled request.
                self._wake_prefix_entry(key)
            shared = self._prefix_store[key]
            self._prefix_store.move_to_end(key)
            self._prefix_last_used[key] = time.monotonic()
            need = self._pages_needed(item)
            own = self._alloc_pages(max(0, need - len(shared)))
            for pid in shared:
                self.alloc.ref(pid)
            self._reserve_slot_pages(slot, list(shared) + own)
            self._refresh_table()
            run = self._extend_jit(p, s2, True)
            (first, first_lp, ti, tv, self.cache, self.last_dev,
             self.rng) = run(
                self.params, self.cache, self.last_dev, padded,
                jnp.int32(len(suffix)), jnp.int32(slot),
                jnp.float32(self.temp[slot]),
                jnp.int32(self.topk[slot]),
                jnp.float32(self.topp[slot]), self.rng)
        else:
            pk, pv = self._prefix_store[key]
            self._prefix_store.move_to_end(key)
            (first, first_lp, ti, tv, self.cache, self.last_dev,
             self.rng) = self._admit_extend_jit(
                self.params, self.cache, self.last_dev, pk, pv, padded,
                jnp.int32(len(suffix)), jnp.int32(slot),
                jnp.float32(self.temp[slot]), jnp.int32(self.topk[slot]),
                jnp.float32(self.topp[slot]), self.rng)
        self.prefix_hits += 1
        _M_PREFIX_HITS.inc()
        _M_PREFIX.inc(outcome='hit')
        first_i = int(first)
        self.counts = self.counts.at[slot].set(0).at[slot, first_i].add(1)
        self._finish_admit(item, slot, first_i, float(first_lp),
                           _tops_list(ti, tv))
        # The slot now holds the FULL prompt's KV — snapshot the longer
        # prefix so a growing chat history keeps extending its cache
        # (turn N+1 hits turn N's whole prompt, not just the oldest
        # 64-token prefix).
        self._prefix_capture(tokens, slot)
        return slot

    def _finish_admit(self, item, slot: int, first: int,
                      first_lp: float = 0.0,
                      first_tops: Optional[list] = None) -> None:
        (tokens, max_new, _, _, _, _, _, stop_ids, want_tops, stream_q,
         fut) = item
        self.last[slot] = first
        stop = frozenset(stop_ids or ())
        # Flight ring: the admit event (seq = prompt bucket), plus the
        # request's timing anchors folded into the slot entry — submit
        # meta popped by future id, admit start from the enclosing
        # admit call, first token = now. TTFT/TPOT derive from these
        # ring-aligned deltas at publish time; the per-token loop
        # records nothing but ring tuples (observe/flight.py).
        now_ns = time.monotonic_ns()
        self.flight.record(flight_lib.ADMIT, slot, _bucket(len(tokens)))
        meta = (self._submit_meta.pop(id(fut), None)
                if fut is not None else None)
        if meta is not None:
            # Submit → admission (pages + slot granted): the queue-wait
            # quantity the paged/chunked admission exists to shrink.
            # For chunked admits the anchor is chunkstart, so chunk
            # rounds count as prefill, not wait.
            _M_ADMIT_WAIT.observe(max(
                0.0, (getattr(self, '_admit_t0_ns', now_ns) - meta[0])
                / 1e9))
        # ctx = prompt ++ generated: the prompt-lookup draft source AND
        # the host mirror of the row's cache length (len(ctx) - 1).
        entry = {'fut': fut, 'want': max_new, 'out': [], 'lps': [],
                 'tops': [], 'stop': stop, 'stream': stream_q, 'sent': 0,
                 'finish': None, 'want_tops': bool(want_tops),
                 'item': item,
                 'ctx': list(tokens) + [first],
                 't_submit_ns': meta[0] if meta else None,
                 't_submit_wall': meta[1] if meta else None,
                 'cls': (meta[2] if meta
                         else request_class.DEFAULT_CLASS),
                 't_admit_ns': getattr(self, '_admit_t0_ns', now_ns),
                 't_first_ns': now_ns}
        if first in stop:
            entry['finish'] = 'stop'
        else:
            entry['out'].append(first)
            entry['lps'].append(first_lp)
            entry['tops'].append(first_tops or [])
            self.tokens_generated += 1
            _M_TOKENS.inc()
            if len(entry['out']) >= max_new:
                entry['finish'] = 'length'
        self.slots[slot] = entry
        # Prefill-only (disaggregated serving): the row's job ends at
        # its first sampled token — export the prefilled pages for the
        # handoff and finish with reason 'handoff'; publish resolves
        # the future and frees the pages at the next drained point. A
        # request that finished outright (first token hit a stop id,
        # max_new == 1) skips the export: no decode phase remains.
        if fut is not None:
            mark = self._disagg_marks.get(id(fut))
            if mark is not None and mark.get('mode') == 'export':
                self._disagg_marks.pop(id(fut), None)
                if entry['finish'] is None:
                    # Export BEFORE marking finished: a failed export
                    # (prefill.flush chaos, device fault) leaves the
                    # row unfinished, so _fail_all surfaces the
                    # standard structured retriable 503 instead of
                    # resolving a handoff that has no pages.
                    self._exports[id(fut)] = self._export_slot(slot,
                                                               tokens)
                    while len(self._exports) > 256:
                        self._exports.popitem(last=False)
                    entry['finish'] = 'handoff'

    @timeline.event
    def _admit_group(self, items) -> None:
        """Prefill same-bucket requests in ONE device call (device
        work: call off-loop). Callers group by bucket and split counts
        into power-of-two sizes so the compile count stays bounded at
        (#buckets × log2(MAX_BATCH)) programs. A single-request group
        whose prompt extends a snapshotted prefix prefills only the
        suffix (_admit_with_prefix)."""
        import jax
        jnp = self._jnp
        # Buffer-reuse guard: admission reuses freed cache rows and
        # reassigns the device `last` carry, so it is only legal at a
        # DRAINED point — an uncollected lookahead step's output for a
        # reused slot would otherwise be consumed by the new occupant
        # (tested: collect always precedes buffer reuse).
        assert not self._inflight, \
            'admit while a step is in flight (collect must precede ' \
            'slot reuse)'
        # self.warm gate on every engine fault site: warmup drives the
        # same admit/step/collect methods synchronously with NO
        # containment wrapper — an env-armed chaos schedule must hit
        # serving traffic, not kill the boot.
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('engine.admit')
        t_admit = time.perf_counter()
        # Prefill-start anchor for every request this call admits
        # (including the prefix-hit path below): _finish_admit folds it
        # into the slot entry, so queue wait and prefill decompose.
        self._admit_t0_ns = time.monotonic_ns()
        # self.warm gate: warmup's synthetic prompts share prefixes
        # across buckets — a warmup hit would skip compiling the very
        # grouped-admit programs warmup exists to build. A BURST of
        # same-prefix requests splits: hits ride the suffix-only path
        # one by one, the rest prefill grouped — exactly the
        # prefix-affinity LB's target traffic shape.
        if self.warm and PREFIX_CACHE_ENTRIES > 0:
            rest = []
            for item in items:
                p = self._prefix_match(item[0])
                if p is not None:
                    self._admit_with_prefix(item, p)
                elif self._should_chunk(item):
                    # Classified as a prefix HIT upstream, but the
                    # snapshot was evicted in the meantime (page-
                    # pressure eviction or LRU overflow from an
                    # earlier group in this same pass): take the
                    # chunked path — a grouped prefill at a
                    # bucket > PREFILL_CHUNK is a program warmup
                    # deliberately never compiled. Deterministic on
                    # followers (mirrored store + config).
                    self._start_chunked(item)
                else:
                    rest.append(item)
            if not rest:
                return
            if len(rest) != len(items):
                # Re-split the misses into power-of-two group sizes
                # (the compile-count bound); re-entry takes the grouped
                # path — or the hit path, if an earlier hit's re-capture
                # made a miss match.
                for group in self._admit_groups(rest):
                    self._admit_group(group)
                return
            items = rest
        bucket = _bucket(len(items[0][0]))
        slots, padded, lengths = [], [], []
        temps, topks, topps = [], [], []
        for item in items:
            tokens = item[0]
            assert _bucket(len(tokens)) == bucket, 'caller groups by bucket'
            slot = self._free_slot_excluding(slots)
            assert slot is not None
            slots.append(slot)
            padded.append(tokens + [0] * (bucket - len(tokens)))
            lengths.append(len(tokens))
            temperature, top_k, top_p, pres, freq = item[2:7]
            self.temp[slot] = max(float(temperature), 0.0)
            self.topk[slot] = int(top_k) if top_k else 0
            self.topp[slot] = float(top_p) if top_p else 0.0
            self.pres[slot] = float(pres or 0.0)
            self.freq[slot] = float(freq or 0.0)
            temps.append(self.temp[slot])
            topks.append(self.topk[slot])
            topps.append(self.topp[slot])
            if self.paged:
                # Reserve the row's worst-case pages up front and point
                # its table at them; positions past the reservation
                # read/write the trash page (pad garbage, never
                # attended). The leader gates admission on this exact
                # count, so alloc cannot fail here.
                self._reserve_slot_pages(
                    slot, self._alloc_pages(self._pages_needed(item)))
        self._refresh_table()
        if self.warm and PREFIX_CACHE_ENTRIES > 0:
            # Every item reaching the grouped prefill was a prefix-cache
            # lookup miss (hits rode _admit_with_prefix above).
            _M_PREFIX.inc(len(items), outcome='miss')
        first, first_lp, tis, tvs, self.cache, self.last_dev, self.rng = \
            self._admit_jit(
                self.params, self.cache, self.last_dev,
                jnp.asarray(padded, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(slots, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(topks, jnp.int32),
                jnp.asarray(topps, jnp.float32), self.rng)
        t_sync = time.perf_counter()
        first = jax.device_get(first)
        first_lp = jax.device_get(first_lp)
        tis, tvs = jax.device_get(tis), jax.device_get(tvs)
        _M_HOST_SYNC_SECONDS.observe(time.perf_counter() - t_sync)
        # Penalty counts: fresh slot, first token counted (host-side
        # eager update; the buffer is otherwise owned by the step jit).
        sl = jnp.asarray(slots, jnp.int32)
        self.counts = self.counts.at[sl].set(0).at[
            sl, jnp.asarray(first, jnp.int32)].add(1)
        for i, item in enumerate(items):
            self._finish_admit(item, slots[i], int(first[i]),
                               float(first_lp[i]),
                               _tops_list(tis[i], tvs[i]))
            if self.warm:
                self._prefix_capture(item[0], slots[i])
        _M_ADMIT_SECONDS.observe(time.perf_counter() - t_admit)

    def _free_slot_excluding(self, taken) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None and i not in taken:
                return i
        return None

    # -- chunked prefill (paged mode) -----------------------------------
    def _should_chunk(self, item) -> bool:
        """Long prefix-miss prompts prefill in PREFILL_CHUNK-token
        pieces interleaved with decode rounds instead of one monolithic
        bucket prefill. Prefix HITS keep the whole-suffix extend path
        (one on-demand program per (p, suffix-bucket), the pre-paging
        compile model) — chunk alignment stays a multiple of
        PREFILL_CHUNK, so the chunk program grid is bounded and
        warmable."""
        if not self.paged or len(item[0]) <= self.prefill_chunk:
            return False
        if self.warm and PREFIX_CACHE_ENTRIES > 0 and \
                self._prefix_match(item[0]) is not None:
            return False
        return True

    def _pending_chunks(self) -> List[int]:
        """Slots mid-chunked-prefill (occupied, unfinished, prefill
        state present)."""
        return [i for i, s in enumerate(self.slots)
                if s is not None and s['finish'] is None and
                s.get('prefill') is not None]

    @timeline.event
    def _start_chunked(self, item) -> int:
        """Begin a chunked admission: claim the slot + reserve all
        pages now (admission blocks on free pages, not bucket shape),
        run the FIRST chunk, and leave the slot in the prefilling state
        — the batch loop advances one chunk per drained round, so short
        requests keep admitting and decoding between chunks. Mirrored
        on followers via the ('chunkstart', item, fp) op."""
        assert not self._inflight, \
            'chunk start while a step is in flight'
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('engine.admit')
        (tokens, max_new, temperature, top_k, top_p, pres, freq,
         stop_ids, want_tops, stream_q, fut) = item
        slot = self._free_slot()
        assert slot is not None
        self.temp[slot] = max(float(temperature), 0.0)
        self.topk[slot] = int(top_k) if top_k else 0
        self.topp[slot] = float(top_p) if top_p else 0.0
        self.pres[slot] = float(pres or 0.0)
        self.freq[slot] = float(freq or 0.0)
        self._reserve_slot_pages(
            slot, self._alloc_pages(self._pages_needed(item)))
        self.slots[slot] = {
            'fut': fut, 'stream': stream_q, 'finish': None,
            'want': max_new, 'out': [], 'lps': [], 'tops': [],
            'stop': frozenset(stop_ids or ()), 'sent': 0,
            'want_tops': bool(want_tops), 'ctx': list(tokens),
            'prefill': {'item': item, 'pos': 0,
                        't_admit_ns': time.monotonic_ns()},
        }
        self._advance_chunk(slot)
        return slot

    @timeline.event
    def _advance_chunk(self, slot: int) -> None:
        """Run ONE prefill chunk for `slot` (drained points only;
        followers replay via ('chunk', slot)). Non-final chunks write
        positions [pos, pos+C) into the row's own pages and touch
        neither the RNG nor the device `last` carry, so a chunked
        admission consumes exactly the contiguous path's RNG stream;
        the final chunk samples the first token and converts the slot
        into a normal decoding entry (_finish_admit)."""
        jnp = self._jnp
        s = self.slots[slot]
        if s is None or s['finish'] is not None or \
                s.get('prefill') is None:
            return          # cancelled mid-prefill; publish reaps it
        assert not self._inflight, 'chunk while a step is in flight'
        st = s['prefill']
        item = st['item']
        tokens = item[0]
        pos = st['pos']
        c = self.prefill_chunk
        remaining = len(tokens) - pos
        t0 = time.perf_counter()
        self._refresh_table()
        if remaining > c:
            run = self._extend_jit(pos, c, False)
            chunk = jnp.asarray([tokens[pos:pos + c]], jnp.int32)
            self.cache = run(
                self.params, self.cache, self.last_dev, chunk,
                jnp.int32(c), jnp.int32(slot),
                jnp.float32(self.temp[slot]),
                jnp.int32(self.topk[slot]),
                jnp.float32(self.topp[slot]), self.rng)
            st['pos'] = pos + c
            self.flight.record(flight_lib.CHUNK, slot, pos + c)
            _M_ADMIT_SECONDS.observe(time.perf_counter() - t0)
            return
        s2 = _bucket(remaining)
        padded = jnp.asarray(
            [tokens[pos:] + [0] * (s2 - remaining)], jnp.int32)
        run = self._extend_jit(pos, s2, True)
        (first, first_lp, ti, tv, self.cache, self.last_dev,
         self.rng) = run(
            self.params, self.cache, self.last_dev, padded,
            jnp.int32(remaining), jnp.int32(slot),
            jnp.float32(self.temp[slot]), jnp.int32(self.topk[slot]),
            jnp.float32(self.topp[slot]), self.rng)
        first_i = int(first)
        self.counts = self.counts.at[slot].set(0).at[
            slot, first_i].add(1)
        # Convert to a decoding slot: _finish_admit rebuilds the entry;
        # the admission anchor is the chunkstart timestamp, so queue
        # wait excludes (and prefill time includes) the chunk rounds.
        self._admit_t0_ns = st['t_admit_ns']
        self.slots[slot] = None
        self._finish_admit(item, slot, first_i, float(first_lp),
                           _tops_list(ti, tv))
        self.flight.record(flight_lib.CHUNK, slot, len(tokens))
        self._prefix_capture(tokens, slot)
        _M_ADMIT_SECONDS.observe(time.perf_counter() - t0)

    @timeline.event
    def _spec_once(self) -> bool:
        """Try ONE speculative round over the pool; False → caller runs
        the normal step. Preconditions: a non-MoE family (spec_k gates
        at init — dense GQA and dense MLA both speculate via their
        verify_step), every active row greedy, no penalties, at least
        one row with a prompt-lookup draft, and K more cache slots free
        on every active row (an out-of-bounds scatter would clamp onto
        valid KV).

        Rows WITHOUT a draft still commit exactly one token (the
        correction IS the target's next greedy token), so a mixed pool
        pays one verify call and nobody stalls. Outputs are exactly the
        non-speculative greedy outputs — acceptance only changes how
        many tokens commit per device call.

        The headroom check is POOL-WIDE by design: verify_step writes K
        slots on EVERY row (a clamped out-of-bounds scatter would
        corrupt a tight row's last valid KV), and shrinking K per-round
        would compile fresh programs from traffic shapes — so one
        near-limit row pauses speculation until it finishes. Low accept
        rates pause it too (SPEC_MIN_ACCEPT/SPEC_COOLDOWN): the fused
        chunk path amortizes dispatch better when drafts keep missing."""
        import jax
        import numpy as np
        jnp = self._jnp
        k = self.spec_k
        # A speculative round is host-SYNCHRONOUS (the verify outputs
        # decide the next feed), so it only runs at a drained point: a
        # lookahead step in flight means this is a pipelined round —
        # decline BEFORE touching the cooldown counter, so leader and
        # followers (which call this on every 'step' op) stay in
        # lockstep.
        if self._inflight:
            return False
        # The cheap preconditions are SHARED with the batch loop's
        # lookahead gate (_spec_precheck: spec enabled, warm, no
        # cooldown, all rows greedy, no penalties) — one definition,
        # so the 'spec takes precedence' decision can never drift from
        # what this method actually accepts. The cooldown inside it is
        # check-only here: it DECREMENTS at _collect_step (one tick
        # per executed fused step, the old per-round cadence), so it
        # keeps draining while the pipeline owns the pool and spec
        # re-probes when it expires.
        if not self._spec_precheck():
            return False
        active_idx = [i for i, s in enumerate(self.slots)
                      if self._row_active(s)]
        drafts = {}
        real_len = {}
        no_draft = False
        for i in active_idx:
            ctx = self.slots[i]['ctx']
            if len(ctx) - 1 + k > self.max_len:
                no_draft = True      # headroom pause — same handling
                break
            d = _lookup_draft(ctx, k)
            if d:
                real_len[i] = len(d)
                drafts[i] = (d + [0] * k)[:k]
        if no_draft or not drafts:
            # Nothing to verify (non-repetitive traffic, or a
            # near-limit row): pause the probing for a few steps and
            # hand the pool to the overlap PIPELINE — without this,
            # greedy traffic that never drafts would re-probe every
            # round and never pipeline at all.
            self._spec_cool = SPEC_NO_DRAFT_COOLDOWN
            return False
        fed = np.zeros((MAX_BATCH, k), np.int32)
        for i in active_idx:
            fed[i, 0] = self.last[i]
            fed[i, 1:] = (drafts[i][:k - 1] if i in drafts
                          else [self.last[i]] * (k - 1))
        want_tops = any(self.slots[i]['want_tops'] for i in active_idx)
        self._refresh_table()
        active_arr = jnp.asarray([self._row_active(s)
                                  for s in self.slots])
        if want_tops:
            greedy, lps, tis, tvs, self.cache = self._spec_jit(
                self.params, self.cache, jnp.asarray(fed), active_arr,
                True)
        else:
            greedy, lps, self.cache = self._spec_jit(
                self.params, self.cache, jnp.asarray(fed), active_arr,
                False)
            tis = tvs = None
        t_sync = time.perf_counter()
        greedy = jax.device_get(greedy)          # [B, K]
        lps = jax.device_get(lps)
        if want_tops:
            tis, tvs = jax.device_get(tis), jax.device_get(tvs)
        _M_HOST_SYNC_SECONDS.observe(time.perf_counter() - t_sync)
        self.step_count += 1
        self.spec_rounds += 1
        self._count_cache_traffic(1, k)
        _M_STEPS.inc()
        _M_SPEC_ROUNDS.inc()
        adv = np.zeros((MAX_BATCH,), np.int32)
        round_prop = round_acc = 0
        for i in active_idx:
            s = self.slots[i]
            prop = drafts.get(i, [int(self.last[i])] * k)
            a = 0
            while a < k and prop[a] == int(greedy[i][a]):
                a += 1
            if i in drafts:
                # Metrics count only REAL proposals (padding past a
                # short draft isn't a proposal, and a coincidental
                # pad-token accept isn't an accepted draft).
                round_prop += real_len[i]
                round_acc += min(a, real_len[i])
            row = (prop[:a] + [int(greedy[i][a])]) if a < k else prop[:k]
            # Cache length advances by the FULL committed run (KV for
            # row[:-1] was just written; row[-1] is the new `last`,
            # whose KV the next step writes — the standing invariant).
            adv[i] = len(row)
            self.last[i] = row[-1]
            for j, tok in enumerate(row):
                if s['finish'] is not None:
                    break
                if tok in s['stop']:
                    s['finish'] = 'stop'
                    break
                s['out'].append(tok)
                s['lps'].append(float(lps[i][j]))
                s['tops'].append(_tops_list(tis[i][j], tvs[i][j])
                                 if want_tops else [])
                s['ctx'].append(tok)
                self.tokens_generated += 1
                _M_TOKENS.inc()
                if len(s['out']) >= s['want']:
                    s['finish'] = 'length'
        import dataclasses as _dc
        self.cache = _dc.replace(self.cache,
                                 length=self.cache.length +
                                 jnp.asarray(adv))
        # Re-pin the device-resident `last` to the committed tokens
        # (the step carry did not see this round).
        mask = np.zeros((MAX_BATCH,), bool)
        mask[active_idx] = True
        self.last_dev = self._fix_last_jit(self.last_dev,
                                           jnp.asarray(mask),
                                           jnp.asarray(self.last))
        self.spec_proposed += round_prop
        self.spec_accepted += round_acc
        self.flight.record(flight_lib.SPEC, 0, round_acc)
        _M_SPEC_PROPOSED.inc(round_prop)
        _M_SPEC_ACCEPTED.inc(round_acc)
        if round_prop and round_acc < SPEC_MIN_ACCEPT * round_prop:
            self._spec_cool = SPEC_COOLDOWN
        return True

    def _remaining(self, inflight_k: int = 0) -> List[int]:
        """Per-active-row token budget before length-finish.
        `inflight_k`: an uncollected call's tokens are budgeted as
        already consumed (the lookahead view)."""
        return [s['want'] - len(s['out']) - inflight_k
                for s in self.slots if self._row_active(s)]

    def _choose_k(self, inflight_k: int = 0) -> int:
        """Step width for the next fused call. k ∈ {1, MAX_STEP_CHUNK}
        ONLY: exactly two step widths in the compiled-variant matrix,
        all built in warmup — a client-chosen max_new must not be able
        to trigger a fresh XLA compile via tail-chunk sizes.
        Leader-only inputs (the admission queue) feed this, so
        multi-host broadcasts the chosen k."""
        if self._hold or self._pending_chunks():
            # A request waiting on free pages retries admission — and
            # a prefilling row advances its chunk — only at drained
            # points: fused 8-token steps would multiply their wait
            # (pre-paging, any waiter sat in _queue and forced k=1
            # through the queue.empty() check below).
            return 1
        remaining = self._remaining(inflight_k)
        if (remaining and min(remaining) >= MAX_STEP_CHUNK and
                (self._queue is None or self._queue.empty())):
            return MAX_STEP_CHUNK
        return 1

    def _lookahead_k(self, inflight_k: int) -> Optional[int]:
        """Width for a lookahead dispatch (step N+1 before step N is
        collected), or None when the pipeline must drain first: a
        request is waiting to admit, a cancel is pending, or some
        active row may finish inside the in-flight call (its tokens
        past the finish would be garbage AND the freed slot must not be
        stepped before re-admission). Speculation-ELIGIBLE pools do not
        look ahead either: a verify round is host-synchronous by
        nature, so speculation and pipelining are alternative TPOT
        strategies — spec takes precedence while its preconditions
        hold, and the pipeline owns sampling/penalized/spec-disabled
        pools plus spec's cooldown windows (the cooldown decrements at
        collect, so an expiring pause re-probes spec at the next
        drained round)."""
        if self._pending_cancels:
            return None
        if self._queue is not None and not self._queue.empty():
            return None
        if self._hold or self._pending_chunks():
            # A held request needs the next drained point to re-try
            # admission; a prefilling row needs it to advance its
            # chunk — don't pipeline past either.
            return None
        if self._spec_precheck():
            return None
        remaining = self._remaining(inflight_k)
        if not remaining or min(remaining) < 1:
            return None
        return self._choose_k(inflight_k)

    def _spec_precheck(self) -> bool:
        """Cheap host-only preconditions for a speculative round (no
        draft scan): used by the batch loop to stop looking ahead when
        the NEXT drained round could speculate instead."""
        if self.spec_k <= 0 or not self.warm or self._spec_cool > 0:
            return False
        active_idx = [i for i, s in enumerate(self.slots)
                      if self._row_active(s)]
        if not active_idx:
            return False
        if any(self.temp[i] > 0 for i in active_idx):
            return False
        return not (self.pres.any() or self.freq.any())

    @timeline.event
    def _dispatch_step(self, k: int,
                       want_tops_force: Optional[bool] = None
                       ) -> _InFlightStep:
        """Dispatch half of a fused step: select the compiled variant
        (k × use_pen × want_tops, all runtime state derived from
        MIRRORED host state so multi-host followers pick the same one),
        enqueue the device call, and return the in-flight handle — NO
        host sync happens here; the outputs stay device-side futures
        until _collect_step. Rows whose `finish` is already set are
        masked out of `active` at dispatch, so a stopped/cancelled/
        length-capped row stops burning decode FLOPs immediately
        instead of at the next reap."""
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('engine.step')
        t0 = time.perf_counter()
        jnp = self._jnp
        self._refresh_table()
        active = jnp.asarray([self._row_active(s) for s in self.slots])
        use_pen = bool(self.pres.any() or self.freq.any())
        want_tops = (bool(want_tops_force) if want_tops_force is not None
                     else any(self._row_active(s) and s['want_tops']
                              for s in self.slots))
        out = self._step_jit(
            self.params, self.cache, self.counts, self.last_dev,
            jnp.asarray(self.temp), jnp.asarray(self.topk),
            jnp.asarray(self.topp), jnp.asarray(self.pres),
            jnp.asarray(self.freq), self.rng, active, k=k,
            use_pen=use_pen, want_tops=want_tops)
        if want_tops:
            (toks, lps, tis, tvs, self.last_dev, self.cache,
             self.counts, self.rng) = out
            handle = _InFlightStep(k, True, toks, lps, tis, tvs)
        else:
            toks, lps, self.last_dev, self.cache, self.counts, \
                self.rng = out
            handle = _InFlightStep(k, False, toks, lps)
        self._inflight.append(handle)
        self._count_cache_traffic(k, k)
        # Ring only on the hot path: one counter bump + one slot store,
        # no sqlite/span/syscall (observe/flight.py; seq = step width).
        self.flight.record(flight_lib.DISPATCH, 0, k)
        _M_STEP_SECONDS.observe(time.perf_counter() - t0,
                                phase='dispatch')
        return handle

    @timeline.event
    def _collect_step(self) -> None:
        """Collect half: block on the OLDEST in-flight step's outputs
        (tokens + chosen logprobs always; the [k, B, K] top-k tensors
        only in the want_tops variant) and run the Python bookkeeping.
        Rows that finish mid-chunk leave the device-resident `last`
        carry at the chunk's final token — a tiny jitted where()
        re-pins it to the host mirror, keeping the invariant device
        last == host mirror for every occupied slot after collect."""
        import jax
        import numpy as np
        assert self._inflight, 'collect with no step in flight'
        if failpoints_lib.ACTIVE and self.warm:
            failpoints_lib.fire('engine.collect')
        h = self._inflight.pop(0)
        t0 = time.perf_counter()
        t_sync = time.perf_counter()
        toks = jax.device_get(h.toks)            # [k, B]
        lps = jax.device_get(h.lps)              # [k, B]
        if h.want_tops:
            tis = jax.device_get(h.tis)          # [k, B, K]
            tvs = jax.device_get(h.tvs)          # [k, B, K]
        _M_HOST_SYNC_SECONDS.observe(time.perf_counter() - t_sync)
        # Timestamped AFTER the device_get: the dispatch→collect ring
        # delta is the chunk's device+transfer wall time.
        self.flight.record(flight_lib.COLLECT, 0, h.k)
        k = h.k
        self.step_count += k
        _M_STEPS.inc(k)
        fixups = []
        for i, s in enumerate(self.slots):
            if s is None or s['finish'] is not None or \
                    s.get('prefill') is not None:
                # Finished rows were masked inactive at dispatch (or
                # this call was dispatched before the finish was known
                # — either way their outputs are not consumed). Rows
                # mid-chunked-prefill are masked too: their step
                # "outputs" are the stale device-last carry, not
                # tokens.
                continue
            for t in range(k):
                tok = int(toks[t][i])
                self.last[i] = tok
                if tok in s['stop']:
                    # EOS/stop token: excluded from the output (OpenAI
                    # semantics), generation for this row is done.
                    s['finish'] = 'stop'
                    break
                s['out'].append(tok)
                s['lps'].append(float(lps[t][i]))
                s['tops'].append(_tops_list(tis[t][i], tvs[t][i])
                                 if h.want_tops else [])
                s['ctx'].append(tok)
                self.tokens_generated += 1
                _M_TOKENS.inc()
                if len(s['out']) >= s['want']:
                    s['finish'] = 'length'
                    break
            if s['finish'] is not None:
                fixups.append(i)
        if fixups:
            mask = np.zeros((MAX_BATCH,), bool)
            mask[fixups] = True
            self.last_dev = self._fix_last_jit(
                self.last_dev, self._jnp.asarray(mask),
                self._jnp.asarray(self.last))
        if self._spec_cool > 0:
            # One cooldown tick per executed fused step (leader AND
            # followers collect in lockstep, so the counter stays
            # mirrored); when it reaches 0, _spec_precheck flips and
            # the batch loop hands the pool back to speculation at the
            # next drained round.
            self._spec_cool -= 1
        _M_STEP_SECONDS.observe(time.perf_counter() - t0,
                                phase='collect')

    def _step_or_dispatch(self, k: int) -> Optional[_InFlightStep]:
        """One 'step' op: a speculative round when it applies (host-
        synchronous, drained points only — then returns None), else a
        pipelined dispatch returning the in-flight handle. Shared by
        the leader's batch loop and multi-host followers so both sides
        make the identical choice from mirrored state."""
        if self._spec_once():
            return None
        return self._dispatch_step(k)

    def _step_once(self, k_force: Optional[int] = None,
                   want_tops_force: Optional[bool] = None) -> None:
        """Synchronous dispatch + collect (warmup and tests; the batch
        loop pipelines via _dispatch_step/_collect_step directly).
        `k_force` overrides the queue-dependent width choice."""
        if self._spec_once():
            return
        k = k_force if k_force is not None else self._choose_k()
        self._dispatch_step(k, want_tops_force=want_tops_force)
        self._collect_step()

    def _publish(self) -> None:
        """Push new tokens to streaming consumers and resolve finished
        slots (runs on the event loop, between device calls — stream
        queues are plain asyncio objects, never touched from a thread).
        Multi-host: the leader broadcasts ('reap',) so followers free
        the same slots at the same point in the op stream."""
        self._bcast(('reap',))
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            q = s['stream']
            if q is not None and s['sent'] < len(s['out']):
                for j in range(s['sent'], len(s['out'])):
                    q.put_nowait((s['out'][j], s['lps'][j], s['tops'][j]))
                s['sent'] = len(s['out'])
            if s['finish'] is not None:
                if q is not None:
                    q.put_nowait(None)           # end-of-stream sentinel
                self._finish_timing(i, s)
                fut = s['fut']
                if fut is not None and not fut.done():
                    fut.set_result((s['out'], s['finish'], s['lps'],
                                    s['tops']))
                if fut is not None:
                    self._resurrect_counts.pop(id(fut), None)
                self.slots[i] = None
                # Paged mode: the row's pages return to the free list
                # NOW (publish directly follows every collect and is
                # the mirrored reap point) — not when the slot is
                # reused. A stopped/cancelled row's memory is
                # admissible at the next drained round. An in-flight
                # lookahead step may still write these pages, but
                # reallocation only happens at drained points, and
                # device ops execute in dispatch order — the stale
                # write lands before the new occupant's prefill.
                self._release_slot_pages(i)
                # Clear the row's sampling/penalty params: use_pen keys
                # off pres/freq.any(), so a stale penalized row would
                # pin every later step onto the penalized compiled
                # variant ([B,V] counts carry) long after the request
                # left.
                self.temp[i] = self.topk[i] = self.topp[i] = 0
                self.pres[i] = self.freq[i] = 0.0

    def _finish_timing(self, slot: int, s: Dict[str, Any]) -> None:
        """Derive the finished request's TTFT/TPOT from the ring-aligned
        timestamps its slot entry carries — ONE histogram observe pair
        per REQUEST at publish time, never per-token telemetry on the
        decode loop — and stash the full decomposition for the HTTP
        handler (pop_timing → engine.queue/prefill/decode spans)."""
        self.flight.record(flight_lib.FINISH, slot, len(s['out']))
        t_sub = s.get('t_submit_ns')
        if t_sub is None:
            return                     # follower / warmup / no meta
        done_ns = time.monotonic_ns()
        n = len(s['out'])
        queue_s = max(0.0, (s['t_admit_ns'] - t_sub) / 1e9)
        prefill_s = max(0.0, (s['t_first_ns'] - s['t_admit_ns']) / 1e9)
        decode_s = max(0.0, (done_ns - s['t_first_ns']) / 1e9)
        ttft = queue_s + prefill_s
        tpot = decode_s / (n - 1) if n > 1 else None
        if s['finish'] == 'handoff':
            # Prefill-only rows skip the fleet latency/goodput
            # families: the DECODE replica finishes the same logical
            # request and counting both sides would double every
            # disagg request in the merged fleet view. The prefill
            # side's own signal is the admission-wait histogram (the
            # prefill_queue SLO kind) observed at _finish_admit.
            if s['fut'] is not None:
                self._timings[id(s['fut'])] = {
                    'submit_wall': s['t_submit_wall'],
                    'queue_s': queue_s, 'prefill_s': prefill_s,
                    'decode_s': 0.0, 'ttft_s': ttft, 'tpot_s': None,
                    'tokens': n, 'finish': s['finish']}
                while len(self._timings) > 1024:
                    self._timings.popitem(last=False)
            return
        _M_TTFT.observe(ttft)
        if tpot is not None:
            _M_TPOT.observe(tpot)
        # Per-class mirror + goodput judgment — `cls` entered the slot
        # already clamped to the closed registry at submit_nowait.
        cls = s.get('cls', request_class.DEFAULT_CLASS)
        _M_CLASS_TTFT.observe(ttft, cls=cls)
        if tpot is not None:
            _M_CLASS_TPOT.observe(tpot, cls=cls)
        _M_GOODPUT.inc(
            cls=cls,
            outcome=('good' if request_class.is_good(cls, ttft, tpot)
                     else 'slow'))
        if s['fut'] is not None:
            self._timings[id(s['fut'])] = {
                'submit_wall': s['t_submit_wall'], 'queue_s': queue_s,
                'prefill_s': prefill_s, 'decode_s': decode_s,
                'ttft_s': ttft, 'tpot_s': tpot, 'tokens': n,
                'finish': s['finish']}
            while len(self._timings) > 1024:
                self._timings.popitem(last=False)

    def pop_timing(self, fut) -> Optional[Dict[str, Any]]:
        """The finished request's latency decomposition, consumed ONCE
        by the HTTP handler that owns `fut` (which records the engine
        spans off the batch loop). None for requests that never
        admitted (429'd, cancelled in queue) or already-popped ones."""
        return self._timings.pop(id(fut), None)

    def _drain_admissible(self) -> list:
        """Pop admissible requests (non-blocking): bounded by free
        slots AND, in paged mode, by free pages (counting what evicting
        unshared prefix-store entries would return). An item that fits
        neither waits in `_hold` — FIFO: once something is held,
        nothing younger is popped past it, so a flood of short prompts
        can never starve a held long one. Admission blocks only on
        free pages, never on bucket shape."""
        items = []
        free_slots = sum(1 for s in self.slots if s is None)
        budget = (self.alloc.free_count + self._evictable_pages()
                  if self.paged else None)

        def fits(it) -> bool:
            nonlocal budget
            if budget is None:
                return True
            n = self._pages_needed(it)
            if n > budget:
                return False
            budget -= n
            return True

        held, self._hold = self._hold, []
        for it in held:
            if it[-1] is not None and it[-1].done():
                self._hold_waited.discard(id(it))
                # Dropping the item is where its resurrection budget
                # dies too — a stale id(fut) entry could otherwise be
                # inherited by a later future reusing the id. Disagg
                # marks (export/adopt payloads) die with it for the
                # same reason.
                self._resurrect_counts.pop(id(it[-1]), None)
                self._disagg_marks.pop(id(it[-1]), None)
                continue          # cancelled while waiting
            if len(items) < free_slots and fits(it):
                self._hold_waited.discard(id(it))
                items.append(it)
            else:
                self._hold.append(it)
        while (not self._hold and len(items) < free_slots and
               not self._queue.empty()):
            it = self._queue.get_nowait()
            if it[-1] is not None and it[-1].done():
                self._resurrect_counts.pop(id(it[-1]), None)
                self._disagg_marks.pop(id(it[-1]), None)
                continue          # cancelled while queued
            if fits(it):
                items.append(it)
            else:
                self._hold.append(it)
                if id(it) not in self._hold_waited:
                    # Counted once per request: this admission attempt
                    # found the pool short of pages.
                    self._hold_waited.add(id(it))
                    _M_PAGE_ALLOC.inc(outcome='wait')
        return items

    @staticmethod
    def _admit_groups(items) -> list:
        """Split pending requests into admit groups: same prompt bucket,
        power-of-two sizes (largest first) — each group is one prefill
        device call, and the compile count stays bounded at
        #buckets × log2(MAX_BATCH) programs."""
        by_bucket: Dict[int, list] = {}
        for it in items:
            by_bucket.setdefault(_bucket(len(it[0])), []).append(it)
        groups = []
        for _, lst in sorted(by_bucket.items()):
            i = 0
            while i < len(lst):
                size = 1
                while size * 2 <= len(lst) - i and size * 2 <= MAX_BATCH:
                    size *= 2
                groups.append(lst[i:i + size])
                i += size
        return groups

    async def _admit_pending(self, first_item=None) -> None:
        # A first_item was popped by the idle wait; it is younger than
        # anything in _hold (which is empty on that path) and older
        # than anything still queued — append + drain keeps FIFO.
        if first_item is not None:
            self._hold.append(first_item)
        # _drain_admissible drops cancelled futures (a 429'd batched
        # fan-out cancelling its enqueued siblings) — don't burn a
        # prefill on them.
        items = self._drain_admissible()
        # Handed-off requests (decode role) admit by page ADOPTION —
        # they carry their KV, so neither the grouped-prefill nor the
        # chunked path applies. Disagg is single-host (no _ctrl): the
        # multihost seam is documented in docs/serving.md.
        adopted = [it for it in items if self._mode_of(it) == 'adopt']
        adopted_ids = {id(it) for it in adopted}
        rest = [it for it in items if id(it) not in adopted_ids]
        grouped = [it for it in rest if not self._should_chunk(it)]
        chunked = [it for it in rest if self._should_chunk(it)]
        for item in adopted:
            try:
                await asyncio.to_thread(self._admit_adopted, item)
            except Exception as e:  # pylint: disable=broad-except
                await self._fail_all(e, extra=item)
        for group in self._admit_groups(grouped):
            if self._ctrl is not None:
                from skypilot_tpu.serve import multihost
                self._bcast(('admit', multihost.strip_items(group),
                             self._page_fp()))
            try:
                await asyncio.to_thread(self._admit_group, group)
            except Exception as e:  # pylint: disable=broad-except
                # _fail_all resets device state (fresh pool +
                # allocator); later groups/chunk starts admit against
                # the rebuilt state — never drop them unfailed, their
                # futures would hang forever.
                await self._fail_all(e, extra=group)
        for item in chunked:
            if self._ctrl is not None:
                from skypilot_tpu.serve import multihost
                self._bcast(('chunkstart',
                             multihost.strip_items([item])[0],
                             self._page_fp()))
            try:
                await asyncio.to_thread(self._start_chunked, item)
            except Exception as e:  # pylint: disable=broad-except
                await self._fail_all(e, extra=item)

    async def batch_loop(self) -> None:
        """Continuous scheduler: admit whenever a slot is free, step
        while anything is active. A late request joins after the
        in-flight fused call(s) drain (at most two while the pipeline
        is looking ahead) — it never waits for earlier requests to
        finish. Concurrent arrivals sharing a prompt bucket prefill in
        ONE device call (grouped admission). Admission, cancels and
        failure resets happen only HERE, at drained points — the
        pipeline invariant (collect always precedes buffer reuse)."""
        # First call builds device state (journal snapshot + pool
        # allocation + jit program construction): off-loop, so a
        # server starting its scheduler keeps answering /health.
        await asyncio.to_thread(self._ensure_state)
        # With the idle sweep armed, the fully-idle queue wait wakes
        # periodically so cold sessions spill even when no request
        # arrives to create a drained point.
        sweep_every = (min(max(self.kv_idle_spill_s, 0.05), 1.0)
                       if self.kv_idle_spill_s > 0 else None)
        while True:
            # Drained point: no step in flight (asserted in admit).
            self._process_cancels()
            if sweep_every is not None and self._sweep_due():
                # Spilling is device work (page export + device_get):
                # off-loop, like every other drained-point device op.
                await asyncio.to_thread(self._sweep_idle_prefixes)
            busy = any(s is not None for s in self.slots)
            if not busy:
                if self._hold:
                    # Requests waiting on free pages: with the pool
                    # idle, prefix-store eviction guarantees they fit
                    # (a reservation never exceeds the pool), so admit
                    # without blocking on new arrivals.
                    await self._admit_pending()
                    if not any(s is not None for s in self.slots):
                        await asyncio.sleep(0.05)   # defensive: no spin
                else:
                    try:
                        if sweep_every is None:
                            item = await self._queue.get()
                        else:
                            item = await asyncio.wait_for(
                                self._queue.get(), timeout=sweep_every)
                    except asyncio.TimeoutError:
                        continue    # loop top runs the idle sweep
                    await self._admit_pending(first_item=item)
                self._publish()         # want==1 resolves without a step
                continue
            if self._free_slot() is not None and (
                    self._hold or not self._queue.empty()):
                await self._admit_pending()
            self._publish()             # first tokens stream immediately
            if all(s is None for s in self.slots):
                continue                # the publish reaped everything
            pending = self._pending_chunks()
            if pending:
                # Chunked prefill interleave: ONE chunk per scheduling
                # round, round-robin over prefilling rows, so decode
                # rounds (below) keep running between chunks and a
                # long prompt never monopolizes the device.
                slot = pending[self._chunk_rr % len(pending)]
                self._chunk_rr += 1
                self._bcast(('chunk', slot))
                try:
                    await asyncio.to_thread(self._advance_chunk, slot)
                except Exception as e:  # pylint: disable=broad-except
                    await self._fail_all(e)
                    continue
                self._publish()     # a final chunk's first token streams
            if not any(self._row_active(s) for s in self.slots):
                continue                # everyone is still prefilling
            try:
                await self._step_round()
            except Exception as e:  # pylint: disable=broad-except
                await self._fail_all(e)
                continue
            self._publish()

    async def _step_round(self) -> None:
        """One scheduling round of device work, PIPELINED: dispatch
        step N, then — while nothing is queued, no cancel is pending
        and no active row can finish inside the in-flight call —
        dispatch step N+1 BEFORE collecting step N, so the device is
        never waiting on Python bookkeeping. Every collect is followed
        by a publish so tokens stream at the same cadence as the
        unpipelined loop. Speculative rounds are host-synchronous and
        run instead of the whole round when applicable. Broadcast
        discipline: ('step', k) at every dispatch, ('collect',) before
        every collect, ('reap',) inside every publish — followers
        replay the identical dispatch/collect interleaving, keeping
        host state (and therefore the next collective) in lockstep."""
        k = self._choose_k()
        self._bcast(('step', k))
        inflight = await asyncio.to_thread(self._step_or_dispatch, k)
        if inflight is None:            # a speculative round ran
            return
        while True:
            k2 = self._lookahead_k(inflight.k)
            if k2 is None:
                break
            self._bcast(('step', k2))
            nxt = await asyncio.to_thread(self._dispatch_step, k2)
            self._bcast(('collect',))
            await asyncio.to_thread(self._collect_step)
            self._publish()
            inflight = nxt
            if self._spec_precheck():
                # Let the next drained round try a speculative verify
                # instead of pipelining past it forever.
                break
        self._bcast(('collect',))
        await asyncio.to_thread(self._collect_step)

    async def _fail_all(self, e: Exception, extra=None) -> None:
        """Contain a device step/admit failure (the failed jit call was
        donated the cache buffer, so the whole pool must be rebuilt —
        see _reset_device_state) with the smallest blast radius:

          * rows that already FINISHED (result complete, publish just
            had not run yet) resolve normally — the failure happened
            after their last token;
          * requests that never SAMPLED a token (admit-group items the
            failure interrupted, rows still mid-chunked-prefill) are
            RESURRECTED: resubmitted internally at the front of the
            hold queue, at most RESURRECT_MAX times each;
          * only rows with tokens already emitted — whose KV state the
            reset destroys mid-generation — surface an error, and it
            is a STRUCTURED, RETRIABLE EngineResetError carrying
            tokens_emitted (docs/ROBUSTNESS.md).

        Items still sitting in self._queue / self._hold are untouched:
        they never reached the device and admit against the rebuilt
        pool."""
        logger.warning(f'Engine step/admit failed; resetting slot pool: '
                       f'{e}')
        # Followers hit the same failure executing the same op; this
        # tells them to rebuild device state in lockstep with us
        # (no-op on followers — their _ctrl is None).
        self._bcast(('reset',))

        def reset_error(n_emitted: int) -> EngineResetError:
            err = EngineResetError(
                f'engine reset after device failure '
                f'({type(e).__name__}: {e}); request state lost',
                tokens_emitted=n_emitted)
            err.__cause__ = e
            return err

        def fail(fut, stream_q, n_emitted: int) -> None:
            if fut is not None:
                self._resurrect_counts.pop(id(fut), None)

            def apply(fut=fut, stream_q=stream_q, n=n_emitted) -> None:
                if stream_q is not None:
                    stream_q.put_nowait(None)
                if fut is not None and not fut.done():
                    fut.set_exception(reset_error(n))
            deliver.append(apply)

        def try_resurrect(item) -> bool:
            fut = item[-1]
            if fut is None or fut.done():
                return False
            count = self._resurrect_counts.get(id(fut), 0)
            if count >= RESURRECT_MAX:
                return False
            self._resurrect_counts[id(fut)] = count + 1
            resurrected.append(item)
            return True

        resurrected: List[tuple] = []
        # Client-visible dispositions (future results/exceptions,
        # stream sentinels) are DEFERRED until the rebuild below
        # lands: waking a future yields a window in which its awaiter
        # runs with the pool still mid-rebuild — a retrying client
        # must never observe (or re-admit against) pre-reset state.
        deliver: List = []
        handled = set()          # id(fut) the slot loop dispositioned
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            fut, stream_q = s['fut'], s['stream']
            if fut is not None:
                handled.add(id(fut))
            if s['finish'] is not None:
                # The row completed BEFORE the failure — deliver its
                # result; undelivered tokens ride the stream first.
                self._finish_timing(i, s)
                if fut is not None:
                    self._resurrect_counts.pop(id(fut), None)

                def apply(s=s, fut=fut, stream_q=stream_q) -> None:
                    if stream_q is not None:
                        for j in range(s['sent'], len(s['out'])):
                            stream_q.put_nowait(
                                (s['out'][j], s['lps'][j], s['tops'][j]))
                        stream_q.put_nowait(None)
                    if fut is not None and not fut.done():
                        fut.set_result((s['out'], s['finish'],
                                        s['lps'], s['tops']))
                deliver.append(apply)
                continue
            emitted = len(s['out'])
            item = s.get('item') or (s.get('prefill') or {}).get('item')
            if emitted == 0 and s['sent'] == 0 and item is not None \
                    and try_resurrect(item):
                continue
            fail(fut, stream_q, emitted)
        if extra is not None:
            # One pending item, or a whole admit group: none of these
            # sampled a token (the failure interrupted their admission),
            # so they resurrect — the pre-fix behavior failed the whole
            # group with the device exception even though only the
            # device call was poisoned.
            items = extra if isinstance(extra, list) else [extra]
            for item in items:
                fut = item[-1]
                if fut is not None and id(fut) in handled:
                    continue     # partially admitted: slot loop owns it
                if try_resurrect(item):
                    continue
                fail(fut, item[-2], 0)
        try:
            # Off-loop: the rebuild snapshots the flight ring into the
            # sqlite journal (a connect can retry-sleep) and allocates
            # a fresh device pool — neither may stall the event loop
            # while other handlers are answering /health or queuing
            # requests. The deferred dispositions run on the loop
            # AFTER this lands (see `deliver` above).
            await asyncio.to_thread(self._reset_device_state,
                                    reason=f'{type(e).__name__}: {e}')
        except BaseException:
            # The rebuild ITSELF failed: the engine cannot serve.
            # The set-aside requests must not hang on futures nobody
            # will ever resolve — fail them before the error
            # propagates (the pre-resurrection code failed everything
            # up front and so never had this window).
            for item in resurrected:
                fail(item[-1], item[-2], 0)
            resurrected.clear()
            for apply in deliver:
                apply()
            raise
        for apply in deliver:
            apply()
        if resurrected:
            # Front of the hold queue, original admission order:
            # resurrected requests are older than anything held or
            # queued, and FIFO admission must stay fair.
            self._hold[:0] = resurrected
            self.resurrected_total += len(resurrected)
            _M_RESURRECTED.inc(len(resurrected))
            logger.info(f'Resurrected {len(resurrected)} request(s) '
                        f'that had not sampled a token; '
                        f'{len(self._hold)} held for re-admission.')
        while len(self._resurrect_counts) > 4096:
            self._resurrect_counts.pop(next(iter(self._resurrect_counts)))


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------

def _openai_error(web, msg: str, status: int = 400,
                  err_type: str = 'invalid_request_error'):
    return web.json_response(
        {'error': {'message': msg, 'type': err_type}}, status=status)


def _reset_error_response(web, e: EngineResetError):
    """EngineResetError → structured 503: the engine recovered (the
    pool was rebuilt) but this request's state was lost — retriable,
    and the client learns how many tokens it already received."""
    return web.json_response(
        {'error': {'message': str(e), 'type': 'engine_reset_error',
                   'retriable': True,
                   'tokens_emitted': e.tokens_emitted}},
        status=503, headers={'Retry-After': '1'})


def _resolve_prompts(engine: InferenceEngine, prompt) -> List[List[int]]:
    """OpenAI `prompt` field → one token-id list PER prompt. Accepts a
    string, a token-id list, a list of strings, or a list of token-id
    lists (the batched forms eval harnesses send — each becomes its own
    choice, continuous-batched in the slot pool)."""
    def encode(p) -> List[int]:
        if isinstance(p, list):
            if not all(isinstance(t, int) for t in p):
                raise ValueError('a prompt list must be all token ids')
            return [int(t) for t in p]
        return [int(t) for t in engine.tokenizer.encode(str(p))]

    if isinstance(prompt, list) and prompt and all(
            isinstance(t, int) for t in prompt):
        return [encode(prompt)]                  # one token-id prompt
    if isinstance(prompt, list):
        if not prompt:
            raise ValueError('empty prompt list')
        return [encode(p) for p in prompt]
    return [encode(prompt)]


def _check_len(engine: InferenceEngine, tokens: List[int],
               max_new: int) -> Optional[str]:
    # The batcher pads prompts up to a power-of-two bucket; admission is
    # checked against the bucketed length so a grouped request can always
    # be served in full.
    if _bucket(len(tokens)) + max_new > engine.max_len:
        return (f'bucketed prompt ({_bucket(len(tokens))}) + max new '
                f'tokens exceeds max_len {engine.max_len}')
    return None


class _SseChoice:
    """Per-choice streaming state: incremental detokenization, the
    stop-string holdback buffer, text offsets, and the engine future.
    Pieces awaiting release pair each token's OWN decoded text with
    that token's logprob info, so a streamed chunk's logprob always
    describes the text it carries and concatenating logprobs.tokens
    reconstructs the streamed text."""

    def __init__(self, engine, idx: int, fut, queue):
        from skypilot_tpu.data.tokenizer import StreamDecoder
        self.idx = idx
        self.fut = fut
        self.queue = queue
        self.decoder = StreamDecoder(engine.tokenizer)
        self.pend: List[list] = []    # [piece_text, lp, tops]
        self.pend_chars = 0
        self.emitted = 0              # chars sent (text_offset)
        self.stopped = False


async def _sse_response(request, engine: InferenceEngine,
                        prompts: List[List[int]], max_new: int, sampling,
                        stop_ids, make_chunks, web, stop_strings=None,
                        want_logprobs: bool = False, top_n: int = 0):
    """Shared SSE plumbing for /v1/completions and /v1/chat/completions,
    over ONE OR MORE choices (n>1 / batched prompts stream too — each
    entry of `prompts` is a choice, chunks carry its index).

    `make_chunks(delta_text, finish_reason, lp=None, index=0)` yields
    the JSON payload(s) for one event; `lp` is a (piece, logprob, tops,
    offset) tuple when the client asked for streaming logprobs.
    finish_reason is set on each choice's final event, per the OpenAI
    streaming contract. Ends with `data: [DONE]` after every choice
    finishes.

    Stop STRINGS stream too: emitted text is held back by
    len(longest stop)-1 chars so a stop string split across tokens can
    never leak to the client; on a match that choice is cancelled
    (engine.cancel) and its finish_reason='stop'.
    """
    temperature, top_k, top_p, pres, freq = sampling
    stops = ([] if stop_strings is None else
             [stop_strings] if isinstance(stop_strings, str)
             else list(stop_strings))
    hold = max((len(s) for s in stops), default=0) - 1
    cls = request_class.from_headers(request.headers)
    choices: List[_SseChoice] = []
    try:
        for idx, tokens in enumerate(prompts):
            q: asyncio.Queue = asyncio.Queue()
            fut = engine.submit_nowait(tokens, max_new, temperature,
                                       top_k, top_p, pres, freq,
                                       stop_ids=stop_ids,
                                       want_tops=(want_logprobs and
                                                  top_n > 0),
                                       stream_q=q, cls=cls)
            choices.append(_SseChoice(engine, idx, fut, q))
    except EngineOverloaded as e:
        # All-or-nothing like _submit_many: cancel enqueued siblings.
        for ch in choices:
            engine.cancel(ch.fut)
            ch.fut.cancel()
        return _openai_error(web, str(e), status=429,
                             err_type='overloaded_error')
    resp = web.StreamResponse(headers={
        'Content-Type': 'text/event-stream',
        'Cache-Control': 'no-cache',
        'X-Accel-Buffering': 'no',
    })
    await resp.prepare(request)

    async def send(payload) -> None:
        await resp.write(b'data: ' +
                         json_lib.dumps(payload).encode() + b'\n\n')

    async def emit_piece(ch: _SseChoice, piece: str, lp, tops) -> None:
        lp_info = ((piece, lp, tops[:top_n], ch.emitted)
                   if want_logprobs and lp is not None else None)
        if not piece and lp_info is None:
            return
        for payload in make_chunks(piece if piece else None, None,
                                   lp=lp_info, index=ch.idx):
            await send(payload)
        ch.emitted += len(piece)

    async def emit_until(ch: _SseChoice, cut: int) -> None:
        """Emit the choice's pend pieces truncated at joined-text index
        `cut` (logprobs past the cut are trimmed, like non-stream)."""
        remaining = cut
        for p_text, p_lp, p_tops in ch.pend:
            if remaining <= 0:
                break
            take = min(len(p_text), remaining)
            await emit_piece(ch, p_text[:take], p_lp, p_tops)
            remaining -= len(p_text)

    async def on_token(ch: _SseChoice, item) -> None:
        tok, lp, tops = item
        piece = ch.decoder.feed([tok])
        ch.pend.append([piece, lp, tops])
        ch.pend_chars += len(piece)
        cut = _stop_scan(''.join(p[0] for p in ch.pend), stops)
        if cut is not None:
            engine.cancel(ch.fut)
            await emit_until(ch, cut)
            ch.pend, ch.stopped = [], True
            return
        # Release from the front while the holdback (len(longest stop)
        # - 1 chars) stays covered by what remains.
        while ch.pend and ch.pend_chars - len(ch.pend[0][0]) >= hold:
            p_text, p_lp, p_tops = ch.pend.pop(0)
            ch.pend_chars -= len(p_text)
            await emit_piece(ch, p_text, p_lp, p_tops)

    async def finish_choice(ch: _SseChoice) -> None:
        out, finish, lps, all_tops = await ch.fut
        del out, lps, all_tops
        if ch.stopped:
            finish = 'stop'
        else:
            tail = ch.decoder.flush()
            if tail:
                # Held-back bytes belong to the last token's piece.
                if ch.pend:
                    ch.pend[-1][0] += tail
                else:
                    ch.pend.append([tail, None, []])
            joined = ''.join(p[0] for p in ch.pend)
            cut = _stop_scan(joined, stops)
            if cut is not None:
                finish = 'stop'
                await emit_until(ch, cut)
            else:
                await emit_until(ch, len(joined))
        for payload in make_chunks(None, finish, index=ch.idx):
            await send(payload)

    # Merge every choice's token queue into one stream (tokens arrive
    # interleaved as the batcher steps the pool).
    merged: asyncio.Queue = asyncio.Queue()

    async def pump(ch: _SseChoice) -> None:
        while True:
            item = await ch.queue.get()
            await merged.put((ch, item))
            if item is None:
                return

    pumps = [asyncio.ensure_future(pump(ch)) for ch in choices]
    try:
        for ch in choices:
            for payload in make_chunks(None, None, first=True,
                                       index=ch.idx):
                await send(payload)
        live = len(choices)
        while live:
            ch, item = await merged.get()
            if item is None:
                await finish_choice(ch)
                live -= 1
                continue
            if not ch.stopped:
                await on_token(ch, item)
        await resp.write(b'data: [DONE]\n\n')
    except Exception as e:  # pylint: disable=broad-except
        # Mid-stream failure: the status line already went out; the only
        # honest signal left is an error event + connection close. An
        # EngineResetError stays STRUCTURED here too — the client
        # learns the failure is retriable and how many tokens of this
        # stream it already holds (emitted chars track the stream; the
        # error carries the engine-side token count).
        logger.warning(f'SSE stream aborted: {e}')
        payload = {'error': {'message': str(e), 'type': 'server_error'}}
        if isinstance(e, EngineResetError):
            payload = {'error': {
                'message': str(e), 'type': 'engine_reset_error',
                'retriable': True, 'tokens_emitted': e.tokens_emitted}}
        try:
            await send(payload)
        except ConnectionError:
            pass
    finally:
        for p in pumps:
            p.cancel()
        # A dropped client must not leave prompts×n slots decoding to
        # max_tokens with no consumer. engine.cancel only reaches
        # ADMITTED slots; fut.cancel() marks still-QUEUED choices done
        # so admission skips them (same pair as the overload branch).
        for ch in choices:
            if not ch.fut.done():
                engine.cancel(ch.fut)
                ch.fut.cancel()
        # Streamed requests decompose too: timings exist for every
        # choice the batch loop published (cancelled-in-queue futures
        # simply have none to pop).
        _record_request_spans(engine, request.headers,
                              [ch.fut for ch in choices])
    await resp.write_eof()
    return resp


def build_app(engine: InferenceEngine):
    from aiohttp import web

    async def health(request):
        """Liveness + the SATURATION DOC the fleet scraper
        (observe/scrape.py) folds into its snapshot: queue depth,
        in-flight count and free KV pages are the engine's own
        admission view — the signal the saturation autoscaler and the
        LB's least-loaded tie-breaker act on."""
        del request
        if not engine.warm:
            return web.json_response({'status': 'warming'}, status=503)
        doc = {
            'status': 'ok',
            'queue_depth': engine.queue_depth(),
            'in_flight': engine.in_flight(),
        }
        if engine.paged and engine.alloc is not None:
            doc['kv_pages_free'] = engine.alloc.free_count
        if engine.host_store is not None:
            # Host spill-tier occupancy: the capacity headroom the
            # KV-hierarchy bench (and a saturation autoscaler) reads.
            doc['kv_host'] = engine.host_store.occupancy()
        if engine.kv_quant != 'none':
            doc['kv_quant'] = engine.kv_quant
        if engine.role:
            doc['role'] = engine.role
        if engine.handoff_store is not None:
            doc['handoff_port'] = engine.handoff_port
            doc['handoff_staged'] = len(engine.handoff_store)
        return web.json_response(doc)

    async def metrics(request):
        """Prometheus text exposition, rendered from the observe
        registry (docs/OBSERVABILITY.md catalog: skytpu_engine_* —
        counters incremented on the hot path, latency histograms from
        the decode pipeline, gauges sampled at scrape time). Consumed
        by the serve LB's instance-aware policy and any scraper."""
        del request
        _M_QUEUE_DEPTH.set(engine.queue_depth())
        _M_IN_FLIGHT.set(engine.in_flight())
        if engine.paged and engine.alloc is not None:
            _M_PAGES_FREE.set(engine.alloc.free_count)
            _M_PAGES_USED.set(engine.alloc.used_count)
        if engine.host_store is not None:
            _M_KV_SPILLED.set(engine.host_store.pages_spilled())
        if engine.handoff_store is not None:
            _M_HANDOFF_STAGED.set(len(engine.handoff_store))
        return web.Response(text=metrics_lib.render(),
                            content_type='text/plain')

    async def debug_flight(request):
        """Dump the flight ring (observe/flight.py): the hot loop's
        last dispatch/collect/admit/finish/spec/cancel/reset events,
        decoded, newest-last. `?limit=N` keeps the newest N (default
        4096 — the full ~64k ring is a big JSON document; ask for
        `?limit=0` to get it all, e.g. before restarting a replica)."""
        try:
            limit = int(request.query.get('limit', '4096'))
        except ValueError:
            return web.json_response({'error': 'bad limit'}, status=400)
        events = engine.flight.dump(limit if limit > 0 else None)
        return web.json_response({
            'capacity': engine.flight.capacity,
            'count': len(events),
            'events': events,
        })

    async def generate(request):
        body = await request.json()
        if 'text' in body:
            tokens = [int(t)
                      for t in engine.tokenizer.encode(str(body['text']))]
        else:
            tokens = [int(t) for t in body['tokens']]
        if not tokens:
            return web.json_response({'error': 'empty prompt'}, status=400)
        max_new = int(body.get('max_new_tokens', 64))
        if max_new < 1:
            return web.json_response({'error': 'max_new_tokens < 1'},
                                     status=400)
        msg = _check_len(engine, tokens, max_new)
        if msg:
            return web.json_response({'error': msg}, status=400)
        # Sampling params are validated/clamped at admission and passed as
        # PER-ROW runtime arrays — untrusted values can neither trigger a
        # recompile nor fail the whole batch (top_k is further clamped to
        # vocab inside decode.select_token_per_row).
        try:
            sampling = _parse_sampling(body)
            stop_ids = (tuple(int(i) for i in body['stop_token_ids'])
                        if 'stop_token_ids' in body else ())
        except (TypeError, ValueError) as e:
            return web.json_response({'error': f'bad sampling params: {e}'},
                                     status=400)
        try:
            fut = engine.submit_nowait(
                tokens, max_new, *sampling, stop_ids=stop_ids,
                cls=request_class.from_headers(request.headers))
            out, finish, lps, _tops = await fut
        except EngineOverloaded as e:
            return web.json_response({'error': str(e)}, status=429)
        except EngineResetError as e:
            return _reset_error_response(web, e)
        _record_request_spans(engine, request.headers, [fut])
        resp: Dict[str, Any] = {'tokens': out, 'finish_reason': finish,
                                'logprobs': lps}
        if 'text' in body:
            resp['text'] = engine.tokenizer.decode(out)
        return web.json_response(resp)

    async def openai_completions(request):
        """OpenAI-compatible completions (reference users serve through
        vLLM's OpenAI server — llm/qwen, llm/mixtral recipes curl
        /v1/completions; those clients work against this engine
        unchanged). Real tokenizer when serving an HF checkpoint;
        token-id and BATCHED (list) prompts honored; n/best_of sampling;
        logprobs=N with top-N alternatives; SSE streaming via
        stream=true incl. streaming logprobs and stop strings."""

        def bad(msg, status=400):
            return _openai_error(web, msg, status=status)

        body = await request.json()
        if not isinstance(body, dict):
            return bad('request body must be a JSON object')
        try:
            prompts = _resolve_prompts(engine, body.get('prompt', ''))
            if any(not t for t in prompts):
                raise ValueError('empty prompt')
            max_new = int(body.get('max_tokens', 16))
            if max_new < 1:
                raise ValueError('max_tokens must be >= 1')
            sampling = _parse_sampling(body, default_temperature=1.0)
            stop_ids = _parse_stop_ids(body, engine.tokenizer)
            stop_strings = body.get('stop')
            _truncate_at_stop_strings('', stop_strings)   # validate shape
            want_logprobs, top_n = _parse_logprobs(body)
            n, best_of = _parse_n(body)
            if body.get('stream') and best_of > n:
                # Ranking needs completed candidates; OpenAI rejects
                # best_of with stream too. n>1 and batched prompts
                # stream fine (per-choice indexed chunks).
                raise ValueError('best_of > n is not supported with '
                                 'stream=true')
        except (TypeError, ValueError) as e:
            return bad(f'invalid request: {e}')
        for tokens in prompts:
            msg = _check_len(engine, tokens, max_new)
            if msg:
                return bad(msg)
        created = int(time.time())
        rid = f'cmpl-{time.time_ns()}'
        model = body.get('model', engine.model_name)

        if body.get('stream'):
            def make_chunks(delta, finish, first=False, lp=None,
                            index=0):
                if first:
                    return
                if delta is None and finish is None and lp is None:
                    return
                lp_obj = None
                if lp is not None:
                    piece, lpv, tops, off = lp
                    lp_obj = {
                        'tokens': [piece], 'token_logprobs':
                            [round(lpv, 6)],
                        'top_logprobs': [
                            {engine.tokenizer.decode([i]): round(v, 6)
                             for i, v in tops}] if top_n else None,
                        'text_offset': [off]}
                yield {
                    'id': rid, 'object': 'text_completion',
                    'created': created, 'model': model,
                    'choices': [{'text': delta or '', 'index': index,
                                 'logprobs': lp_obj,
                                 'finish_reason': finish}],
                }
            # One choice per prompt×n, OpenAI index order.
            stream_prompts = [t for t in prompts for _ in range(n)]
            return await _sse_response(request, engine, stream_prompts,
                                       max_new, sampling, stop_ids,
                                       make_chunks, web,
                                       stop_strings=stop_strings,
                                       want_logprobs=want_logprobs,
                                       top_n=top_n)

        try:
            results, total_out = await _submit_many(
                engine, prompts, max_new, sampling, stop_ids, n, best_of,
                want_tops=want_logprobs and top_n > 0,
                headers=request.headers)
        except EngineOverloaded as e:
            return _openai_error(web, str(e), status=429,
                                 err_type='overloaded_error')
        except EngineResetError as e:
            return _reset_error_response(web, e)
        choices = []
        for idx, (out, finish, lps, tops) in enumerate(results):
            text = engine.tokenizer.decode(out)
            text, cut = _truncate_at_stop_strings(text, stop_strings)
            if cut:
                finish = 'stop'
            lp_obj = None
            if want_logprobs:
                lp_obj = _completion_logprobs(
                    engine.tokenizer, out, lps, text,
                    tops=[t[:top_n] for t in tops] if top_n else None)
            choices.append({'text': text, 'index': idx,
                            'logprobs': lp_obj, 'finish_reason': finish})
        n_prompt = sum(len(t) for t in prompts)
        return web.json_response({
            'id': rid,
            'object': 'text_completion',
            'created': created,
            'model': model,
            'choices': choices,
            'usage': {'prompt_tokens': n_prompt,
                      'completion_tokens': total_out,
                      'total_tokens': n_prompt + total_out},
        })

    async def openai_chat(request):
        """OpenAI-compatible chat completions with per-family templating
        (reference flagship: llm/qwen/README.md:60 curls
        /v1/chat/completions against its serve endpoint). The template is
        chosen from the tokenizer's special tokens (llama3 headers /
        ChatML / plain) — see data/tokenizer.py."""
        from skypilot_tpu.data import tokenizer as tokenizer_lib

        def bad(msg, status=400):
            return _openai_error(web, msg, status=status)

        body = await request.json()
        if not isinstance(body, dict):
            return bad('request body must be a JSON object')
        try:
            prompt_text = tokenizer_lib.apply_chat_template(
                body.get('messages'), engine.tokenizer.chat_family)
            # The template carries its specials literally — skip the
            # tokenizer post-processor (real Llama-3 tokenizer.json
            # auto-prepends BOS, which would double it here).
            tokens = [int(t) for t in engine.tokenizer.encode(
                prompt_text, add_special_tokens=False)]
            if not tokens:
                raise ValueError('empty prompt after templating')
            max_new = int(body.get('max_tokens',
                                   body.get('max_completion_tokens', 256)))
            if max_new < 1:
                raise ValueError('max_tokens must be >= 1')
            sampling = _parse_sampling(body, default_temperature=1.0)
            stop_ids = _parse_stop_ids(body, engine.tokenizer)
            stop_strings = body.get('stop')
            _truncate_at_stop_strings('', stop_strings)
            want_logprobs, top_n = _parse_logprobs(body, chat=True)
            if body.get('best_of') is not None:
                # Reject loudly, like the completions endpoint rejects
                # unsupported shapes — validating best_of and then
                # silently ignoring it (the old behavior) returns
                # results the client did not ask for.
                raise ValueError('best_of is not supported on '
                                 '/v1/chat/completions; use n')
            n, _ = _parse_n(body)
        except (TypeError, ValueError) as e:
            return bad(f'invalid request: {e}')
        msg = _check_len(engine, tokens, max_new)
        if msg:
            return bad(msg)
        created = int(time.time())
        rid = f'chatcmpl-{time.time_ns()}'
        model = body.get('model', engine.model_name)

        if body.get('stream'):
            def make_chunks(delta, finish, first=False, lp=None,
                            index=0):
                base = {'id': rid, 'object': 'chat.completion.chunk',
                        'created': created, 'model': model}
                if first:
                    yield {**base, 'choices': [{
                        'index': index,
                        'delta': {'role': 'assistant', 'content': ''},
                        'finish_reason': None}]}
                    return
                if delta is not None or lp is not None:
                    lp_obj = None
                    if lp is not None:
                        piece, lpv, tops, _off = lp
                        lp_obj = {'content': [{
                            'token': piece, 'logprob': round(lpv, 6),
                            'top_logprobs': [
                                {'token': engine.tokenizer.decode([i]),
                                 'logprob': round(v, 6)}
                                for i, v in tops] if top_n else None}]}
                    yield {**base, 'choices': [{
                        'index': index,
                        'delta': {'content': delta or ''},
                        'logprobs': lp_obj,
                        'finish_reason': None}]}
                if finish is not None:
                    yield {**base, 'choices': [{
                        'index': index, 'delta': {},
                        'finish_reason': finish}]}
            return await _sse_response(request, engine, [tokens] * n,
                                       max_new, sampling, stop_ids,
                                       make_chunks, web,
                                       stop_strings=stop_strings,
                                       want_logprobs=want_logprobs,
                                       top_n=top_n)

        try:
            results, total_out = await _submit_many(
                engine, [tokens], max_new, sampling, stop_ids, n, n,
                want_tops=want_logprobs and top_n > 0,
                headers=request.headers)
        except EngineOverloaded as e:
            return _openai_error(web, str(e), status=429,
                                 err_type='overloaded_error')
        except EngineResetError as e:
            return _reset_error_response(web, e)
        choices = []
        for idx, (out, finish, lps, tops) in enumerate(results):
            text = engine.tokenizer.decode(out)
            text, cut = _truncate_at_stop_strings(text, stop_strings)
            if cut:
                finish = 'stop'
            lp_obj = None
            if want_logprobs:
                # Chat logprobs format: content entries of
                # {token, logprob, top_logprobs}, trimmed to the
                # (possibly stop-string-cut) returned text.
                flat = _completion_logprobs(
                    engine.tokenizer, out, lps, text,
                    tops=[t[:top_n] for t in tops] if top_n else None)
                content = []
                for j, (p, v) in enumerate(zip(flat['tokens'],
                                               flat['token_logprobs'])):
                    entry = {'token': p, 'logprob': v}
                    if top_n:
                        entry['top_logprobs'] = [
                            {'token': tt, 'logprob': tv} for tt, tv in
                            flat['top_logprobs'][j].items()]
                    content.append(entry)
                lp_obj = {'content': content}
            choices.append({'index': idx,
                            'message': {'role': 'assistant',
                                        'content': text},
                            'logprobs': lp_obj,
                            'finish_reason': finish})
        return web.json_response({
            'id': rid,
            'object': 'chat.completion',
            'created': created,
            'model': model,
            'choices': choices,
            'usage': {'prompt_tokens': len(tokens),
                      'completion_tokens': total_out,
                      'total_tokens': len(tokens) + total_out},
        })

    async def openai_models(request):
        del request
        return web.json_response({
            'object': 'list',
            'data': [{'id': engine.model_name, 'object': 'model',
                      'owned_by': 'skytpu'}],
        })

    # -- disaggregated prefill/decode (serve/disagg; docs/serving.md) --
    def _disagg_unsupported(msg: str):
        return web.json_response(
            {'error': {'message': msg, 'type': 'handoff_unsupported'}},
            status=501)

    def _disagg_done_doc(orig: str, body, out, finish, lps,
                         n_prompt: int = 0):
        """The final response document (in the ORIGINAL endpoint's
        shape) for a request that completed at prefill admission —
        first token hit a stop id or max_new == 1, so there is no
        decode phase to hand off."""
        if orig == '/v1/completions':
            text = engine.tokenizer.decode(out)
            return {
                'id': f'cmpl-{time.time_ns()}',
                'object': 'text_completion', 'created': int(time.time()),
                'model': body.get('model', engine.model_name),
                'choices': [{'text': text, 'index': 0, 'logprobs': None,
                             'finish_reason': finish}],
                'usage': {'prompt_tokens': n_prompt,
                          'completion_tokens': len(out),
                          'total_tokens': n_prompt + len(out)},
            }
        doc = {'tokens': out, 'finish_reason': finish, 'logprobs': lps}
        if 'text' in body:
            doc['text'] = engine.tokenizer.decode(out)
        return doc

    async def disagg_prefill(request):
        """Stage 1 of the two-stage disagg pipeline (the LB drives
        it): prefill the prompt + sample the first token on THIS
        replica, export the KV pages, ship them npy-framed to the
        decode replica named by X-Skytpu-Handoff-Target, and answer
        with the handoff id the LB passes to /disagg/continue. The
        request body is the ORIGINAL endpoint's body (?orig= names
        it), so the LB forwards bytes, not a re-encoding."""
        if not engine.paged:
            return _disagg_unsupported(
                'disagg requires paged mode (SKYTPU_ENGINE_PAGED=1)')
        if engine._ctrl is not None:  # pylint: disable=protected-access
            return _disagg_unsupported(
                'disagg prefill is single-host for now (multi-host '
                'page export is a documented seam, docs/serving.md)')
        target = request.headers.get('X-Skytpu-Handoff-Target',
                                     '').strip()
        if not target:
            return web.json_response(
                {'error': 'missing X-Skytpu-Handoff-Target header'},
                status=400)
        try:
            body = await request.json()
        except ValueError:
            return web.json_response({'error': 'bad json'}, status=400)
        orig = request.query.get('orig', '/generate')
        want_tops = False
        try:
            if orig == '/v1/completions':
                prompts = _resolve_prompts(engine, body.get('prompt', ''))
                if len(prompts) != 1 or not prompts[0]:
                    raise ValueError('disagg prefill serves exactly one '
                                     'non-empty prompt')
                tokens = prompts[0]
                max_new = int(body.get('max_tokens', 16))
                sampling = _parse_sampling(body, default_temperature=1.0)
                stop_ids = _parse_stop_ids(body, engine.tokenizer)
                want_logprobs, top_n = _parse_logprobs(body)
                want_tops = want_logprobs and top_n > 0
                n, best_of = _parse_n(body)
                if n != 1 or best_of != 1:
                    raise ValueError('disagg prefill serves '
                                     'single-choice requests (n=1)')
            elif orig == '/generate':
                if 'text' in body:
                    tokens = [int(t) for t in
                              engine.tokenizer.encode(str(body['text']))]
                else:
                    tokens = [int(t) for t in body['tokens']]
                if not tokens:
                    raise ValueError('empty prompt')
                max_new = int(body.get('max_new_tokens', 64))
                sampling = _parse_sampling(body)
                stop_ids = (tuple(int(i) for i in body['stop_token_ids'])
                            if 'stop_token_ids' in body else ())
            else:
                raise ValueError(f'unsupported orig endpoint {orig!r}')
            if max_new < 1:
                raise ValueError('max new tokens must be >= 1')
        except (TypeError, ValueError, KeyError) as e:
            return web.json_response(
                {'error': f'invalid request: {e}'}, status=400)
        msg = _check_len(engine, tokens, max_new)
        if msg:
            return web.json_response({'error': msg}, status=400)
        cls = request_class.from_headers(request.headers)
        try:
            fut = engine.submit_nowait(tokens, max_new, *sampling,
                                       stop_ids=stop_ids,
                                       want_tops=want_tops, cls=cls)
            engine.mark_prefill_export(fut)
            out, finish, lps, tops = await fut
        except EngineOverloaded as e:
            return web.json_response({'error': str(e)}, status=429)
        except EngineResetError as e:
            return _reset_error_response(web, e)
        _record_request_spans(engine, request.headers, [fut])
        blob = engine.pop_export(fut)
        if finish != 'handoff':
            # Completed outright at admission — nothing to hand off.
            return web.json_response(
                {'done': _disagg_done_doc(orig, body, out, finish, lps,
                                          n_prompt=len(tokens))})
        if blob is None:
            # finish says handoff but the export stash aged out (a
            # pathological handler backlog): retriable.
            return web.json_response(
                {'error': {'message': 'export blob lost before send',
                           'type': 'handoff_send_error',
                           'retriable': True}},
                status=503, headers={'Retry-After': '1'})
        from skypilot_tpu.serve.disagg import handoff as handoff_lib
        from skypilot_tpu.utils import framed
        temperature, top_k, top_p, pres, freq = sampling
        arrays = {'a': blob['a'], 'b': blob['b']}
        meta = handoff_lib.build_meta(
            handoff_id=handoff_lib.new_handoff_id(),
            model=engine.model_name,
            vocab_size=engine.cfg.vocab_size,
            page_size=engine.page_size, family=engine.cache_family(),
            bucket=blob['bucket'], tokens=tokens, max_new=max_new,
            first_token=int(out[0]),
            first_lp=(float(lps[0]) if lps else 0.0),
            first_tops=(tops[0] if tops else []),
            temperature=temperature, top_k=top_k, top_p=top_p,
            presence_penalty=pres, frequency_penalty=freq,
            stop_ids=list(stop_ids), want_tops=want_tops, cls=cls,
            kv_sha256=handoff_lib.kv_fingerprint(arrays))
        try:
            await asyncio.to_thread(handoff_lib.send,
                                    framed.parse_addr(target), meta,
                                    arrays)
        except handoff_lib.HandoffError as e:
            _M_HANDOFF.inc(stage='send', outcome='error')
            status = 503 if e.retriable else 400
            headers = {'Retry-After': '1'} if e.retriable else None
            return web.json_response(
                {'error': {'message': str(e),
                           'type': 'handoff_send_error', 'kind': e.kind,
                           'retriable': e.retriable}},
                status=status, headers=headers)
        _M_HANDOFF.inc(stage='send', outcome='ok')
        return web.json_response(
            {'handoff': {'id': meta['handoff_id'],
                         'first_token': int(out[0]),
                         'prompt_tokens': len(tokens)}})

    async def disagg_continue(request):
        """Stage 2: adopt the staged pages into this replica's pool
        and run the decode phase, answering in the ORIGINAL endpoint's
        shape (?orig=), SSE streaming included. A missing handoff id
        (expired, already consumed, or never received — the prefill
        replica may have died mid-send) is a structured retriable 503:
        the LB re-runs the whole pipeline."""
        if engine.handoff_store is None:
            return _disagg_unsupported(
                'no handoff receiver on this replica '
                '(set --handoff-port)')
        try:
            body = await request.json()
        except ValueError:
            return web.json_response({'error': 'bad json'}, status=400)
        orig = request.query.get('orig', '/generate')
        hid = str(body.get('handoff_id', ''))
        entry = engine.handoff_store.pop(hid) if hid else None
        _M_HANDOFF_STAGED.set(len(engine.handoff_store))
        if entry is None:
            return web.json_response(
                {'error': {'message': f'handoff {hid!r} not staged '
                                      f'(expired, consumed, or never '
                                      f'received)',
                           'type': 'handoff_missing', 'retriable': True}},
                status=503, headers={'Retry-After': '0'})
        meta, arrays = entry
        stream = bool(body.get('stream'))
        if not stream:
            try:
                fut = engine.submit_adopted(meta, arrays)
                out, finish, lps, tops = await fut
            except EngineOverloaded as e:
                return web.json_response({'error': str(e)}, status=429)
            except EngineResetError as e:
                return _reset_error_response(web, e)
            del tops
            _record_request_spans(engine, request.headers, [fut])
            return web.json_response(
                _disagg_done_doc(orig, body, out, finish, lps,
                                 n_prompt=len(meta['tokens'])))
        # SSE decode stream in the completions chunk shape (the one
        # streaming transport the disagg router routes — the LB's
        # eligibility check pins it).
        from skypilot_tpu.data.tokenizer import StreamDecoder
        try:
            q: asyncio.Queue = asyncio.Queue()
            fut = engine.submit_adopted(meta, arrays, stream_q=q)
        except EngineOverloaded as e:
            return web.json_response({'error': str(e)}, status=429)
        rid = f'cmpl-{time.time_ns()}'
        created = int(time.time())
        model = body.get('model', engine.model_name)
        resp = web.StreamResponse()
        resp.headers['Content-Type'] = 'text/event-stream'
        resp.headers['Cache-Control'] = 'no-cache'
        await resp.prepare(request)

        async def send_doc(doc) -> None:
            await resp.write(b'data: ' +
                             json_lib.dumps(doc).encode() + b'\n\n')

        decoder = StreamDecoder(engine.tokenizer)
        try:
            while True:
                item = await q.get()
                if item is None:
                    break
                piece = decoder.feed([item[0]])
                if piece:
                    await send_doc({
                        'id': rid, 'object': 'text_completion',
                        'created': created, 'model': model,
                        'choices': [{'text': piece, 'index': 0,
                                     'logprobs': None,
                                     'finish_reason': None}]})
            try:
                _, finish, _, _ = await fut
            except EngineResetError as e:
                # Mid-stream reset: the structured event IS the
                # truncation marker ([DONE] never arrives).
                await send_doc({'error': {
                    'message': str(e), 'type': 'engine_reset_error',
                    'retriable': True,
                    'tokens_emitted': e.tokens_emitted}})
                return resp
            tail = decoder.flush()
            await send_doc({
                'id': rid, 'object': 'text_completion',
                'created': created, 'model': model,
                'choices': [{'text': tail, 'index': 0, 'logprobs': None,
                             'finish_reason': finish}]})
            await resp.write(b'data: [DONE]\n\n')
        except (ConnectionResetError, OSError):
            engine.cancel(fut)
        finally:
            _record_request_spans(engine, request.headers, [fut])
        return resp

    app = web.Application()
    app.router.add_get('/health', health)
    app.router.add_get('/', health)
    app.router.add_get('/metrics', metrics)
    app.router.add_get('/debug/flight', debug_flight)
    app.router.add_post('/generate', generate)
    app.router.add_post('/v1/completions', openai_completions)
    app.router.add_post('/v1/chat/completions', openai_chat)
    app.router.add_get('/v1/models', openai_models)
    app.router.add_post('/disagg/prefill', disagg_prefill)
    app.router.add_post('/disagg/continue', disagg_continue)

    async def _start(app_):
        engine.start()
        # Decode-side page handoff listener (framed TCP): any paged
        # replica with a handoff port can adopt — the CONTROL plane
        # decides which pool a replica serves in; the engine itself is
        # role-capable both ways (a decode replica still serves
        # monolithic traffic for request shapes the two-stage router
        # does not cover).
        if engine.paged and engine.handoff_port:
            from skypilot_tpu.serve.disagg import handoff as handoff_lib
            engine.handoff_store = handoff_lib.HandoffStore()
            engine._handoff_receiver = handoff_lib.HandoffReceiver(
                '0.0.0.0', engine.handoff_port, engine.handoff_store,
                validate=engine.handoff_validate).start()
            app_['handoff_receiver'] = engine._handoff_receiver

    async def _observe_gc_loop():
        # The replica writes span rows per request and multi-MB
        # flight_snapshot rows per failure reset into its HOST-LOCAL
        # journal DB — no API server or serve controller ever sees
        # that file, so this process must collect it itself (same
        # contract as the server/controller GC loops).
        from skypilot_tpu import observe
        while True:
            await asyncio.sleep(3600)
            try:
                await asyncio.to_thread(observe.gc)
            except Exception:  # pylint: disable=broad-except
                logger.warning('observe GC pass failed (will retry)',
                               exc_info=True)
            if engine.handoff_store is not None:
                # Orphaned handoffs also sweep lazily on every
                # put/pop; this catches a fully idle store.
                engine.handoff_store.sweep()

    async def _start_gc(app_):
        app_['observe_gc'] = asyncio.create_task(_observe_gc_loop())

    async def _stop_gc(app_):
        task = app_.pop('observe_gc', None)
        if task is not None:
            task.cancel()
        receiver = app_.pop('handoff_receiver', None)
        if receiver is not None:
            await asyncio.to_thread(receiver.stop)

    app.on_startup.append(_start)
    app.on_startup.append(_start_gc)
    app.on_cleanup.append(_stop_gc)
    return app


def build_parser() -> argparse.ArgumentParser:
    """The engine CLI parser (factored out so tests can pin the
    gang-env defaults against the REAL production parser)."""
    parser = argparse.ArgumentParser(prog='skytpu-engine')
    parser.add_argument('--model', default=None,
                        help='Preset name (models.list_presets); optional '
                             'when --hf-dir is given.')
    parser.add_argument('--ckpt-dir', default=None,
                        help='Orbax trainer checkpoint to serve.')
    parser.add_argument('--hf-dir', default=None,
                        help='HF checkpoint directory (safetensors + '
                             'tokenizer.json) to serve.')
    parser.add_argument('--tokenizer', default=None,
                        help='Path to a tokenizer.json (overrides the '
                             'one in --hf-dir).')
    parser.add_argument('--max-len', type=int, default=None)
    parser.add_argument('--mesh', default=None,
                        help="Shard serving over a device mesh, e.g. "
                             "'tensor=8' or 'data=2,tensor=4' (the "
                             'reference serves 8-chip TP replicas).')
    parser.add_argument('--quantize', choices=['int8'], default=None,
                        help='Weight-only quantization for serving '
                             '(dense Llama and MLA families; composes '
                             'with --mesh).')
    parser.add_argument('--warm-buckets', default='all',
                        help="Comma-separated prompt buckets to pre-"
                             "compile, or 'all' (the default: /health "
                             'flips warm only when NO client request '
                             'can ever hit a fresh XLA compile — pass '
                             "'16' for a faster, cliffier boot).")
    # Multi-host serving: one replica spanning a whole (multi-host)
    # slice, like the reference's multi-host vLLM/JetStream replicas.
    # Defaults come from the gang env the slice driver exports, so a
    # multi-host `skytpu serve up` needs no extra flags.
    parser.add_argument('--coordinator',
                        default=knobs.get_str(
                            'SKYTPU_COORDINATOR_ADDRESS'),
                        help='jax.distributed coordinator host:port '
                             '(multi-host serving).')
    parser.add_argument('--num-processes', type=int,
                        default=knobs.get_int('SKYTPU_NUM_PROCESSES'))
    parser.add_argument('--process-id', type=int,
                        default=knobs.get_int('SKYTPU_NODE_RANK'))
    parser.add_argument('--seed', type=int, default=None,
                        help='Pin the sampling RNG (multi-host sets '
                             'this automatically).')
    parser.add_argument('--port', type=int,
                        default=knobs.get_int('SKYTPU_SERVE_PORT'))
    parser.add_argument('--host', default='0.0.0.0')
    # Disaggregated serving: the framed-TCP port this replica accepts
    # KV page handoffs on (serve/disagg). Default -1 = the fixed
    # HANDOFF_PORT_OFFSET convention (HTTP port + 1000) the LB derives
    # decode targets from; 0 disables the receiver entirely.
    parser.add_argument('--handoff-port', type=int,
                        default=knobs.get_int('SKYTPU_ENGINE_HANDOFF_PORT'))
    return parser


def main() -> None:
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    from aiohttp import web
    args = build_parser().parse_args()
    multihost_on = bool(args.coordinator) and args.num_processes > 1
    seed = args.seed
    if multihost_on:
        from skypilot_tpu.serve import multihost
        multihost.require_token()   # refuse guessable tokens pre-boot
        multihost.init_distributed(args.coordinator, args.num_processes,
                                   args.process_id)
        if not args.mesh:
            raise ValueError('multi-host serving needs --mesh spanning '
                             'the global device count (e.g. tensor=8 '
                             'on a 2-host v5e-8... slice).')
        if seed is None:
            # Every process in THIS gang must draw identical samples,
            # but replicas/restarts must not correlate: the leader
            # draws a fresh seed and ships it in the warmup op;
            # followers get a placeholder that op overwrites.
            seed = (int(time.time_ns()) % (2**31) if args.process_id == 0
                    else 0)
    engine = InferenceEngine(args.model or (None if args.hf_dir
                                            else 'llama-1b'),
                             ckpt_dir=args.ckpt_dir, hf_dir=args.hf_dir,
                             tokenizer_path=args.tokenizer,
                             max_len=args.max_len, quantize=args.quantize,
                             mesh=args.mesh, seed=seed)
    # KV handoff receiver port (disagg decode role): -1 = derive from
    # the HTTP port by the fixed offset, 0 = disabled. Multi-host
    # serving disables it — page export across a gang is a documented
    # seam (docs/serving.md).
    if args.handoff_port < 0:
        from skypilot_tpu.serve.disagg import handoff as handoff_lib
        engine.handoff_port = args.port + handoff_lib.HANDOFF_PORT_OFFSET
    else:
        engine.handoff_port = args.handoff_port or None
    if multihost_on:
        engine.handoff_port = None
    if args.warm_buckets == 'all':
        buckets = engine.all_buckets()
    else:
        buckets = [int(b) for b in args.warm_buckets.split(',') if b]
    if multihost_on and args.process_id != 0:
        # Follower: mirror the leader's ops forever (warmup arrives as
        # the first control op); no HTTP frontend.
        multihost.follower_serve(engine, args.coordinator)
        return
    if multihost_on:
        engine._ctrl = multihost.ControlLeader(args.coordinator,
                                               args.num_processes)
        # The warmup op also carries the leader's attention backend:
        # all processes must compile (and later select) the SAME
        # step/verify/chunk program family or the gang's collectives
        # would diverge — env skew across hosts must not be able to
        # split the variant matrix.
        engine._bcast(('warmup', buckets, seed, engine.attn_backend))
    engine.warmup(buckets=buckets)   # readiness flips only once fast
    web.run_app(build_app(engine), host=args.host, port=args.port,
                print=None)


if __name__ == '__main__':
    main()
