"""Native inference engine: HTTP server over the KV-cache decode path.

Reference analog: the reference serves TPU models through external
engines (JetStream/vLLM recipes, examples/tpu/v6e/README.md:119-127);
this framework owns the model code, so the engine is native and ~200
lines: aiohttp front, a dynamic batcher, and models/decode.py underneath.

TPU-first design:
  - **Bucketed dynamic batching**: concurrent requests are grouped
    within a small window; a group shares one `decode.generate` call.
    Static shapes rule on TPU, so groups are keyed by (prompt-length
    bucket, sampling params) — each key compiles once and is cached by
    jax forever after. MIXED prompt lengths batch together: prompts are
    right-padded to the bucket and models/decode.py's ragged path
    (per-row cache lengths) makes padding invisible.
  - **Byte-level text mode**: POST {'text': ...} uses the hermetic
    byte tokenizer (data/loader.py), so the engine serves text without
    downloads; token mode ({'tokens': [...]}) is the raw interface.
  - **Checkpoint loading**: --ckpt-dir restores trainer checkpoints
    (orbax, train/checkpoints.py) so `skytpu jobs launch` training and
    `skytpu serve up` serving share weights end-to-end.

Run: python -m skypilot_tpu.serve.engine --model llama-1b --port 8000
(the serve plane sets $SKYTPU_SERVE_PORT; see examples/serve-llama-1b).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

MAX_BATCH = int(os.environ.get('SKYTPU_ENGINE_MAX_BATCH', '8'))
BATCH_WINDOW_S = float(os.environ.get('SKYTPU_ENGINE_BATCH_WINDOW', '0.01'))


def _bucket(n: int, floor: int = 16) -> int:
    """Round up to a power of two (bounded compile count)."""
    b = floor
    while b < n:
        b *= 2
    return b


class InferenceEngine:
    """Owns params + the batched generate loop."""

    def __init__(self, model: str, ckpt_dir: Optional[str] = None,
                 max_len: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        from skypilot_tpu.models import decode as decode_lib
        from skypilot_tpu.models import get_config, mla, module_for
        self._jnp = jnp
        self.cfg = get_config(model)
        # MLA models generate over the latent cache (models/mla.py);
        # everything else over the K/V cache. Same call surface.
        self._decode = (mla if isinstance(self.cfg, mla.MLAConfig)
                        else decode_lib)
        self.max_len = max_len or min(self.cfg.max_seq_len, 2048)
        if ckpt_dir:
            from skypilot_tpu.parallel import MeshSpec, build_mesh
            from skypilot_tpu.train import checkpoints, train_lib
            mesh = build_mesh(MeshSpec())
            tx = train_lib.default_optimizer(learning_rate=1e-4,
                                             warmup_steps=1, total_steps=2)
            with checkpoints.Checkpointer(ckpt_dir) as ckpt:
                state = ckpt.restore(self.cfg, mesh, tx)
                if state is None:
                    raise FileNotFoundError(
                        f'No checkpoint under {ckpt_dir!r}.')
                params = state.params
            logger.info(f'Restored checkpoint step {int(state.step)} '
                        f'from {ckpt_dir}.')
        else:
            mod = module_for(self.cfg)
            params = jax.jit(lambda r: mod.init_params(r, self.cfg))(
                jax.random.PRNGKey(0))
            logger.info('No --ckpt-dir: serving randomly-initialized '
                        'params (benchmark/demo mode).')
        self.params = decode_lib.cast_params_for_decode(params, self.cfg)
        # Created by start() on the SERVING event loop: an asyncio.Queue
        # binds to the loop that first awaits it, and the engine object
        # may outlive a loop (tests; server restarts).
        self._queue: Optional[asyncio.Queue] = None
        self.warm = False

    def start(self) -> None:
        """Bind the batcher to the current event loop (call at server
        startup)."""
        self._queue = asyncio.Queue()
        asyncio.create_task(self.batch_loop())

    def warmup(self) -> None:
        # Compile through the SAME call signature _run_group uses
        # (prompt_lengths + rng arrays present): a different jit pytree
        # (None vs array) would compile a program no real request ever
        # hits, and /health would flip while the first request still
        # pays the full compile.
        import jax
        jnp = self._jnp
        self._decode.generate(
            self.params, jnp.zeros((1, 16), jnp.int32), self.cfg, 16,
            max_len=self.max_len, temperature=0.0, top_k=None, top_p=None,
            prompt_lengths=jnp.asarray([8], jnp.int32),
            rng=jax.random.PRNGKey(0))
        self.warm = True
        logger.info('Engine warm (first generate compiled).')

    # -- batching ----------------------------------------------------------
    async def submit(self, tokens: List[int], max_new: int,
                     temperature: float, top_k: Optional[int],
                     top_p: Optional[float]) -> List[int]:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((tokens, max_new, temperature, top_k, top_p,
                               fut))
        return await fut

    async def batch_loop(self) -> None:
        """Group compatible requests, run one generate per group."""
        while True:
            first = await self._queue.get()
            group = [first]
            deadline = time.monotonic() + BATCH_WINDOW_S
            while len(group) < MAX_BATCH:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(),
                                                  timeout)
                except asyncio.TimeoutError:
                    break
                # Same prompt-length BUCKET and sampling params → same
                # compiled program (ragged right-padding inside the
                # bucket); anything else goes back on the queue for the
                # next group.
                if (_bucket(len(item[0])) == _bucket(len(first[0])) and
                        item[2:5] == first[2:5]):
                    group.append(item)
                else:
                    await self._queue.put(item)
                    break
            await self._run_group(group)

    async def _run_group(self, group) -> None:
        jnp = self._jnp
        lens = [len(g[0]) for g in group]
        s = _bucket(max(lens))
        tokens = jnp.asarray(
            [g[0] + [0] * (s - len(g[0])) for g in group], jnp.int32)
        lengths = jnp.asarray(lens, jnp.int32)
        max_new = min(_bucket(max(g[1] for g in group)), self.max_len - s)
        _, _, temperature, top_k, top_p, _ = group[0]
        import jax
        try:
            out = await asyncio.to_thread(
                self._decode.generate, self.params, tokens, self.cfg,
                max_new, max_len=self.max_len, temperature=temperature,
                top_k=top_k, top_p=top_p, prompt_lengths=lengths,
                rng=jax.random.PRNGKey(int(time.time_ns()) % (2**31)))
            out = jax.device_get(out)
            for i, (_, want_new, *_rest, fut) in enumerate(group):
                if not fut.done():
                    fut.set_result([int(t) for t in out[i][:want_new]])
        except Exception as e:  # pylint: disable=broad-except
            for *_a, fut in group:
                if not fut.done():
                    fut.set_exception(e)


def build_app(engine: InferenceEngine):
    from aiohttp import web

    async def health(request):
        del request
        if not engine.warm:
            return web.json_response({'status': 'warming'}, status=503)
        return web.json_response({'status': 'ok'})

    async def generate(request):
        body = await request.json()
        if 'text' in body:
            from skypilot_tpu.data import loader as loader_lib
            tokens = [int(t) for t in
                      loader_lib.tokenize_text(body['text'])]
        else:
            tokens = [int(t) for t in body['tokens']]
        if not tokens:
            return web.json_response({'error': 'empty prompt'}, status=400)
        max_new = int(body.get('max_new_tokens', 64))
        if max_new < 1:
            return web.json_response({'error': 'max_new_tokens < 1'},
                                     status=400)
        # The batcher pads prompts up to a power-of-two bucket; admission
        # is checked against the bucketed length so a grouped request can
        # always be served in full.
        if _bucket(len(tokens)) + max_new > engine.max_len:
            return web.json_response(
                {'error': f'bucketed prompt ({_bucket(len(tokens))}) + '
                          f'max_new_tokens exceeds max_len '
                          f'{engine.max_len}'}, status=400)
        top_k = body.get('top_k')
        top_p = body.get('top_p')
        out = await engine.submit(
            tokens, max_new, float(body.get('temperature', 0.0)),
            int(top_k) if top_k is not None else None,
            float(top_p) if top_p is not None else None)
        resp: Dict[str, Any] = {'tokens': out}
        if 'text' in body:
            resp['text'] = bytes(t for t in out if t < 256).decode(
                'utf-8', errors='replace')
        return web.json_response(resp)

    app = web.Application()
    app.router.add_get('/health', health)
    app.router.add_get('/', health)
    app.router.add_post('/generate', generate)

    async def _start(app_):
        del app_
        engine.start()

    app.on_startup.append(_start)
    return app


def main() -> None:
    from aiohttp import web
    parser = argparse.ArgumentParser(prog='skytpu-engine')
    parser.add_argument('--model', default='llama-1b')
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--max-len', type=int, default=None)
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYTPU_SERVE_PORT',
                                                   '8000')))
    parser.add_argument('--host', default='0.0.0.0')
    args = parser.parse_args()
    engine = InferenceEngine(args.model, ckpt_dir=args.ckpt_dir,
                             max_len=args.max_len)
    engine.warmup()   # readiness flips only once serving is fast
    web.run_app(build_app(engine), host=args.host, port=args.port,
                print=None)


if __name__ == '__main__':
    main()
