"""Disaggregated prefill/decode serving (docs/serving.md §disagg).

Two independently-scaled replica pools behind one LB: PREFILL replicas
run the compute-shaped phase (chunked prefill + first-token sampling)
and ship the request's KV pages + sampler state to a DECODE replica,
which adopts the pages into its own ``PageAllocator`` and carries the
latency-shaped phase (token-by-token decode, SSE streaming). A burst
of long prompts then saturates the prefill pool's queue — scaled on
the ``prefill_queue`` SLO — while interactive TPOT on the decode pool
holds (the loadgen ``prefill_burst`` scorecard is the checked-in
proof).

Modules:
  * :mod:`.handoff` — the page handoff transport: npy-framed KV rows
    over the shared framed-TCP idiom (utils/framed.py), content
    fingerprints, and the decode-side staging store.

The engine's ``/disagg/prefill`` + ``/disagg/continue`` endpoints and
the LB's two-stage router live with their hosts (serve/engine.py,
serve/load_balancer.py) and bridge to this package lazily — ``serve``
ranks below ``serve/disagg`` in the skylint layer DAG.
"""
