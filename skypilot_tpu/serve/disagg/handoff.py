"""KV page handoff between prefill and decode replicas.

Wire format: ONE framed-TCP exchange (utils/framed.py — the same
versioned framing, npy array encoding, deadline discipline and
structured error replies the input-data service ships batches over)
per handoff:

  request  {'op': 'handoff', 'meta': {...}}  + arrays {'a': ..., 'b': ...}
  reply    {'ok': True, 'handoff_id': ...}   (or {'error', 'kind'})

``meta`` carries everything the decode replica needs to continue the
request as if it had prefilled it itself: the prompt tokens, sampler
state (temperature/top-k/top-p/penalties, the sampled FIRST token and
its logprobs), stop ids, the request class, and the export geometry
(bucket, page size, family). ``arrays`` are the [L, 1, bucket, ...]
contiguous per-token cache rows in :func:`models.paging.gather_prefix`
order — (k, v) for PagedKV, (c_kv, k_rope) for PagedLatent. Page IDS
never cross the wire: the decode replica reserves pages through its
OWN refcounted allocator and scatters the page CONTENTS in
(``paging.adopt_rows``), so the two pools' allocators stay sovereign
and a handoff can never alias or leak a page on either side.

Integrity discipline: ``meta['kv_sha256']`` is the content fingerprint
of the arrays, recomputed on the receive side BEFORE staging — a
truncated or bit-flipped page refuses loudly (kind ``integrity``)
instead of decoding garbage with HTTP 200. Config skew (different
model/vocab/page size) refuses with kind ``spec`` — never retried, a
mismatched pool pairing does not heal.

Staging: adopted-but-not-yet-continued handoffs wait in
:class:`HandoffStore` as HOST memory only — no device pages are
allocated until the decode engine actually admits the request
(``/disagg/continue``), so an orphaned handoff (its LB died between
stages) costs RAM until the TTL sweep, never KV pool pages. Duplicate
handoff ids are refused (kind ``duplicate``): a retried send that
actually landed twice must not double-admit.

Device-to-device transport (ICI within a slice) is a documented seam:
:func:`send` is the one place serialization happens, so a D2D path
replaces this module's body without touching the engine or LB.

Failpoints: ``handoff.send`` (prefill side, before the socket op) and
``handoff.recv`` (decode side, inside the receiver handler) — the
chaos suite's mid-handoff kill windows (docs/ROBUSTNESS.md).
"""
from __future__ import annotations

import hashlib
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import failpoints as failpoints_lib
from skypilot_tpu.utils import framed
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

# The decode replica's handoff listener rides alongside its HTTP port
# at a fixed offset, so the LB (and the prefill replica it instructs)
# can derive the handoff address from the replica URL it already
# routes to — no extra service discovery. Engines accept
# --handoff-port to override.
HANDOFF_PORT_OFFSET = 1000

# Whole-exchange deadline for one handoff send (connect + frame +
# ack). A dead decode replica costs the prefill handler this long,
# bounded — the LB's stage-1 read timeout must exceed it.
SEND_TIMEOUT_ENV = 'SKYTPU_HANDOFF_TIMEOUT'
SEND_TIMEOUT_DEFAULT = 30.0

# Staged handoffs whose /disagg/continue never arrives (the
# orchestrating LB died between stages) are swept after this many
# seconds. Host memory only — no pages are held.
STORE_TTL_ENV = 'SKYTPU_HANDOFF_TTL'
STORE_TTL_DEFAULT = 120.0

# meta keys every handoff must carry — refused (kind 'spec') otherwise.
REQUIRED_META = ('handoff_id', 'model', 'vocab_size', 'page_size',
                 'family', 'bucket', 'tokens', 'max_new', 'first_token',
                 'kv_sha256')


class HandoffError(RuntimeError):
    """Prefill-side send failure (socket/protocol/refusal). ``kind``
    mirrors the framed reply's error kind; ``retriable`` is False only
    for configuration refusals (kind ``spec``) — a retry on another
    replica pair cannot heal those."""

    def __init__(self, message: str, kind: str = 'error'):
        super().__init__(message)
        self.kind = kind
        self.retriable = kind != 'spec'


def kv_fingerprint(arrays: Dict[str, np.ndarray]) -> str:
    """Content sha256 over the handoff arrays (name-ordered, shape and
    dtype included so a reshaped buffer can't collide)."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def new_handoff_id() -> str:
    return uuid.uuid4().hex


def handoff_addr_for_url(url: str,
                         offset: int = HANDOFF_PORT_OFFSET
                         ) -> Tuple[str, int]:
    """Replica HTTP url → its handoff (host, port): the fixed-offset
    convention the LB uses to point prefill replicas at decode
    replicas."""
    rest = url.split('://', 1)[-1].rstrip('/')
    host, port = framed.parse_addr(rest, default_port=8000)
    return host, port + offset


def send_timeout() -> float:
    return knobs.get_float(SEND_TIMEOUT_ENV)


def send(addr: Tuple[str, int], meta: Dict[str, Any],
         arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Ship one handoff to a decode replica's receiver; returns the
    ack. Raises :class:`HandoffError` on any failure — socket errors
    and protocol refusals are retriable (another prefill attempt or
    decode target may succeed), ``spec``-kinded refusals are not.

    Blocking (stdlib sockets): callers on an event loop run it via
    ``asyncio.to_thread``."""
    try:
        if failpoints_lib.ACTIVE:
            # A firing is a transport failure (the chaos window for a
            # prefill replica dying mid-send) — classed retriable like
            # any socket fault below.
            failpoints_lib.fire('handoff.send')
        reply, _ = framed.request(addr, {'op': 'handoff', 'meta': meta},
                                  arrays, timeout=send_timeout())
        return reply
    except framed.RemoteError as e:
        raise HandoffError(f'decode replica refused handoff: {e}',
                           kind=e.kind) from e
    except (framed.ProtocolError, OSError,
            failpoints_lib.FailpointError) as e:
        raise HandoffError(
            f'handoff transport to {addr[0]}:{addr[1]} failed: '
            f'{type(e).__name__}: {e}') from e


class HandoffStore:
    """Decode-side staging for received handoffs, keyed by handoff id.

    Thread-safe: the receiver's connection threads put, the engine's
    event loop pops. Entries are (meta, arrays) HOST tuples — no
    device state — with a TTL sweep for orphans and a hard entry cap
    (a flooding peer exhausts its own handoffs, not this process's
    RAM). Duplicate puts refuse: at-most-once admission is the
    adopt-side half of the no-leak contract."""

    def __init__(self, ttl: Optional[float] = None, max_entries: int = 256):
        if ttl is None:
            ttl = knobs.get_float(STORE_TTL_ENV)
        self.ttl = ttl
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: Dict[str, Tuple[float, Dict[str, Any],
                                       Dict[str, np.ndarray]]] = {}
        # Recently-consumed ids: a duplicate arriving AFTER its twin
        # was adopted must refuse too, not stage a second admission.
        self._consumed: Dict[str, float] = {}

    def put(self, meta: Dict[str, Any],
            arrays: Dict[str, np.ndarray]) -> None:
        hid = str(meta['handoff_id'])
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            if hid in self._entries or hid in self._consumed:
                raise framed.RemoteError(
                    f'handoff {hid} already received — duplicate '
                    f'delivery refused (at-most-once adoption)',
                    kind='duplicate')
            if len(self._entries) >= self.max_entries:
                raise framed.RemoteError(
                    f'handoff store full ({self.max_entries} staged); '
                    f'retry shortly', kind='overloaded')
            self._entries[hid] = (now + self.ttl, meta, arrays)

    def pop(self, handoff_id: str
            ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            entry = self._entries.pop(handoff_id, None)
            if entry is None:
                return None
            self._consumed[handoff_id] = now + self.ttl
            return entry[1], entry[2]

    def sweep(self) -> int:
        """Drop expired entries; returns how many were swept."""
        with self._lock:
            return self._sweep_locked(time.monotonic())

    def _sweep_locked(self, now: float) -> int:
        dead = [hid for hid, (exp, _, _) in self._entries.items()
                if exp <= now]
        for hid in dead:
            del self._entries[hid]
            logger.warning(f'handoff {hid} expired unconsumed after '
                           f'{self.ttl:.0f}s — swept (host memory '
                           f'only; no pages were held)')
        for hid in [h for h, exp in self._consumed.items()
                    if exp <= now]:
            del self._consumed[hid]
        return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class HandoffReceiver:
    """The decode replica's framed-TCP listener.

    ``validate(meta) -> Optional[str]`` is the engine's compatibility
    check (model/vocab/page-size/bucket coverage); a non-None return
    refuses the handoff with kind ``spec``. Integrity (content
    fingerprint) and duplicate refusals happen here too — BEFORE
    staging, so nothing unverifiable ever waits for adoption."""

    def __init__(self, host: str, port: int, store: HandoffStore,
                 validate: Optional[Callable[[Dict[str, Any]],
                                             Optional[str]]] = None):
        self.store = store
        self._validate = validate
        self._server = framed.FramedServer(host, port, self._handle,
                                           name='kv-handoff')
        self.addr = self._server.addr

    def start(self) -> 'HandoffReceiver':
        self._server.start()
        logger.info(f'KV handoff receiver listening on '
                    f'{self.addr[0]}:{self.addr[1]}.')
        return self

    def stop(self) -> None:
        self._server.stop()

    # ------------------------------------------------------------------
    def _handle(self, obj: Dict[str, Any], arrays: framed.Arrays
                ) -> Tuple[Dict[str, Any], Optional[framed.Arrays]]:
        if failpoints_lib.ACTIVE:
            failpoints_lib.fire('handoff.recv')
        if obj.get('op') != 'handoff':
            raise framed.RemoteError(
                f'unknown op {obj.get("op")!r}', kind='spec')
        meta = obj.get('meta')
        if not isinstance(meta, dict):
            raise framed.RemoteError('handoff without meta', kind='spec')
        missing = [k for k in REQUIRED_META if k not in meta]
        if missing:
            raise framed.RemoteError(
                f'handoff meta missing {missing}', kind='spec')
        if set(arrays) != {'a', 'b'}:
            raise framed.RemoteError(
                f'handoff arrays must be exactly {{a, b}}, got '
                f'{sorted(arrays)}', kind='spec')
        digest = kv_fingerprint(arrays)
        if digest != meta['kv_sha256']:
            raise framed.RemoteError(
                f'handoff {meta["handoff_id"]} KV fingerprint mismatch '
                f'(sent {meta["kv_sha256"][:12]}…, received '
                f'{digest[:12]}…) — refusing to adopt corrupted pages',
                kind='integrity')
        if self._validate is not None:
            msg = self._validate(meta)
            if msg:
                raise framed.RemoteError(msg, kind='spec')
        self.store.put(meta, dict(arrays))
        return {'ok': True, 'handoff_id': meta['handoff_id']}, None


def build_meta(*, handoff_id: str, model: str, vocab_size: int,
               page_size: int, family: str, bucket: int,
               tokens: List[int], max_new: int, first_token: int,
               first_lp: float, first_tops: List,
               temperature: float, top_k: Optional[int],
               top_p: Optional[float], presence_penalty: float,
               frequency_penalty: float, stop_ids: List[int],
               want_tops: bool, cls: str,
               kv_sha256: str) -> Dict[str, Any]:
    """The handoff meta document — one constructor so the prefill
    handler and the tests can never drift on field names."""
    return {
        'handoff_id': handoff_id, 'model': model,
        'vocab_size': int(vocab_size), 'page_size': int(page_size),
        'family': family, 'bucket': int(bucket),
        'tokens': [int(t) for t in tokens], 'max_new': int(max_new),
        'first_token': int(first_token), 'first_lp': float(first_lp),
        'first_tops': first_tops or [],
        'temperature': float(temperature),
        'top_k': (int(top_k) if top_k else 0),
        'top_p': (float(top_p) if top_p else 0.0),
        'presence_penalty': float(presence_penalty),
        'frequency_penalty': float(frequency_penalty),
        'stop_ids': [int(i) for i in (stop_ids or ())],
        'want_tops': bool(want_tops), 'cls': cls,
        'kv_sha256': kv_sha256,
        'sent_unix': round(time.time(), 6),
    }
