"""`service:` YAML section → typed spec.

Reference analog: sky/serve/service_spec.py (readiness probe, replica
policy, ports). Field names follow the reference so its service YAMLs parse
unchanged:

service:
  readiness_probe: /health            # or {path:, initial_delay_seconds:,
                                      #     timeout_seconds:}
  replicas: 2                         # static count, OR:
  replica_policy:
    min_replicas: 1
    max_replicas: 4
    target_qps_per_replica: 10
    upscale_delay_seconds: 300
    downscale_delay_seconds: 1200
  ports: 8000                         # port the replica app listens on
  load_balancing_policy: least_load   # round_robin |
                                      # instance_aware_least_load |
                                      # prefix_affinity
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

_SERVICE_FIELDS = frozenset({
    'readiness_probe', 'replicas', 'replica_policy', 'ports',
    'load_balancing_policy', 'spot_placer',
    # Pool mode (reference: sky jobs pool — service_spec.py:40-64): a pool
    # is this same spec with `pool: true` + `workers: N`. Workers are
    # replicas that idle after setup; managed jobs exec onto them.
    'pool', 'workers',
    # Disaggregated prefill/decode serving (serve/disagg,
    # docs/serving.md): two independently-scaled replica pools behind
    # one LB, with KV page handoff between them.
    'disagg',
})
# Per-pool sub-config keys inside `disagg:`. Each pool takes either
# `replicas: N` (static) or the replica_policy autoscaling fields.
_DISAGG_ROLES = ('prefill', 'decode')
# Serve-only concepts a pool has no use for: there is no HTTP app to
# probe or balance (reference rejects these for pool too).
_POOL_UNSUPPORTED = frozenset({
    'readiness_probe', 'ports', 'load_balancing_policy', 'replica_policy',
    'replicas',
})
_POLICY_FIELDS = frozenset({
    'min_replicas', 'max_replicas', 'target_qps_per_replica',
    'target_queue_depth_per_replica',
    'upscale_delay_seconds', 'downscale_delay_seconds',
})


@dataclasses.dataclass
class ReadinessProbe:
    path: str = '/'
    initial_delay_seconds: float = 60.0
    timeout_seconds: float = 15.0


@dataclasses.dataclass
class ReplicaPolicy:
    min_replicas: int = 1
    max_replicas: Optional[int] = None      # None → fixed at min_replicas
    target_qps_per_replica: Optional[float] = None
    # Saturation autoscaling (serve/autoscalers.py
    # SaturationAutoscaler): target fleet queue depth per replica,
    # computed from the controller scraper's engine-reported
    # saturation; falls back to QPS when scrape data goes stale.
    target_queue_depth_per_replica: Optional[float] = None
    upscale_delay_seconds: float = 300.0
    downscale_delay_seconds: float = 1200.0

    @property
    def autoscaling_enabled(self) -> bool:
        return (self.max_replicas is not None and
                (self.target_qps_per_replica is not None or
                 self.target_queue_depth_per_replica is not None))


@dataclasses.dataclass
class DisaggSpec:
    """Per-role replica policies for disaggregated prefill/decode
    serving: each pool scales independently (the whole point — a
    long-prompt flood grows the prefill pool off its queue-wait SLO
    while the decode pool holds interactive TPOT)."""
    prefill: ReplicaPolicy
    decode: ReplicaPolicy

    def role_policy(self, role: str) -> ReplicaPolicy:
        return self.prefill if role == 'prefill' else self.decode


@dataclasses.dataclass
class ServiceSpec:
    readiness_probe: ReadinessProbe
    policy: ReplicaPolicy
    port: int = 8000
    load_balancing_policy: str = 'least_load'
    # Spot placement policy name (serve/spot_placer.py); None disables
    # placement (replicas launch wherever provisioning failover lands).
    spot_placer: Optional[str] = None
    # Pool mode: replicas are idle workers for managed jobs (no LB, no
    # HTTP probe — readiness is cluster liveness).
    pool: bool = False
    # Disaggregated prefill/decode pools; None = monolithic service.
    disagg: Optional[DisaggSpec] = None

    @staticmethod
    def _parse_pool_policy(role: str, cfg: Any) -> ReplicaPolicy:
        """One disagg pool's config: ``{replicas: N}`` or the
        replica_policy autoscaling fields (same grammar as the
        top-level section)."""
        if not isinstance(cfg, dict) or not cfg:
            raise ValueError(
                f"disagg.{role} must be a mapping with 'replicas' or "
                f'replica-policy fields, got {cfg!r}')
        if 'replicas' in cfg:
            extra = set(cfg) - {'replicas'}
            if extra:
                raise ValueError(
                    f"disagg.{role}: 'replicas' excludes "
                    f'{sorted(extra)}')
            return ReplicaPolicy(min_replicas=int(cfg['replicas']))
        unknown = set(cfg) - _POLICY_FIELDS
        if unknown:
            raise ValueError(
                f'Unknown disagg.{role} fields: {sorted(unknown)}')
        policy = ReplicaPolicy(
            min_replicas=int(cfg.get('min_replicas', 1)),
            max_replicas=(int(cfg['max_replicas'])
                          if 'max_replicas' in cfg else None),
            target_qps_per_replica=(
                float(cfg['target_qps_per_replica'])
                if 'target_qps_per_replica' in cfg else None),
            target_queue_depth_per_replica=(
                float(cfg['target_queue_depth_per_replica'])
                if 'target_queue_depth_per_replica' in cfg else None),
            upscale_delay_seconds=float(
                cfg.get('upscale_delay_seconds', 300.0)),
            downscale_delay_seconds=float(
                cfg.get('downscale_delay_seconds', 1200.0)))
        if policy.max_replicas is not None and \
                policy.max_replicas < policy.min_replicas:
            raise ValueError(f'disagg.{role}: max_replicas < '
                             f'min_replicas')
        return policy

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'ServiceSpec':
        config = dict(config or {})
        unknown = set(config) - _SERVICE_FIELDS
        if unknown:
            raise ValueError(f'Unknown service fields: {sorted(unknown)}. '
                             f'Valid: {sorted(_SERVICE_FIELDS)}')
        if config.get('pool'):
            bad = set(config) & _POOL_UNSUPPORTED
            if bad:
                raise ValueError(
                    f'{sorted(bad)} not supported for pool. A pool only '
                    f"takes 'workers: <num>' (and optionally "
                    f"'spot_placer').")
            workers = int(config.get('workers', 1))
            if workers < 1:
                raise ValueError('pool workers must be >= 1')
            placer = config.get('spot_placer')
            if placer is not None:
                from skypilot_tpu.serve import spot_placer as placer_lib
                if placer not in placer_lib.PLACERS:
                    raise ValueError(
                        f'Unknown spot_placer {placer!r}; available: '
                        f'{sorted(placer_lib.PLACERS)}')
            return cls(readiness_probe=ReadinessProbe(),
                       policy=ReplicaPolicy(min_replicas=workers),
                       port=0, spot_placer=placer, pool=True)
        if 'workers' in config:
            raise ValueError("'workers' requires 'pool: true' "
                             "(use 'replicas' for a service).")
        probe_cfg = config.get('readiness_probe', '/')
        if isinstance(probe_cfg, str):
            probe = ReadinessProbe(path=probe_cfg)
        else:
            probe = ReadinessProbe(
                path=probe_cfg.get('path', '/'),
                initial_delay_seconds=float(
                    probe_cfg.get('initial_delay_seconds', 60.0)),
                timeout_seconds=float(probe_cfg.get('timeout_seconds', 15.0)))

        disagg = None
        if 'disagg' in config:
            d_cfg = config['disagg']
            if not isinstance(d_cfg, dict):
                raise ValueError("'disagg' must be a mapping with "
                                 "'prefill' and 'decode' sections")
            unknown = set(d_cfg) - set(_DISAGG_ROLES)
            if unknown:
                raise ValueError(f'Unknown disagg sections: '
                                 f'{sorted(unknown)}; valid: '
                                 f'{list(_DISAGG_ROLES)}')
            missing = [r for r in _DISAGG_ROLES if r not in d_cfg]
            if missing:
                raise ValueError(f'disagg needs both pools; missing: '
                                 f'{missing}')
            if 'replicas' in config or config.get('replica_policy'):
                raise ValueError(
                    "'disagg' replaces top-level 'replicas'/"
                    "'replica_policy': each pool declares its own "
                    'count or autoscaling policy')
            disagg = DisaggSpec(
                prefill=cls._parse_pool_policy('prefill',
                                               d_cfg['prefill']),
                decode=cls._parse_pool_policy('decode',
                                              d_cfg['decode']))

        pol_cfg = dict(config.get('replica_policy') or {})
        unknown = set(pol_cfg) - _POLICY_FIELDS
        if unknown:
            raise ValueError(
                f'Unknown replica_policy fields: {sorted(unknown)}')
        if 'replicas' in config and pol_cfg:
            raise ValueError("Use either 'replicas' (static) or "
                             "'replica_policy', not both.")
        if 'replicas' in config:
            policy = ReplicaPolicy(min_replicas=int(config['replicas']))
        else:
            policy = ReplicaPolicy(
                min_replicas=int(pol_cfg.get('min_replicas', 1)),
                max_replicas=(int(pol_cfg['max_replicas'])
                              if 'max_replicas' in pol_cfg else None),
                target_qps_per_replica=(
                    float(pol_cfg['target_qps_per_replica'])
                    if 'target_qps_per_replica' in pol_cfg else None),
                target_queue_depth_per_replica=(
                    float(pol_cfg['target_queue_depth_per_replica'])
                    if 'target_queue_depth_per_replica' in pol_cfg
                    else None),
                upscale_delay_seconds=float(
                    pol_cfg.get('upscale_delay_seconds', 300.0)),
                downscale_delay_seconds=float(
                    pol_cfg.get('downscale_delay_seconds', 1200.0)))
        if policy.max_replicas is not None and \
                policy.max_replicas < policy.min_replicas:
            raise ValueError('max_replicas < min_replicas')

        ports = config.get('ports', 8000)
        lb = config.get('load_balancing_policy', 'least_load')
        # Importing the policies module is what populates the registry.
        from skypilot_tpu.serve import load_balancing_policies  # noqa: F401
        from skypilot_tpu.utils import registry
        if lb.lower() not in registry.LB_POLICY_REGISTRY:
            raise ValueError(
                f'Unknown load_balancing_policy {lb!r}; available: '
                f'{registry.LB_POLICY_REGISTRY.keys()}')
        placer = config.get('spot_placer')
        if placer is not None:
            from skypilot_tpu.serve import spot_placer as placer_lib
            if placer not in placer_lib.PLACERS:
                raise ValueError(
                    f'Unknown spot_placer {placer!r}; available: '
                    f'{sorted(placer_lib.PLACERS)}')
        return cls(readiness_probe=probe, policy=policy, port=int(ports),
                   load_balancing_policy=lb.lower(), spot_placer=placer,
                   disagg=disagg)

    @staticmethod
    def _pool_to_yaml(policy: ReplicaPolicy) -> Dict[str, Any]:
        if policy.autoscaling_enabled or policy.max_replicas is not None:
            return {k: v for k, v in dataclasses.asdict(policy).items()
                    if v is not None}
        return {'replicas': policy.min_replicas}

    def to_yaml_config(self) -> Dict[str, Any]:
        if self.pool:
            out = {'pool': True, 'workers': self.policy.min_replicas}
            if self.spot_placer is not None:
                out['spot_placer'] = self.spot_placer
            return out
        out: Dict[str, Any] = {
            'readiness_probe': dataclasses.asdict(self.readiness_probe),
            'ports': self.port,
            'load_balancing_policy': self.load_balancing_policy,
        }
        if self.spot_placer is not None:
            out['spot_placer'] = self.spot_placer
        if self.disagg is not None:
            out['disagg'] = {
                'prefill': self._pool_to_yaml(self.disagg.prefill),
                'decode': self._pool_to_yaml(self.disagg.decode),
            }
            return out
        pol = self.policy
        if pol.autoscaling_enabled or pol.max_replicas is not None:
            out['replica_policy'] = {
                k: v for k, v in dataclasses.asdict(pol).items()
                if v is not None
            }
        else:
            out['replicas'] = pol.min_replicas
        return out
