"""Serve plane: replicated serving with autoscaling + load balancing.

Reference analog: sky/serve/ (service.py, replica_managers.py,
autoscalers.py, load_balancer.py). TPU-first redesign notes:
- controller + load balancer run in ONE detached process per service (the
  LB is asyncio; the control loop is a thread) next to the API server — no
  dedicated controller cluster to provision.
- each replica is a TPU slice cluster launched through the normal
  execution path, so replicas inherit provisioning failover for free.
"""
from skypilot_tpu.serve.core import down
from skypilot_tpu.serve.core import status
from skypilot_tpu.serve.core import up
from skypilot_tpu.serve.core import update

__all__ = ['up', 'down', 'status', 'update']
