"""Autoscalers: request-rate and engine-saturation scaling with
hysteresis.

Reference analog: sky/serve/autoscalers.py (`Autoscaler:116`,
`_AutoscalerWithHysteresis:369`, `RequestRateAutoscaler:455`). The
decision function is pure — (signal, now) → target — so it unit-tests
with synthetic clocks, no clusters.

Since the elastic plane landed these classes are ADAPTERS: each wraps
one ``elastic.PoolController`` whose ElasticSpec declares the serve
signal, bounds and delays, so serve flap-damps with the exact same
decision core as the data-worker pool and the rollout fleet
(docs/ELASTIC.md). The serve-visible behavior — the two signals, the
QPS fallback, the pending/delay hysteresis — is pinned by the
existing tests and unchanged.

Two signals (ROADMAP item 3: scale on engine-reported saturation, not
LB-side probes):

  * ``request_rate`` — LB-observed QPS over a sliding window divided
    by ``target_qps_per_replica``. Cheap, always available, but blind
    to request COST: 10 QPS of 4k-token prompts saturates a replica
    that 10 QPS of chat turns barely warms.
  * ``saturation`` — the fleet's engine-reported queue depth (scraped
    by observe/scrape.py from every replica's /health + /metrics)
    divided by ``target_queue_depth_per_replica``. Queue depth is the
    engine's own admission backlog — it already prices request cost
    in. When the scraped snapshot goes STALE (scraper dead, all
    replicas unreachable) the policy FALLS BACK to the QPS signal —
    the DECLARED stale-signal fallback of the elastic contract —
    rather than flying blind on a dead replica's last word
    (``skytpu_serve_autoscaler_fallback_total`` counts it).

Both share the same hysteresis: a raw target must hold for
``upscale_delay_seconds`` (or ``downscale_delay_seconds``) before the
decision changes — absorbing bursts without flapping replicas whose
provision time is minutes.
"""
from __future__ import annotations

import math
import threading
from typing import Deque, Mapping, Optional, Tuple

from collections import deque

from skypilot_tpu import sky_logging
from skypilot_tpu.elastic import controller as elastic_controller
from skypilot_tpu.elastic import spec as elastic_spec
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import vclock
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

# Sliding window over which QPS is measured (reference default 60s).
QPS_WINDOW_SECONDS = 60.0

# A saturation snapshot older than this is STALE: the saturation
# autoscaler falls back to the QPS signal. Matches the scraper's
# default staleness window.
SATURATION_STALE_SECONDS = 30.0

# Decision gauges. One controller process per service, so no service
# label is needed (or allowed: service names are unbounded).
_TARGET_GAUGE = metrics_lib.gauge(
    'skytpu_serve_autoscaler_target_replicas',
    'Current autoscaler decision (post-hysteresis replica target).')
_QPS_GAUGE = metrics_lib.gauge(
    'skytpu_serve_autoscaler_qps',
    'Request rate over the sliding QPS window.')
_QUEUE_GAUGE = metrics_lib.gauge(
    'skytpu_serve_autoscaler_queue_depth',
    'Fleet engine-reported queue depth (sum over fresh scraped '
    'replicas) feeding the saturation autoscaler.')
_FALLBACK_TOTAL = metrics_lib.counter(
    'skytpu_serve_autoscaler_fallback_total',
    'Saturation-autoscaler decisions that could not use the scraped '
    'signal, by reason (stale: snapshot older than the staleness '
    'window; no_signal: no scrape data was ever published).',
    labels={'reason': ('stale', 'no_signal')})


class Autoscaler:

    def __init__(self, policy: spec_lib.ReplicaPolicy):
        self.policy = policy

    def record_request(self, now: Optional[float] = None) -> None:
        """Called by the load balancer on every proxied request."""

    def observe_saturation(self, queue_depths: Mapping[str, float],
                           now: Optional[float] = None) -> None:
        """Called by the controller after each scrape round with the
        FRESH per-replica engine queue depths (url → depth). Base
        policies ignore it."""

    def target_replicas(self, now: Optional[float] = None) -> int:
        raise NotImplementedError

    @classmethod
    def make(cls, policy: spec_lib.ReplicaPolicy,
             pool: str = 'serve') -> 'Autoscaler':
        """``pool`` is the elastic pool label the decision publishes
        under (the disagg controller passes its role — 'prefill' /
        'decode'; the label set is closed in elastic/spec.py)."""
        if not policy.autoscaling_enabled:
            return FixedAutoscaler(policy)
        name = ('saturation'
                if policy.target_queue_depth_per_replica is not None
                else 'request_rate')
        return registry.AUTOSCALER_REGISTRY.type_from_str(name)(
            policy, pool=pool)


class FixedAutoscaler(Autoscaler):
    """Static replica count (service.replicas: N)."""

    def target_replicas(self, now: Optional[float] = None) -> int:
        _TARGET_GAUGE.set(self.policy.min_replicas)
        return self.policy.min_replicas


@registry.AUTOSCALER_REGISTRY.register(name='request_rate')
class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), with hysteresis."""

    def __init__(self, policy: spec_lib.ReplicaPolicy,
                 pool: str = 'serve'):
        super().__init__(policy)
        assert policy.autoscaling_enabled
        self._timestamps: Deque[float] = deque()
        # record_request runs on the LB's event-loop thread while
        # target_replicas runs on the reconcile thread (and, for the
        # saturation subclass, the scrape-loop thread) — both trim the
        # deque, and an unsynchronized check-then-popleft pair can
        # IndexError or pop an in-window sample.
        self._ts_lock = threading.Lock()
        self._ctl = elastic_controller.PoolController(
            self._elastic_spec(pool))

    def _elastic_spec(self, pool: str) -> elastic_spec.ElasticSpec:
        """The declared contract this policy scales under. Subclasses
        override to swap the signal; the hysteresis shape (delay-gated,
        clean_rounds=1, no cooldown) is serve's pinned behavior."""
        return elastic_spec.ElasticSpec(
            pool=pool,
            signal=self._qps_reading,
            # None objective (a saturation-only policy reaching the
            # QPS shape) reduces to HOLD — never invent a target from
            # an undeclared objective.
            target_per_unit=self.policy.target_qps_per_replica,
            min_units=self.policy.min_replicas,
            max_units=(self.policy.max_replicas or
                       self.policy.min_replicas),
            upscale_delay_seconds=self.policy.upscale_delay_seconds,
            downscale_delay_seconds=self.policy.downscale_delay_seconds)

    # Test-pinned decision state lives on the wrapped PoolController;
    # these views keep the (old, documented) poke surface stable.
    @property
    def _current_target(self) -> int:
        return self._ctl.target

    @_current_target.setter
    def _current_target(self, value: int) -> None:
        self._ctl.target = value

    @property
    def _pending(self) -> Optional[Tuple[int, float]]:
        p = self._ctl.pending
        return None if p is None else (p[0], p[1])

    @_pending.setter
    def _pending(self, value: Optional[Tuple[int, float]]) -> None:
        self._ctl.pending = (None if value is None
                             else (value[0], value[1], 0))

    def record_request(self, now: Optional[float] = None) -> None:
        now = vclock.now() if now is None else now
        with self._ts_lock:
            self._timestamps.append(now)
            # Trim at APPEND, not only at read: the saturation
            # subclass can go rounds/days without reaching _qps() (its
            # fresh-signal branch never reads QPS), and an untrimmed
            # deque grows by one float per proxied request forever.
            self._trim(now)

    def _trim(self, now: float) -> None:
        # Callers hold _ts_lock.
        cutoff = now - QPS_WINDOW_SECONDS
        while self._timestamps and self._timestamps[0] < cutoff:
            self._timestamps.popleft()

    def _qps(self, now: float) -> float:
        with self._ts_lock:
            self._trim(now)
            return len(self._timestamps) / QPS_WINDOW_SECONDS

    def _qps_reading(self, now: float) -> elastic_spec.Reading:
        """The request-rate signal: always fresh (computed on demand
        from the LB-fed window), so it never takes the stale path."""
        qps = self._qps(now)
        _QPS_GAUGE.set(qps)
        return elastic_spec.Reading(value=qps, ts=now)

    def _clamp(self, want: int) -> int:
        lo = self.policy.min_replicas
        hi = self.policy.max_replicas or lo
        return max(lo, min(hi, want))

    def _qps_target(self, now: float) -> int:
        qps = self._qps(now)
        _QPS_GAUGE.set(qps)
        if self.policy.target_qps_per_replica is None:
            # No QPS objective configured (saturation-only policy
            # falling back here): hold the current decision rather
            # than invent one from an undeclared target.
            return self._current_target
        return self._clamp(
            math.ceil(qps / self.policy.target_qps_per_replica))

    def _raw_target(self, now: float) -> int:
        return self._ctl.compute_raw(now)[0]

    def target_replicas(self, now: Optional[float] = None) -> int:
        now = vclock.now() if now is None else now
        raw, source = self._ctl.compute_raw(now)
        target = self._ctl.decide(now, raw, source)
        _TARGET_GAUGE.set(target)
        return target


@registry.AUTOSCALER_REGISTRY.register(name='saturation')
class SaturationAutoscaler(RequestRateAutoscaler):
    """target = ceil(fleet queue depth / target_queue_depth_per_replica)
    from ENGINE-REPORTED saturation, falling back to the QPS signal
    when the scraped snapshot is stale. Shares the request-rate
    hysteresis (the raw signal differs; the flap-damping should not).
    In elastic terms: the saturation Reading is the signal, the QPS
    window is the DECLARED stale/no-signal fallback."""

    def __init__(self, policy: spec_lib.ReplicaPolicy,
                 pool: str = 'serve'):
        assert policy.target_queue_depth_per_replica is not None
        self._fleet_queue_depth: Optional[float] = None
        self._saturation_ts: Optional[float] = None
        self.stale_after = knobs.get_float(
            'SKYTPU_SATURATION_STALE_SECONDS')
        super().__init__(policy, pool=pool)

    def _elastic_spec(self, pool: str) -> elastic_spec.ElasticSpec:
        base = super()._elastic_spec(pool)
        per_replica = self.policy.target_queue_depth_per_replica
        return elastic_spec.ElasticSpec(
            pool=pool,
            signal=self._saturation_reading,
            target_per_unit=per_replica,
            min_units=base.min_units,
            max_units=base.max_units,
            upscale_delay_seconds=base.upscale_delay_seconds,
            downscale_delay_seconds=base.downscale_delay_seconds,
            stale_after=self.stale_after,
            fallback=self._qps_target,
            on_fallback=self._count_fallback)

    def _saturation_reading(self, now: float
                            ) -> Optional[elastic_spec.Reading]:
        del now  # freshness is the snapshot's own stamp.
        if self._saturation_ts is None:
            return None
        return elastic_spec.Reading(value=self._fleet_queue_depth,
                                    ts=self._saturation_ts)

    def _count_fallback(self, reason: str) -> None:
        _FALLBACK_TOTAL.inc(reason=reason)

    def observe_saturation(self, queue_depths: Mapping[str, float],
                           now: Optional[float] = None) -> None:
        if not queue_depths:
            # An EMPTY snapshot is "no fresh signal" (every replica
            # stale/unreachable, or none scraped yet) — refreshing the
            # timestamp on it would read as "fleet queue depth 0" and
            # scale an unreachable, possibly saturated fleet DOWN.
            # Let the timestamp age out so _raw_target takes the
            # stale→QPS fallback instead. (A healthy idle fleet posts
            # a NON-empty mapping of zero depths.)
            return
        now = vclock.now() if now is None else now
        total = float(sum(queue_depths.values()))
        self._fleet_queue_depth = total
        self._saturation_ts = now
        _QUEUE_GAUGE.set(total)
