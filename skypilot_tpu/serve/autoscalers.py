"""Autoscalers: request-rate scaling with hysteresis.

Reference analog: sky/serve/autoscalers.py (`Autoscaler:116`,
`_AutoscalerWithHysteresis:369`, `RequestRateAutoscaler:455`). The decision
function is pure — (request timestamps, ready count, now) → target — so it
unit-tests with synthetic clocks, no clusters.
"""
from __future__ import annotations

import math
from typing import Deque, List, Optional

from collections import deque

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import vclock
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

# Sliding window over which QPS is measured (reference default 60s).
QPS_WINDOW_SECONDS = 60.0

# Decision gauges. One controller process per service, so no service
# label is needed (or allowed: service names are unbounded).
_TARGET_GAUGE = metrics_lib.gauge(
    'skytpu_serve_autoscaler_target_replicas',
    'Current autoscaler decision (post-hysteresis replica target).')
_QPS_GAUGE = metrics_lib.gauge(
    'skytpu_serve_autoscaler_qps',
    'Request rate over the sliding QPS window.')


class Autoscaler:

    def __init__(self, policy: spec_lib.ReplicaPolicy):
        self.policy = policy

    def record_request(self, now: Optional[float] = None) -> None:
        """Called by the load balancer on every proxied request."""

    def target_replicas(self, now: Optional[float] = None) -> int:
        raise NotImplementedError

    @classmethod
    def make(cls, policy: spec_lib.ReplicaPolicy) -> 'Autoscaler':
        if policy.autoscaling_enabled:
            return registry.AUTOSCALER_REGISTRY.type_from_str(
                'request_rate')(policy)
        return FixedAutoscaler(policy)


class FixedAutoscaler(Autoscaler):
    """Static replica count (service.replicas: N)."""

    def target_replicas(self, now: Optional[float] = None) -> int:
        _TARGET_GAUGE.set(self.policy.min_replicas)
        return self.policy.min_replicas


@registry.AUTOSCALER_REGISTRY.register(name='request_rate')
class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), with hysteresis: the
    raw target must hold for upscale_delay_seconds (or
    downscale_delay_seconds) before the decision changes — absorbing bursts
    without flapping replicas whose provision time is minutes."""

    def __init__(self, policy: spec_lib.ReplicaPolicy):
        super().__init__(policy)
        assert policy.autoscaling_enabled
        self._timestamps: Deque[float] = deque()
        self._current_target = policy.min_replicas
        # (proposed_target, since_when) while a change is pending.
        self._pending: Optional[tuple] = None

    def record_request(self, now: Optional[float] = None) -> None:
        now = vclock.now() if now is None else now
        self._timestamps.append(now)

    def _qps(self, now: float) -> float:
        cutoff = now - QPS_WINDOW_SECONDS
        while self._timestamps and self._timestamps[0] < cutoff:
            self._timestamps.popleft()
        return len(self._timestamps) / QPS_WINDOW_SECONDS

    def _raw_target(self, now: float) -> int:
        qps = self._qps(now)
        assert self.policy.target_qps_per_replica is not None
        want = math.ceil(qps / self.policy.target_qps_per_replica)
        lo = self.policy.min_replicas
        hi = self.policy.max_replicas or lo
        return max(lo, min(hi, want))

    def target_replicas(self, now: Optional[float] = None) -> int:
        now = vclock.now() if now is None else now
        raw = self._raw_target(now)
        # One source of truth with the decision input (_raw_target has
        # already trimmed the window, so this is a cheap re-read).
        _QPS_GAUGE.set(self._qps(now))
        if raw == self._current_target:
            self._pending = None
            _TARGET_GAUGE.set(self._current_target)
            return self._current_target
        if self._pending is None or self._pending[0] != raw:
            self._pending = (raw, now)
            _TARGET_GAUGE.set(self._current_target)
            return self._current_target
        delay = (self.policy.upscale_delay_seconds
                 if raw > self._current_target else
                 self.policy.downscale_delay_seconds)
        if now - self._pending[1] >= delay:
            logger.info(f'Autoscaler: {self._current_target} → {raw} '
                        f'replicas (held {now - self._pending[1]:.0f}s).')
            self._current_target = raw
            self._pending = None
        _TARGET_GAUGE.set(self._current_target)
        return self._current_target
