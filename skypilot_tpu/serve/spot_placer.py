"""Spot replica placement: spread across zones, dodge preemption-prone ones.

Reference analog: sky/serve/spot_placer.py (`SpotPlacer:170`,
`DynamicFallbackSpotPlacer:254`). The problem: spot TPU capacity is
zone-correlated — when a zone reclaims one replica it usually reclaims the
rest soon after — so a service with every replica in one zone loses them
all at once. The placer keeps a live map of candidate (cloud, region, zone)
locations with a preemption history and places each new spot replica where
capacity has been most durable, spreading replicas across zones first.

TPU-first differences from the reference:
  - candidates come from `Cloud.regions_with_offering` over the task's
    `TpuSlice` (slice shapes are zone-constrained in the catalog), not from
    per-instance-type launchable enumeration;
  - preemption COUNTS are retained across fallback resets, so a zone that
    has burned us five times ranks below one that burned us once even after
    the active set is rebuilt.
"""
from __future__ import annotations

import collections
import dataclasses
import enum
import typing
from typing import Dict, List, Optional

from skypilot_tpu import sky_logging

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve import service_spec as spec_lib

logger = sky_logging.init_logger(__name__)

DEFAULT_PLACER = 'dynamic_fallback'


class LocationStatus(enum.Enum):
    ACTIVE = 'ACTIVE'
    PREEMPTED = 'PREEMPTED'


@dataclasses.dataclass(frozen=True, order=True)
class Location:
    cloud: str
    region: str
    zone: Optional[str]

    def to_override(self) -> Dict[str, Optional[str]]:
        return {'region': self.region, 'zone': self.zone}

    def __str__(self) -> str:
        loc = f'{self.cloud}/{self.region}'
        return f'{loc}/{self.zone}' if self.zone else loc


def _candidate_locations(task: 'task_lib.Task') -> List[Location]:
    """Enumerate feasible (cloud, region, zone) triples for the task.

    Respects a user-pinned region/zone (the pin shrinks the candidate set
    rather than being overridden)."""
    from skypilot_tpu import check as check_lib
    candidates = []
    for res in task.resources_list():
        clouds = ([res.cloud] if res.cloud is not None else
                  check_lib.get_cached_enabled_clouds_or_refresh())
        for cloud in clouds:
            try:
                regions = cloud.regions_with_offering(res)
            except Exception as e:  # pylint: disable=broad-except
                # One broken cloud must not kill placement, but a
                # silent skip hides why a zone never gets candidates.
                logger.debug(f'spot placer: {cloud} offering lookup '
                             f'failed ({e}); skipping.')
                continue
            for region in regions:
                if res.region is not None and region.name != res.region:
                    continue
                zones = [z.name for z in region.zones] or [None]
                for zone in zones:
                    if res.zone is not None and zone != res.zone:
                        continue
                    candidates.append(
                        Location(str(cloud), region.name, zone))
    return sorted(set(candidates))


def validate_spec(spec: 'spec_lib.ServiceSpec',
                  task: 'task_lib.Task') -> None:
    """Admission-time checks for `service.spot_placer` (serve.core.up)."""
    name = spec.spot_placer
    if name is None:
        return
    if name not in PLACERS:
        raise ValueError(f'Unknown spot_placer {name!r}; '
                         f'valid: {sorted(PLACERS)}')
    if not all(r.use_spot for r in task.resources_list()):
        raise ValueError(
            'service.spot_placer requires every task resource option to '
            'set use_spot: true (got an on-demand option).')


class SpotPlacer:
    """Base placer: location inventory + preemption bookkeeping."""

    def __init__(self, task: 'task_lib.Task'):
        locations = _candidate_locations(task)
        self.location2status: Dict[Location, LocationStatus] = {
            loc: LocationStatus.ACTIVE for loc in locations}
        self.preemption_counts: Dict[Location, int] = \
            collections.defaultdict(int)
        self._cost_cache: Dict[Location, float] = {}
        self._resources = task.resources_list()[0]
        logger.info(f'Spot placer: {len(locations)} candidate locations.')

    # -- status ---------------------------------------------------------
    def set_active(self, location: Location) -> None:
        if location in self.location2status:
            self.location2status[location] = LocationStatus.ACTIVE

    def set_preemptive(self, location: Location) -> None:
        if location in self.location2status:
            self.location2status[location] = LocationStatus.PREEMPTED
            self.preemption_counts[location] += 1

    def clear_preemptive_locations(self) -> None:
        for loc in self.location2status:
            self.location2status[loc] = LocationStatus.ACTIVE

    def active_locations(self) -> List[Location]:
        return [l for l, s in self.location2status.items()
                if s is LocationStatus.ACTIVE]

    def preemptive_locations(self) -> List[Location]:
        return [l for l, s in self.location2status.items()
                if s is LocationStatus.PREEMPTED]

    # -- selection ------------------------------------------------------
    def select_next_location(self,
                             current: List[Location]) -> Optional[Location]:
        raise NotImplementedError

    def _hourly_cost(self, location: Location) -> float:
        if location not in self._cost_cache:
            try:
                res = self._resources.copy(**location.to_override())
                self._cost_cache[location] = res.get_cost(seconds=3600)
            except Exception as e:  # pylint: disable=broad-except
                # inf = "never pick on price"; log why so a catalog gap
                # doesn't silently exile a perfectly good zone.
                logger.debug(f'spot placer: no cost for {location} '
                             f'({e}); treating as infinitely '
                             f'expensive.')
                self._cost_cache[location] = float('inf')
        return self._cost_cache[location]

    @classmethod
    def from_task(cls, spec: 'spec_lib.ServiceSpec',
                  task: 'task_lib.Task') -> Optional['SpotPlacer']:
        """Placer iff the service asked for one AND the task runs on spot.

        Misconfiguration degrades to no-placer (with a warning) instead of
        raising: this runs inside the controller AND inside `serve down`
        teardown — a raise here would wedge shutdown of a service whose
        spec was admitted by an older validator. Admission-time rejection
        is `validate_spec` (called from serve.core.up)."""
        name = spec.spot_placer
        if name is None:
            return None
        try:
            validate_spec(spec, task)
        except ValueError as e:
            logger.warning(f'Spot placer disabled: {e}')
            return None
        placer = PLACERS[name](task)
        if not placer.location2status:
            logger.warning('Spot placer found no candidate locations; '
                           'placement disabled.')
            return None
        return placer


class DynamicFallbackSpotPlacer(SpotPlacer):
    """Spread over unused active zones; on preemption, fall back elsewhere.

    Selection order: (1) active locations not currently hosting a replica,
    (2) any active location. Within a tier, fewest historical preemptions
    wins, then lowest hourly cost. When preemptions leave fewer than two
    active locations, the preempted set is reactivated (capacity weather
    changes) — but their counts persist, so they rank last."""

    def select_next_location(self,
                             current: List[Location]) -> Optional[Location]:
        active = self.active_locations()
        if not active:
            self.clear_preemptive_locations()
            active = self.active_locations()
            if not active:
                return None
        candidates = [l for l in active if l not in current] or active
        choice = min(candidates,
                     key=lambda l: (self.preemption_counts[l],
                                    self._hourly_cost(l), l))
        logger.info(f'Spot placer selected {choice} '
                    f'(active={len(active)}, in-use={len(current)}).')
        return choice

    def set_preemptive(self, location: Location) -> None:
        super().set_preemptive(location)
        if len(self.active_locations()) < 2:
            self.clear_preemptive_locations()


PLACERS = {
    DEFAULT_PLACER: DynamicFallbackSpotPlacer,
}
