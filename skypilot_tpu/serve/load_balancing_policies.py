"""Per-request replica selection policies.

Reference analog: sky/serve/load_balancing_policies.py
(`RoundRobinPolicy:85`, `LeastLoadPolicy:111` — the default).
"""
from __future__ import annotations

import hashlib
import itertools
import threading
from typing import Dict, List, Optional

from skypilot_tpu.utils import registry


class LoadBalancingPolicy:
    """Tracks the ready-replica set and picks a target per request."""

    # The LB computes the (JSON-parse-cost) affinity hint only for
    # policies that set this.
    wants_affinity_key = False

    def has_replicas(self) -> bool:
        with self._lock:
            return bool(self._replicas)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._replicas: List[str] = []       # replica URLs
        self._in_flight: Dict[str, int] = {}
        # Engine-reported saturation (url → queue depth), published by
        # the controller after every scrape round (observe/scrape.py).
        # STALE entries never arrive here — the scraper's snapshot
        # withholds them — so an empty dict degrades every policy to
        # its pre-fleet-telemetry behavior.
        self._saturation: Dict[str, float] = {}

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            self._replicas = list(urls)
            self._in_flight = {
                u: self._in_flight.get(u, 0) for u in urls
            }

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        """Optional per-replica capacity weights (url → relative QPS
        capability). Base policies ignore them; instance-aware ones
        normalize load by them."""
        del weights

    def set_replica_saturation(self,
                               queue_depths: Dict[str, float]) -> None:
        """Fresh engine-reported queue depths (url → depth). Load-aware
        policies use them to break in-flight-count ties: the LB's own
        in-flight count sees requests it proxied, the engine's queue
        depth also prices what each request COSTS (a 4k-token prefill
        queues deeper than a chat turn)."""
        with self._lock:
            self._saturation = dict(queue_depths)

    def _load_key(self, url: str):
        """Sort key for 'least loaded': LB-side in-flight first (it
        moves per request, the scraped depth only per scrape round),
        engine queue depth as the tie-breaker."""
        return (self._in_flight.get(url, 0),
                self._saturation.get(url, 0.0))

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        """Pick a replica. `affinity_key` (e.g. the prompt head) is a
        ROUTING HINT — only affinity-aware policies use it; the rest
        ignore it."""
        raise NotImplementedError

    def request_started(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = self._in_flight.get(url, 0) + 1

    def request_finished(self, url: str) -> None:
        with self._lock:
            if url in self._in_flight:
                self._in_flight[url] = max(0, self._in_flight[url] - 1)


@registry.LB_POLICY_REGISTRY.register(name='round_robin')
class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._counter = itertools.count()

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return self._replicas[next(self._counter) % len(self._replicas)]


@registry.LB_POLICY_REGISTRY.register(name='least_load')
class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests
    (reference default — best for LLM serving where request cost
    varies wildly), with scraped engine queue depth breaking ties —
    two replicas with equal in-flight counts can hide very different
    admission backlogs."""

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return min(self._replicas, key=self._load_key)


@registry.LB_POLICY_REGISTRY.register(name='instance_aware_least_load')
class InstanceAwareLeastLoadPolicy(LeastLoadPolicy):
    """Least NORMALIZED load: in-flight count divided by the replica's
    capacity weight, so a v5e-16 replica takes proportionally more
    traffic than a v5e-8 one in a heterogeneous (e.g. spot-fallback)
    replica set. Weights come from the serve controller (chip count of
    each replica's launched slice). Reference analog:
    sky/serve/load_balancing_policies.py:151
    (InstanceAwareLeastLoadPolicy, normalized by per-accelerator target
    QPS)."""

    def __init__(self) -> None:
        super().__init__()
        self._weights: Dict[str, float] = {}

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        with self._lock:
            self._weights = {u: max(float(w), 1e-9)
                             for u, w in weights.items()}

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return min(
                self._replicas,
                key=lambda u: (self._in_flight.get(u, 0) /
                               self._weights.get(u, 1.0),
                               self._saturation.get(u, 0.0) /
                               self._weights.get(u, 1.0)))


@registry.LB_POLICY_REGISTRY.register(name='prefix_affinity')
class PrefixAffinityPolicy(LeastLoadPolicy):
    """Rendezvous-hash requests sharing a prompt prefix onto the same
    replica, so per-replica prefix KV caches (serve/engine.py) keep
    hitting — the chat pattern (same system prompt / growing history)
    stays warm on one replica instead of spraying across the fleet.

    Net-new vs the reference (its LB policies are load-only); the
    analog in big serving stacks is vLLM router session affinity.

    Rendezvous (highest-random-weight) hashing keeps the mapping stable
    under replica churn: removing a replica remaps ONLY the keys that
    lived on it. A load guard falls back to least-load when the
    affinity target is overloaded relative to the fleet (affinity must
    never become a hot-spot amplifier).
    """

    # Fall back to least-load when the affinity target has this many
    # more in-flight requests than the least-loaded replica.
    HOTSPOT_SLACK = 4
    wants_affinity_key = True

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            coolest = min(self._replicas, key=self._load_key)
            if affinity_key is None:
                return coolest
            target = max(
                self._replicas,
                key=lambda u: hashlib.md5(
                    f'{affinity_key}\x00{u}'.encode()).digest())
            if (self._in_flight.get(target, 0) -
                    self._in_flight.get(coolest, 0)) > self.HOTSPOT_SLACK:
                return coolest
            return target
