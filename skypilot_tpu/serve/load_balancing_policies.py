"""Per-request replica selection policies.

Reference analog: sky/serve/load_balancing_policies.py
(`RoundRobinPolicy:85`, `LeastLoadPolicy:111` — the default).
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

from skypilot_tpu.utils import registry


class LoadBalancingPolicy:
    """Tracks the ready-replica set and picks a target per request."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._replicas: List[str] = []       # replica URLs
        self._in_flight: Dict[str, int] = {}

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            self._replicas = list(urls)
            self._in_flight = {
                u: self._in_flight.get(u, 0) for u in urls
            }

    def select(self) -> Optional[str]:
        raise NotImplementedError

    def request_started(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = self._in_flight.get(url, 0) + 1

    def request_finished(self, url: str) -> None:
        with self._lock:
            if url in self._in_flight:
                self._in_flight[url] = max(0, self._in_flight[url] - 1)


@registry.LB_POLICY_REGISTRY.register(name='round_robin')
class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._counter = itertools.count()

    def select(self) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            return self._replicas[next(self._counter) % len(self._replicas)]


@registry.LB_POLICY_REGISTRY.register(name='least_load')
class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests (reference
    default — best for LLM serving where request cost varies wildly)."""

    def select(self) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            return min(self._replicas,
                       key=lambda u: self._in_flight.get(u, 0))
