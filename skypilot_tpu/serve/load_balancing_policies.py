"""Per-request replica selection policies.

Reference analog: sky/serve/load_balancing_policies.py
(`RoundRobinPolicy:85`, `LeastLoadPolicy:111` — the default).

Disaggregated serving adds :class:`PoolRouter` — not a registered
policy but the LB's two-stage routing state: a class/length-aware pick
over the PREFILL pool (least-load; only prompts long enough to be
worth a handoff round-trip go two-stage) and a session-ring-pinned
pick over the DECODE pool (the PR-12 bounded-load consistent-hash
ring, so a session's decode replica — and any prefix pages adopted
there — stays stable across LB restarts and pool churn).
"""
from __future__ import annotations

import bisect
import hashlib
import itertools
import math
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import registry


class LoadBalancingPolicy:
    """Tracks the ready-replica set and picks a target per request."""

    # The LB computes the (JSON-parse-cost) affinity hint only for
    # policies that set this.
    wants_affinity_key = False

    def has_replicas(self) -> bool:
        with self._lock:
            return bool(self._replicas)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._replicas: List[str] = []       # replica URLs
        self._in_flight: Dict[str, int] = {}
        # Engine-reported saturation (url → queue depth), published by
        # the controller after every scrape round (observe/scrape.py).
        # STALE entries never arrive here — the scraper's snapshot
        # withholds them — so an empty dict degrades every policy to
        # its pre-fleet-telemetry behavior.
        self._saturation: Dict[str, float] = {}

    def set_ready_replicas(self, urls: List[str]) -> None:
        with self._lock:
            self._replicas = list(urls)
            self._in_flight = {
                u: self._in_flight.get(u, 0) for u in urls
            }

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        """Optional per-replica capacity weights (url → relative QPS
        capability). Base policies ignore them; instance-aware ones
        normalize load by them."""
        del weights

    def set_replica_saturation(self,
                               queue_depths: Dict[str, float]) -> None:
        """Fresh engine-reported queue depths (url → depth). Load-aware
        policies use them to break in-flight-count ties: the LB's own
        in-flight count sees requests it proxied, the engine's queue
        depth also prices what each request COSTS (a 4k-token prefill
        queues deeper than a chat turn)."""
        with self._lock:
            self._saturation = dict(queue_depths)

    def _load_key(self, url: str):
        """Sort key for 'least loaded': LB-side in-flight first (it
        moves per request, the scraped depth only per scrape round),
        engine queue depth as the tie-breaker."""
        return (self._in_flight.get(url, 0),
                self._saturation.get(url, 0.0))

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        """Pick a replica. `affinity_key` (e.g. the prompt head) is a
        ROUTING HINT — only affinity-aware policies use it; the rest
        ignore it."""
        raise NotImplementedError

    def request_started(self, url: str) -> None:
        with self._lock:
            self._in_flight[url] = self._in_flight.get(url, 0) + 1

    def request_finished(self, url: str) -> None:
        with self._lock:
            if url in self._in_flight:
                self._in_flight[url] = max(0, self._in_flight[url] - 1)


@registry.LB_POLICY_REGISTRY.register(name='round_robin')
class RoundRobinPolicy(LoadBalancingPolicy):

    def __init__(self) -> None:
        super().__init__()
        self._counter = itertools.count()

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return self._replicas[next(self._counter) % len(self._replicas)]


@registry.LB_POLICY_REGISTRY.register(name='least_load')
class LeastLoadPolicy(LoadBalancingPolicy):
    """Route to the replica with the fewest in-flight requests
    (reference default — best for LLM serving where request cost
    varies wildly), with scraped engine queue depth breaking ties —
    two replicas with equal in-flight counts can hide very different
    admission backlogs."""

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return min(self._replicas, key=self._load_key)


@registry.LB_POLICY_REGISTRY.register(name='instance_aware_least_load')
class InstanceAwareLeastLoadPolicy(LeastLoadPolicy):
    """Least NORMALIZED load: in-flight count divided by the replica's
    capacity weight, so a v5e-16 replica takes proportionally more
    traffic than a v5e-8 one in a heterogeneous (e.g. spot-fallback)
    replica set. Weights come from the serve controller (chip count of
    each replica's launched slice). Reference analog:
    sky/serve/load_balancing_policies.py:151
    (InstanceAwareLeastLoadPolicy, normalized by per-accelerator target
    QPS)."""

    def __init__(self) -> None:
        super().__init__()
        self._weights: Dict[str, float] = {}

    def set_replica_weights(self, weights: Dict[str, float]) -> None:
        with self._lock:
            self._weights = {u: max(float(w), 1e-9)
                             for u, w in weights.items()}

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        del affinity_key
        with self._lock:
            if not self._replicas:
                return None
            return min(
                self._replicas,
                key=lambda u: (self._in_flight.get(u, 0) /
                               self._weights.get(u, 1.0),
                               self._saturation.get(u, 0.0) /
                               self._weights.get(u, 1.0)))


class _HashRing:
    """A deterministic consistent-hash ring over replica URLs.

    Determinism is the whole point: ring points are md5 of
    ``<url>#<vnode>`` — a pure function of the replica set — so a
    REBUILT ring (LB restart, controller failover) maps every key to
    the same replica as its predecessor, with no state to persist or
    hand off. VNODES points per replica smooth arc sizes so removing
    one replica spreads its keys roughly evenly over the survivors
    instead of dumping them on one neighbor.
    """

    VNODES = 64

    def __init__(self, urls: List[str]):
        points = []
        for url in sorted(set(urls)):
            for i in range(self.VNODES):
                points.append((self._point(f'{url}#{i}'), url))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    @staticmethod
    def _point(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8],
                              'big')

    def walk(self, key: str) -> Iterator[str]:
        """Replica URLs clockwise from the key's ring position, each
        DISTINCT replica yielded once — the bounded-load probe order.
        The first yield is the key's home replica; later yields are
        the deterministic spill order when the home is over the load
        bound."""
        n = len(self._points)
        if n == 0:
            return
        start = bisect.bisect_right(self._hashes, self._point(key))
        seen = set()
        for step in range(n):
            _, url = self._points[(start + step) % n]
            if url not in seen:
                seen.add(url)
                yield url


@registry.LB_POLICY_REGISTRY.register(name='prefix_affinity',
                                      aliases=['consistent_hash'])
class PrefixAffinityPolicy(LeastLoadPolicy):
    """Bounded-load consistent hashing: requests sharing a session (or
    prompt-prefix) key land on one replica, so per-replica prefix KV
    caches (serve/engine.py) keep hitting — the chat pattern (same
    system prompt / growing history) stays warm on one replica instead
    of spraying across the fleet.

    Two properties the earlier rendezvous+slack version lacked, both
    exposed the moment a replayable load harness measured them
    (skypilot_tpu/loadgen):

      * RESTART-STABLE: the ring is a pure function of the replica
        set (_HashRing), so a restarted LB (fresh in-flight counts,
        fresh policy object) routes every session exactly where the
        old process did — sessions keep their hot prefix pages through
        rolling updates and controller failover. The old version's
        in-flight-delta fallback made post-restart routing depend on
        arrival order.
      * LOAD-BOUNDED (the consistent-hashing-with-bounded-loads
        recipe): a replica accepts an affinity request only while its
        in-flight count stays within LOAD_BOUND x the fleet's mean;
        past that, the walk spills to the NEXT ring replica — itself
        deterministic — so a Zipf-popular session can never turn
        affinity into a hot-spot amplifier, and the spill target is
        stable rather than "whichever replica was coolest".

    Churn behavior is the classic consistent-hash guarantee: removing
    a replica remaps only the keys that lived on it; adding one steals
    only the arcs it now owns.
    """

    # Max in-flight on a replica relative to a perfectly even spread
    # before an affinity request spills to the next ring replica
    # (c in the bounded-load literature; 1.25 keeps p99 load within
    # ~25% of mean while remapping few keys).
    LOAD_BOUND = 1.25
    wants_affinity_key = True

    def __init__(self) -> None:
        super().__init__()
        self._ring = _HashRing([])

    def set_ready_replicas(self, urls: List[str]) -> None:
        ring = _HashRing(urls)          # built outside the lock
        with self._lock:
            self._replicas = list(urls)
            self._in_flight = {
                u: self._in_flight.get(u, 0) for u in urls
            }
            self._ring = ring

    def _capacity(self) -> int:
        """Per-replica admission bound: ceil(c * (total_in_flight + 1)
        / n). The +1 counts the request being placed, so a single
        replica fleet (mean == its own load) always admits."""
        total = sum(self._in_flight.get(u, 0) for u in self._replicas)
        return math.ceil(self.LOAD_BOUND * (total + 1) /
                         len(self._replicas))

    def select(self, affinity_key: Optional[str] = None) -> Optional[str]:
        with self._lock:
            if not self._replicas:
                return None
            if affinity_key is None:
                return min(self._replicas, key=self._load_key)
            capacity = self._capacity()
            for url in self._ring.walk(affinity_key):
                if self._in_flight.get(url, 0) + 1 <= capacity:
                    return url
            # Every replica at the bound (only possible transiently —
            # capacity tracks total load): plain least-load.
            return min(self._replicas, key=self._load_key)


# ------------------------------------------------------------------
# Disaggregated prefill/decode routing (serve/disagg; docs/serving.md)
# ------------------------------------------------------------------

# Prompts shorter than this (tokens; chars/4 for string prompts) skip
# the two-stage pipeline: a tiny prefill on the decode replica costs
# less than a handoff round-trip, and short interactive turns are the
# TPOT-sensitive traffic disaggregation protects. Matches the engine's
# 64-token prefix-snapshot floor by default.
DISAGG_MIN_PROMPT_ENV = 'SKYTPU_LB_DISAGG_MIN_PROMPT'
DISAGG_MIN_PROMPT_DEFAULT = 64


def _prompt_units(payload: Dict[str, Any], path: str) -> Optional[int]:
    """Estimated prompt length (tokens, or chars/4 for text) of a
    single-prompt generation body; None when the shape is not the
    single-prompt form the two-stage pipeline serves."""
    if path == '/generate':
        tokens = payload.get('tokens')
        if isinstance(tokens, list) and all(
                isinstance(t, int) for t in tokens):
            return len(tokens)
        text = payload.get('text')
        if isinstance(text, str):
            return max(1, len(text) // 4)
        return None
    prompt = payload.get('prompt')
    if isinstance(prompt, list) and prompt and all(
            isinstance(t, int) for t in prompt):
        return len(prompt)
    if isinstance(prompt, str) and prompt:
        return max(1, len(prompt) // 4)
    return None


class PoolRouter:
    """Two-stage routing state for disaggregated serving.

    ``plan()`` is the class/length-aware gate: only single-prompt
    generation POSTs whose prompt is long enough (or whose declared
    class is ``long_context``) route prefill-pool-first; everything
    else — short interactive turns, chat/batched/multi-choice shapes,
    stop-string bodies — proxies single-stage to the decode pool,
    which is a full engine. ``pick_prefill`` is least-load over the
    prefill pool; ``pick_decode`` is the deterministic bounded-load
    session ring over the decode pool (restart-stable, so adopted
    pages and prefix snapshots stay hot on one replica)."""

    def __init__(self, min_prompt: Optional[int] = None):
        if min_prompt is None:
            min_prompt = knobs.get_int(DISAGG_MIN_PROMPT_ENV)
        self.min_prompt = min_prompt
        self._prefill = LeastLoadPolicy()
        self._decode = PrefixAffinityPolicy()

    # ------------------------------------------------------- pool state
    def set_pools(self, prefill_urls: List[str],
                  decode_urls: List[str]) -> None:
        self._prefill.set_ready_replicas(prefill_urls)
        self._decode.set_ready_replicas(decode_urls)

    def set_saturation(self, queue_depths: Dict[str, float]) -> None:
        self._prefill.set_replica_saturation(queue_depths)
        self._decode.set_replica_saturation(queue_depths)

    def has_pools(self) -> bool:
        return self._prefill.has_replicas() and \
            self._decode.has_replicas()

    def prefill_urls(self) -> List[str]:
        with self._prefill._lock:  # pylint: disable=protected-access
            return list(self._prefill._replicas)  # pylint: disable=protected-access

    # ------------------------------------------------------ eligibility
    @staticmethod
    def eligible(method: str, path: str) -> bool:
        """The cheap pre-parse gate: only these (method, path) pairs
        can ever route two-stage, so the LB skips the body JSON parse
        for everything else (chat bodies are multi-KB)."""
        return method == 'POST' and path in ('/generate',
                                             '/v1/completions')

    def plan(self, method: str, path: str, payload: Any,
             cls: str) -> Optional[Dict[str, Any]]:
        """The two-stage routing decision for one request, or None for
        single-stage. ``payload`` is the parsed JSON body (or None).
        The returned plan carries what the LB's disagg pipeline needs:
        the orig path, streaming-ness, and the prompt estimate."""
        if not self.eligible(method, path) or \
                not isinstance(payload, dict):
            return None
        units = _prompt_units(payload, path)
        if units is None:
            return None
        if path == '/v1/completions':
            # Shapes the /disagg endpoints don't serve stay
            # single-stage on the (full-engine) decode pool.
            if payload.get('stop') or payload.get('logprobs') \
                    or payload.get('suffix'):
                return None
            if int(payload.get('n') or 1) != 1 or \
                    int(payload.get('best_of') or 0) > 1:
                return None
        if cls != 'long_context' and units < self.min_prompt:
            return None
        # /generate ignores 'stream' (plain JSON always) — the plan
        # must agree, or the disagg pipeline would answer the same
        # body SSE-shaped while the monolithic endpoint answers JSON.
        stream = (bool(payload.get('stream'))
                  if path == '/v1/completions' else False)
        return {'path': path, 'units': units, 'stream': stream}

    # ------------------------------------------------------------ picks
    def pick_prefill(self, excluded=()) -> Optional[str]:
        p = self._prefill
        with p._lock:  # pylint: disable=protected-access
            candidates = [u for u in p._replicas  # pylint: disable=protected-access
                          if u not in excluded]
            if not candidates:
                return None
            return min(candidates, key=p._load_key)  # pylint: disable=protected-access

    def pick_decode(self, key: Optional[str],
                    excluded=()) -> Optional[str]:
        d = self._decode
        if not excluded:
            return d.select(key)
        with d._lock:  # pylint: disable=protected-access
            candidates = [u for u in d._replicas  # pylint: disable=protected-access
                          if u not in excluded]
            if not candidates:
                return None
            if key is not None:
                for url in d._ring.walk(key):  # pylint: disable=protected-access
                    if url in candidates:
                        return url
            return min(candidates, key=d._load_key)  # pylint: disable=protected-access

    # ------------------------------------------------- load accounting
    def request_started(self, prefill_url: str, decode_url: str) -> None:
        self._prefill.request_started(prefill_url)
        self._decode.request_started(decode_url)

    def request_finished(self, prefill_url: str,
                         decode_url: str) -> None:
        self._prefill.request_finished(prefill_url)
        self._decode.request_finished(decode_url)
