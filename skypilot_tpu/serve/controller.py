"""Per-service controller process: reconcile loop + load balancer.

Reference analog: sky/serve/service.py + controller.py — there, controller
and LB are separate processes on a controller cluster; here one detached
process runs both (reconcile loop in a thread, LB on the asyncio loop),
because a process boundary between two components that share only the
ready-replica list buys nothing but IPC.
"""
from __future__ import annotations

import argparse
import os
import threading
import time
import traceback

from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.observe import costs as costs_lib
from skypilot_tpu.observe import scrape as scrape_lib
from skypilot_tpu.observe import slo as slo_lib
from skypilot_tpu.serve import autoscalers as autoscaler_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger('skypilot_tpu.serve.controller')

RECONCILE_SECONDS = knobs.get_float('SKYTPU_SERVE_SYNC_SECONDS')
# Journal/span retention cadence for THIS process (mirrors the API
# server's hourly GC loop): the controller and its LB write journal
# events and spans into their own DB — often on a different host from
# the API server — so without a local observe.gc() those rows would
# grow until the disk fills.
GC_INTERVAL_SECONDS = knobs.get_float('SKYTPU_SERVE_GC_SECONDS')


class ServiceController:

    def __init__(self, service_name: str):
        record = serve_state.get_service(service_name)
        if record is None:
            raise ValueError(f'Service {service_name!r} not found.')
        self.name = service_name
        self.record = record
        # One controller process per service: adopt the trace of the
        # `serve up` request so replica transitions, probe events and
        # launch subprocesses all correlate back to it.
        from skypilot_tpu.observe import trace
        trace.adopt(record.get('trace_id'))
        self._load_from_record(record)
        version = int(record.get('version') or 1)
        update_mode = record.get('update_mode') or 'rolling'
        if self.spec.disagg is not None:
            # Disaggregated service: one manager per pool, sharing the
            # service's replica-id sequence and partitioning the
            # replica table by role-tagged cluster names.
            self.managers = {
                role: replica_managers.ReplicaManager(
                    self.name, self.task, self.spec, version=version,
                    update_mode=update_mode, role=role)
                for role in ('prefill', 'decode')}
        else:
            self.managers = {None: replica_managers.ReplicaManager(
                self.name, self.task, self.spec, version=version,
                update_mode=update_mode)}
        # Back-compat alias: the monolithic manager (tests, update
        # adoption). Disagg updates adopt through every manager.
        self.manager = next(iter(self.managers.values()))
        self.lb = lb_lib.LoadBalancer(self.spec.load_balancing_policy,
                                      self.autoscaler,
                                      service_name=self.name)
        # Fleet telemetry plane (non-pool services): the scraper pulls
        # every READY replica's /metrics + /health each round; the SLO
        # engine evaluates burn rates over the stored samples; the
        # saturation snapshot feeds the LB policy's tie-breaker and
        # the saturation autoscaler. Pools have no replica HTTP apps
        # to scrape.
        self.scraper = None
        self.slo_engine = None
        self.cost_meter = None
        self.scrape_loop = None
        if not self.spec.pool:
            self.scraper = scrape_lib.Scraper()
            specs = slo_lib.default_specs()
            if self.spec.disagg is not None:
                # Per-stage SLO kinds (observe/slo.py): queue wait on
                # the prefill pool, decode-side TTFT (adoption → first
                # streamed token) on the decode pool — each evaluated
                # over ITS pool's scrape targets only.
                specs += [
                    slo_lib.SLOSpec(kind='prefill_queue',
                                    objective=0.95,
                                    threshold_seconds=2.5),
                    slo_lib.SLOSpec(kind='decode_ttft', objective=0.95,
                                    threshold_seconds=1.0),
                ]
            self.slo_engine = slo_lib.SLOEngine(specs, entity=self.name)
            # Cost attribution rides the same scrape cadence: the
            # meter registers/deregisters with the routable set and
            # accrues + evaluates budgets each round, entity-scoped to
            # this service like the SLO engine.
            self.cost_meter = costs_lib.CostMeter(entity=self.name)
            self.scrape_loop = scrape_lib.ScrapeLoop(
                self.scraper, on_round=self._on_scrape_round)
            self.lb.attach_fleet(self.scraper, self.slo_engine,
                                 self.cost_meter)
        self._stop = threading.Event()

    def _load_from_record(self, record) -> None:
        """Build spec/task/autoscaler(s) from a service record (shared
        by startup and update adoption)."""
        self.spec = spec_lib.ServiceSpec.from_yaml_config(record['spec'])
        task_cfg = dict(record['task_config'])
        task_cfg.pop('service', None)
        self.task = task_lib.Task.from_yaml_config(task_cfg)
        if self.spec.disagg is not None:
            # One autoscaler per pool — independent scaling is the
            # point of disaggregation: the prefill pool grows off its
            # queue saturation while the decode pool holds TPOT.
            # The role doubles as the elastic pool label, so each
            # pool's decisions land under skytpu_elastic_target{pool}.
            self.autoscalers = {
                role: autoscaler_lib.Autoscaler.make(
                    self.spec.disagg.role_policy(role), pool=role)
                for role in ('prefill', 'decode')}
            # The LB's request-rate signal (QPS fallback) goes to the
            # decode pool's autoscaler: every request decodes; only
            # long-prompt ones prefill remotely.
            self.autoscaler = self.autoscalers['decode']
        else:
            self.autoscaler = autoscaler_lib.Autoscaler.make(
                self.spec.policy)
            self.autoscalers = {None: self.autoscaler}
        # url → pool role, refreshed each reconcile pass; the scrape
        # round splits saturation snapshots per pool with it.
        self._pool_urls = {}

    def _maybe_adopt_update(self, record) -> None:
        """serve update bumped the stored version: reload task/spec and let
        reconcile migrate the replica set (rolling or blue_green). The
        manager's version is the comparison base — it also moves on a
        failed-update rollback, which rewrites the record itself."""
        version = int(record.get('version') or 1)
        if version == self.manager.version:
            # Keep the controller's own mirrors in step (rollback case).
            if self.spec is not self.manager.spec:
                self.spec = self.manager.spec
                self.task = self.manager.task
                self.autoscaler = autoscaler_lib.Autoscaler.make(
                    self.spec.policy)
            return
        self._load_from_record(record)
        # Disagg: every pool manager adopts the new version (a
        # mono↔disagg TOPOLOGY change needs a controller restart —
        # the manager set is fixed at startup; documented in
        # docs/serving.md).
        for manager in self.managers.values():
            manager.reload(self.task, self.spec, version,
                           record.get('update_mode') or 'rolling')

    # ------------------------------------------------------------------
    def _on_scrape_round(self, scraper: 'scrape_lib.Scraper') -> None:
        """After every scrape round (scrape-loop thread): publish the
        FRESH saturation snapshot to the LB policy and the autoscaler,
        then evaluate the SLOs over the stored samples. Attribute
        reads, not captures — update adoption swaps self.autoscaler."""
        snapshot = scraper.saturation_snapshot()
        depths = {url: s.queue_depth for url, s in snapshot.items()}
        self.lb.set_replica_saturation(depths)
        if self.spec.disagg is not None:
            # Independent pool scaling: each autoscaler sees only ITS
            # pool's saturation (an empty sub-snapshot is no-signal →
            # QPS fallback / hold, exactly the monolithic contract).
            pool_urls = dict(self._pool_urls)
            for role, autoscaler in self.autoscalers.items():
                autoscaler.observe_saturation(
                    {u: d for u, d in depths.items()
                     if pool_urls.get(u) == role})
        else:
            self.autoscaler.observe_saturation(depths)
        if self.slo_engine is not None:
            self.slo_engine.evaluate()
        if self.cost_meter is not None:
            self.cost_meter.accrue()
            self.cost_meter.evaluate()

    def _sync_scrape_targets(self, id_urls) -> None:
        """Reconcile-thread hook: the scrape target set IS the
        routable set (the pass's ready_id_urls() snapshot — one filter
        definition, one query), identified by journal entity
        (<service>/<replica_id>)."""
        if self.scraper is None:
            return
        self._set_fleet_targets([
            scrape_lib.Target(entity=f'{self.name}/{rid}', url=url)
            for rid, url in id_urls])

    def _set_fleet_targets(self, targets) -> None:
        """One routable-set hand-off for BOTH fleet consumers: the
        scraper's target set and the cost meter's metered-replica set
        stay the same snapshot (a replica the LB can route must be
        both scraped and billed). The meter prices each entity's pool
        from its role segment; register() is idempotent and a dropped
        entity gets its final accrual on deregister."""
        self.scraper.set_targets(targets)
        if self.cost_meter is None:
            return
        try:
            live = {t.entity for t in targets}
            for entity in list(self.cost_meter.replicas()):
                if entity not in live:
                    self.cost_meter.deregister(entity)
            for t in targets:
                parts = t.entity.split('/')
                pool = (parts[-2] if len(parts) >= 3 and
                        parts[-2] in costs_lib.POOLS else 'serve')
                self.cost_meter.register(t.entity, pool)
        except Exception:  # pylint: disable=broad-except
            # Pricing must never take down reconciliation — the next
            # pass retries registration from the same snapshot.
            logger.warning('cost meter target sync failed:\n' +
                           traceback.format_exc())

    def _maybe_gc_observe(self) -> None:
        """Hourly events+spans retention in the controller process —
        the shared observe.gc() the API server's GC loop also runs
        (GC only there would leak this process's journal/span rows
        forever when the controller runs on its own host)."""
        now = time.time()
        if now - self._last_observe_gc < GC_INTERVAL_SECONDS:
            return
        self._last_observe_gc = now
        from skypilot_tpu import observe
        pruned = observe.gc()
        if any(pruned.values()):
            logger.info(f'observe GC: pruned {pruned["events"]} '
                        f'event(s), {pruned["spans"]} span(s), '
                        f'{pruned["costs"]} cost row(s)')

    def _reconcile_loop(self) -> None:
        serve_state.set_service_status(self.name,
                                       ServiceStatus.REPLICA_INIT)
        # First pass runs a GC immediately: a controller that restarts
        # daily would otherwise never reach the interval.
        self._last_observe_gc = 0.0
        while not self._stop.is_set():
            try:
                if failpoints.ACTIVE:
                    # Inside the try: a firing exercises the pass-level
                    # containment below (one reconcile pass lost, loop
                    # alive, next pass repairs).
                    failpoints.fire('controller.reconcile')
                self._maybe_gc_observe()
                record = serve_state.get_service(self.name)
                if record is None or record['status'] in (
                        ServiceStatus.SHUTTING_DOWN, ServiceStatus.SHUTDOWN):
                    break
                self._maybe_adopt_update(record)
                permanently_failed = None
                for role, manager in self.managers.items():
                    if self.spec.pool:
                        # Worker count is resizable in place
                        # (jobs/pool.py rewrites the stored spec);
                        # honor the live value.
                        target = int((record['spec'] or {}).get(
                            'workers', self.spec.policy.min_replicas))
                    else:
                        target = self.autoscalers[role].target_replicas()
                    manager.reconcile(target)
                    if manager.permanently_failed:
                        permanently_failed = manager.permanently_failed
                if permanently_failed:
                    for manager in self.managers.values():
                        manager.terminate_all()
                    serve_state.set_service_status(
                        self.name, ServiceStatus.FAILED,
                        failure_reason=permanently_failed)
                    logger.warning(f'Service {self.name!r} FAILED: '
                                   f'{permanently_failed}')
                    break
                if self.spec.pool:
                    # Workers have no URLs; readiness is status-driven.
                    ready = [r for r in serve_state.get_replicas(self.name)
                             if r['status'] is ReplicaStatus.READY]
                elif self.spec.disagg is not None:
                    # ONE routable snapshot per pool per pass. The LB's
                    # single-stage _ready set IS the decode pool (full
                    # engines — they serve any shape); the PoolRouter
                    # gets both pools; service readiness keys on the
                    # decode pool (with no prefill replica the router
                    # has no pools and traffic degrades to monolithic
                    # on decode, which still serves).
                    pool_ready = {}
                    targets = []
                    for role, manager in self.managers.items():
                        id_urls = manager.ready_id_urls()
                        pool_ready[role] = [url for _, url in id_urls]
                        targets += [
                            scrape_lib.Target(
                                entity=f'{self.name}/{role}/{rid}',
                                url=url)
                            for rid, url in id_urls]
                    self._pool_urls = {
                        u: role for role, urls in pool_ready.items()
                        for u in urls}
                    ready = pool_ready['decode']
                    self.lb.set_ready_replicas(ready)
                    self.lb.set_pool_replicas(pool_ready['prefill'],
                                              pool_ready['decode'])
                    self.lb.policy.set_replica_weights(
                        self.managers['decode'].ready_url_weights(ready))
                    if self.scraper is not None:
                        self._set_fleet_targets(targets)
                else:
                    # ONE routable-set snapshot per pass: LB targets,
                    # capacity weights and scrape targets all derive
                    # from the same ready_id_urls() result, so a
                    # replica flipping READY mid-pass cannot make the
                    # routed set drift from the scraped set.
                    id_urls = self.manager.ready_id_urls()
                    ready = [url for _, url in id_urls]
                    self.lb.set_ready_replicas(ready)
                    self.lb.policy.set_replica_weights(
                        self.manager.ready_url_weights(ready))
                    self._sync_scrape_targets(id_urls)
                status = (ServiceStatus.READY if ready else
                          ServiceStatus.REPLICA_INIT)
                if record['status'] is not status:
                    serve_state.set_service_status(self.name, status)
            except Exception:  # pylint: disable=broad-except
                logger.warning('reconcile error:\n' + traceback.format_exc())
            self._stop.wait(RECONCILE_SECONDS)

    # ------------------------------------------------------------------
    def run(self) -> None:
        serve_state.update_service(self.name, controller_pid=os.getpid())
        if self.spec.pool:
            # Pools have no load balancer: the reconcile loop IS the
            # controller (workers are consumed via `jobs launch --pool`).
            logger.info(f'Pool {self.name!r}: reconcile loop only.')
            self._reconcile_loop()
            return
        loop_thread = threading.Thread(target=self._reconcile_loop,
                                       daemon=True)
        loop_thread.start()
        if self.scrape_loop is not None:
            self.scrape_loop.start()
        lb_port = int(self.record['lb_port'])
        logger.info(f'Service {self.name!r}: load balancer on :{lb_port}, '
                    f'policy={self.spec.load_balancing_policy}.')
        try:
            web.run_app(self.lb.build_app(), host='0.0.0.0', port=lb_port,
                        print=None, handle_signals=True)
        finally:
            self._stop.set()
            if self.scrape_loop is not None:
                self.scrape_loop.stop()
            loop_thread.join(timeout=10)


def shutdown_service(service_name: str) -> None:
    """Tear down every replica, then mark SHUTDOWN (runs in the `serve
    down` caller, not the controller, so it works when the controller is
    already dead)."""
    record = serve_state.get_service(service_name)
    if record is None:
        return
    serve_state.set_service_status(service_name,
                                   ServiceStatus.SHUTTING_DOWN)
    # Stop the controller first so it cannot relaunch what we delete.
    # SIGTERM, wait (aiohttp graceful shutdown + in-flight launch threads
    # can hold it for a while), then SIGKILL — a live controller racing the
    # teardown below would resurrect replicas.
    pid = record.get('controller_pid')
    if pid:
        pid = int(pid)

        def _dead(p: int) -> bool:
            # Reap if it's our child (a zombie still answers kill(p, 0)).
            try:
                wpid, _ = os.waitpid(p, os.WNOHANG)
                if wpid == p:
                    return True
            except ChildProcessError:
                pass          # not our child: signal-0 probe below decides
            try:
                os.kill(p, 0)
                return False
            except (OSError, ProcessLookupError):
                return True

        try:
            os.kill(pid, 15)
            for _ in range(75):           # up to 15s graceful
                if _dead(pid):
                    break
                time.sleep(0.2)
            else:
                os.kill(pid, 9)
        except (OSError, ProcessLookupError):
            pass
    spec = spec_lib.ServiceSpec.from_yaml_config(record['spec'])
    task_cfg = dict(record['task_config'])
    task_cfg.pop('service', None)
    task = task_lib.Task.from_yaml_config(task_cfg)
    roles = (['prefill', 'decode'] if spec.disagg is not None
             else [None])
    for role in roles:
        replica_managers.ReplicaManager(
            service_name, task, spec, role=role).terminate_all()
    # A launch thread that survived the SIGTERM window may have registered
    # a cluster after terminate_all enumerated the table: sweep any cluster
    # named like this service's replicas.
    from skypilot_tpu import global_state
    from skypilot_tpu.backends import slice_backend
    prefixes = tuple(
        f'{service_name}-{role}-replica-' if role else
        f'{service_name}-replica-' for role in roles)
    for cluster in global_state.get_clusters():
        if cluster['name'].startswith(prefixes):
            try:
                handle = slice_backend.SliceResourceHandle.from_dict(
                    cluster['handle'])
                slice_backend.TpuSliceBackend().teardown(handle,
                                                         terminate=True)
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Orphan sweep of {cluster["name"]}: {e}')
    serve_state.set_service_status(service_name, ServiceStatus.SHUTDOWN)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service', required=True)
    args = parser.parse_args()
    try:
        ServiceController(args.service).run()
    except Exception as e:  # pylint: disable=broad-except
        traceback.print_exc()
        serve_state.set_service_status(
            args.service, ServiceStatus.FAILED,
            failure_reason=f'{type(e).__name__}: {e}')


if __name__ == '__main__':
    main()
