"""User-facing serve API: up / status / down.

Reference analog: sky/serve client+server core (`sky serve up/status/down`).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve.serve_state import ServiceStatus

logger = sky_logging.init_logger(__name__)

DEFAULT_LB_PORT_START = 30001


def _free_port(start: int) -> int:
    for port in range(start, start + 200):
        with socket.socket() as s:
            try:
                s.bind(('127.0.0.1', port))
                return port
            except OSError:
                continue
    raise RuntimeError('No free port for the load balancer.')


def _spawn_controller(service_name: str) -> int:
    log_path = serve_state.controller_log_path(service_name)
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    pp = env.get('PYTHONPATH', '')
    if repo_root not in pp.split(os.pathsep):
        env['PYTHONPATH'] = f'{repo_root}{os.pathsep}{pp}' if pp else repo_root
    with open(log_path, 'ab') as log_file:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.serve.controller',
             '--service', service_name],
            stdout=log_file, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
    return proc.pid


from skypilot_tpu.usage import usage_lib
from skypilot_tpu.utils import knobs


@usage_lib.tracked('serve.up')
def up(task: task_lib.Task, service_name: Optional[str] = None,
       lb_port: Optional[int] = None) -> Dict[str, Any]:
    """Bring up a service; returns {name, endpoint} immediately (replicas
    come up asynchronously — watch `serve status`)."""
    if task.service_spec is None:
        raise ValueError(
            "Task has no 'service:' section; add one (readiness_probe, "
            "replicas/replica_policy, ports) to serve it.")
    from skypilot_tpu import admin_policy
    task = admin_policy.apply(task, 'serve.up', cluster_name=service_name)
    spec = spec_lib.ServiceSpec.from_yaml_config(task.service_spec)
    from skypilot_tpu.serve import spot_placer as spot_placer_lib
    spot_placer_lib.validate_spec(spec, task)
    if spec.pool and task.run is not None:
        raise ValueError(
            "A pool task must not have a 'run' section — workers idle "
            'after setup; jobs submitted with --pool bring their own run '
            'command.')
    name = service_name or task.name or 'service'
    existing = serve_state.get_service(name)
    if existing is not None and not existing['status'].is_terminal():
        raise ValueError(
            f'Service {name!r} already exists ({existing["status"].value}). '
            f'Tear it down first with `skytpu serve down {name}`.')
    if existing is not None:
        serve_state.remove_service(name)
    if spec.pool:
        lb_port = 0          # pools run no load balancer
    elif lb_port is None:
        lb_port = _free_port(DEFAULT_LB_PORT_START)
    if not serve_state.add_service(name, task.to_yaml_config(),
                                   spec.to_yaml_config(), lb_port):
        # Lost a concurrent-up race: a second controller would fight the
        # winner over the LB port and clobber its status.
        raise ValueError(f'Service {name!r} was just created by another '
                         f'request; check `skytpu serve status`.')
    pid = _spawn_controller(name)
    serve_state.update_service(name, controller_pid=pid)
    if spec.pool:
        logger.info(f'Pool {name!r} starting; '
                    f'{spec.policy.min_replicas} worker(s) '
                    f'(controller pid {pid}).')
        return {'name': name, 'endpoint': None}
    endpoint = f'http://127.0.0.1:{lb_port}'
    logger.info(f'Service {name!r} starting; endpoint {endpoint} '
                f'(controller pid {pid}).')
    return {'name': name, 'endpoint': endpoint}


from skypilot_tpu.utils.proc import pid_alive as _pid_alive

# A service whose controller dies at every spawn (poisoned spec, broken
# environment) stops being respawned past this many restarts — otherwise
# every `serve status` forks another doomed controller, forever.
MAX_CONTROLLER_RESTARTS = knobs.get_int(
    'SKYTPU_SERVE_MAX_CONTROLLER_RESTARTS')


def maybe_recover_controllers() -> None:
    """Crash watchdog (jobs-scheduler analog): a non-terminal service or
    pool whose controller process died hard gets a fresh controller that
    re-adopts its replicas from state (the reconcile loop is stateless
    against the DB, so resume = restart the process)."""
    from skypilot_tpu.utils import locks
    with locks.cluster_status_lock('serve-watchdog', timeout=30):
        for r in serve_state.get_services():
            if r['status'].is_terminal() or \
                    r['status'] is ServiceStatus.SHUTTING_DOWN:
                continue
            if _pid_alive(r.get('controller_pid')):
                continue
            restarts = int(r.get('controller_restarts') or 0) + 1
            if restarts > MAX_CONTROLLER_RESTARTS:
                serve_state.set_service_status(
                    r['name'], ServiceStatus.FAILED,
                    failure_reason=f'controller died {restarts} times')
                logger.warning(f'Controller of {r["name"]!r} keeps dying; '
                               f'marked FAILED (tear down with serve '
                               f'down).')
                continue
            pid = _spawn_controller(r['name'])
            serve_state.update_service(r['name'], controller_pid=pid,
                                       controller_restarts=restarts)
            logger.warning(f'Controller of {r["name"]!r} died; resumed '
                           f'with pid={pid} (restart {restarts}).')


def status(service_names: Optional[List[str]] = None,
           pool: Optional[bool] = None) -> List[Dict[str, Any]]:
    """Service (pool=False), pool (pool=True), or combined (None) status."""
    maybe_recover_controllers()
    records = serve_state.get_services()
    if service_names:
        records = [r for r in records if r['name'] in service_names]
    out = []
    for r in records:
        is_pool = bool((r['spec'] or {}).get('pool'))
        if pool is not None and is_pool != pool:
            continue
        replicas = serve_state.get_replicas(r['name'])
        out.append({
            'name': r['name'],
            'status': r['status'],
            'endpoint': (None if is_pool else
                         f"http://127.0.0.1:{r['lb_port']}"),
            'pool': is_pool,
            'version': int(r.get('version') or 1),
            'update_mode': r.get('update_mode') or 'rolling',
            'created_at': r['created_at'],
            'failure_reason': r.get('failure_reason'),
            'replicas': [{
                'replica_id': rep['replica_id'],
                'status': rep['status'],
                'url': rep['url'],
                'cluster_name': rep['cluster_name'],
                'job_id': rep.get('job_id'),
                'version': int(rep.get('version') or 1),
            } for rep in replicas],
        })
    return out


@usage_lib.tracked('serve.update')
def update(task: task_lib.Task, service_name: str,
           mode: str = 'rolling') -> Dict[str, Any]:
    """Migrate a live service to a new task/spec version.

    Reference analog: sky serve update (serve_utils.UpdateMode —
    `rolling` replaces replicas one at a time with the READY count never
    dipping below target; `blue_green` brings up a full new set and cuts
    traffic over atomically). The live controller adopts the bumped
    version on its next reconcile pass.
    """
    if mode not in ('rolling', 'blue_green'):
        raise ValueError(f"update mode must be 'rolling' or 'blue_green', "
                         f'got {mode!r}')
    record = serve_state.get_service(service_name)
    if record is None or record['status'].is_terminal():
        raise ValueError(
            f'Service {service_name!r} is not running; use `serve up`.')
    if task.service_spec is None:
        raise ValueError("Task has no 'service:' section.")
    from skypilot_tpu import admin_policy
    task = admin_policy.apply(task, 'serve.update',
                              cluster_name=service_name)
    spec = spec_lib.ServiceSpec.from_yaml_config(task.service_spec)
    from skypilot_tpu.serve import spot_placer as spot_placer_lib
    spot_placer_lib.validate_spec(spec, task)
    was_pool = bool((record['spec'] or {}).get('pool'))
    if spec.pool != was_pool:
        raise ValueError('Cannot convert between a service and a pool; '
                         'tear down and recreate instead.')
    import json as json_lib
    version = int(record.get('version') or 1) + 1
    serve_state.update_service(
        service_name,
        task_config=json_lib.dumps(task.to_yaml_config()),
        spec=json_lib.dumps(spec.to_yaml_config()),
        version=version, update_mode=mode)
    logger.info(f'Service {service_name!r} updating to version {version} '
                f'({mode}).')
    return {'name': service_name, 'version': version, 'mode': mode}


def down(service_name: str, purge: bool = False) -> None:
    from skypilot_tpu.serve import controller as controller_lib
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.ServeUserTerminatedError(
            f'Service {service_name!r} not found.')
    controller_lib.shutdown_service(service_name)
    if purge:
        serve_state.remove_service(service_name)
    logger.info(f'Service {service_name!r} torn down.')


def wait_until(service_name: str, statuses, timeout: float = 120.0
               ) -> ServiceStatus:
    """Test/automation helper: block until the service hits a status."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        record = serve_state.get_service(service_name)
        if record is not None:
            last = record['status']
            if last in statuses:
                return last
        time.sleep(0.3)
    raise TimeoutError(
        f'service {service_name} stuck in {last}, wanted {statuses}')
