"""Multi-host serving: one engine replica spanning a whole TPU slice.

Reference analog: the reference's serve replicas are vLLM/JetStream
instances doing TP over all chips of a (possibly multi-host) slice —
multi-host slices are one schedulable unit
(reference sky/backends/cloud_vm_ray_backend.py:6439-6452,
examples/tpu/v6e/README.md:119-127). Here the native engine does the
same: every host joins one jax.distributed job, params/cache shard over
the GLOBAL mesh, and XLA's collectives ride ICI/DCN inside the same
jitted step/admit programs single-host serving uses.

Design — leader-follower SPMD mirroring:
  - Process 0 (leader) runs the HTTP frontend and the continuous
    batcher. Every engine-level operation that touches the device
    (warmup, an admit group, a decode step DISPATCH, a step COLLECT,
    a failure reset) is broadcast over a tiny TCP control channel
    BEFORE the leader executes it. The decode pipeline's dispatch and
    collect halves are SEPARATE ops: the leader may dispatch step N+1
    before collecting step N (double buffering), and followers replay
    the identical interleaving, so every process's host state — and
    therefore its next collective — advances at the same op-stream
    points.
  - Followers run the SAME engine methods with the SAME inputs, so the
    whole host-side state (slot pool, sampling arrays, prefix store,
    speculative drafts) evolves identically everywhere and every
    process enters the same XLA collective in the same order — the
    SPMD contract. Device RNG is seeded deterministically, jit outputs
    that the host reads are replicated over the mesh, and everything
    derivable from mirrored state (speculation decisions, prefix hits,
    penalty variants) is NOT broadcast — only the leader-private bits
    are (queue-dependent step width, request payloads).

The control channel is ordered + reliable (TCP, length-prefixed
pickle); the jax.distributed coordinator handles device-level wiring.
A follower that dies takes the replica down (the slice driver restarts
the gang) — the same failure unit the reference's multi-host vLLM
replicas have.

Env contract (set by skylet/slice_driver.py for gang jobs):
SKYTPU_COORDINATOR_ADDRESS, SKYTPU_NUM_PROCESSES, SKYTPU_NODE_RANK —
the engine's --coordinator/--num-processes/--process-id default to
these, so `skytpu serve up` on a multi-host slice needs no extra
flags — plus SKYTPU_MH_TOKEN, a per-job random secret authenticating
the control channel (startup refuses to run without it; see
_resolve_token).
"""
from __future__ import annotations

import hmac
import io
import os
import pickle
import socket
import struct
import time
from typing import Any, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import failpoints as failpoints_lib
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

# The control channel listens next to the jax.distributed coordinator.
CONTROL_PORT_OFFSET = 1000
CONNECT_TIMEOUT_S = knobs.get_float('SKYTPU_MH_CONNECT_TIMEOUT')
# Per-broadcast send budget: a follower whose TCP buffer stays full
# this long is wedged, and the documented contract is to fail the
# replica loudly so the slice driver restarts the gang — NOT to park
# the leader's event-loop thread (and with it the whole HTTP frontend)
# inside sendall forever.
SEND_TIMEOUT_S = knobs.get_float('SKYTPU_MH_SEND_TIMEOUT')
# Handshake magic + shared token: a follower must prove it belongs to
# this gang before the leader counts it (and before it receives request
# payloads); anything else connecting to the port is dropped. The token
# rides the gang env like the coordinator address does.
_MAGIC = b'SKYTPU-MH1'


def _resolve_token() -> str:
    """The control-channel secret (SKYTPU_MH_TOKEN, exported per-job by
    the slice driver's gang env).

    The leader binds 0.0.0.0 and ships request payloads (user prompts)
    to anything passing the HMAC handshake, so a guessable secret —
    the old 'local' / SKYTPU_JOB_ID (a small integer) fallback — lets
    a port squatter claim a follower slot and read traffic. Multi-host
    startup now REFUSES to run without a real token; the escape hatch
    (SKYTPU_MH_ALLOW_INSECURE_TOKEN=1) exists for loopback debugging
    only."""
    token = knobs.get_str('SKYTPU_MH_TOKEN')
    if token:
        return token
    if knobs.get_bool('SKYTPU_MH_ALLOW_INSECURE_TOKEN'):
        return knobs.get_str('SKYTPU_JOB_ID', default='local')
    raise RuntimeError(
        'multi-host serving needs SKYTPU_MH_TOKEN (a per-job random '
        'secret; the slice driver exports it alongside '
        'SKYTPU_COORDINATOR_ADDRESS). Refusing the guessable '
        "'local'/job-id fallback — set "
        'SKYTPU_MH_ALLOW_INSECURE_TOKEN=1 only for loopback '
        'debugging.')


class _SafeUnpickler(pickle.Unpickler):
    """Control ops are PURE DATA (tuples/lists/dicts of primitives);
    refusing every class lookup turns a squatted port from arbitrary
    code execution into a parse error."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            f'control channel refuses class {module}.{name}')


def require_token() -> None:
    """Fail-fast preflight for multi-host startup: raise the
    _resolve_token refusal BEFORE jax.distributed joins and the model
    builds, so a missing SKYTPU_MH_TOKEN surfaces in seconds with a
    clear message instead of after minutes of boot."""
    _resolve_token()


def control_address(coordinator: str) -> Tuple[str, int]:
    host, port = coordinator.rsplit(':', 1)
    return host, int(port) + CONTROL_PORT_OFFSET


def init_distributed(coordinator: str, num_processes: int,
                     process_id: int) -> None:
    """Join the jax.distributed job (before ANY backend init).

    Pins the platform first: a force-registered TPU plugin would
    otherwise initialize during distributed setup and can hang on a
    held chip even for CPU-intended runs. On CPU, cross-process
    collectives need the gloo implementation."""
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    import jax
    if 'cpu' in (os.environ.get('JAX_PLATFORMS') or ''):
        jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    logger.info(f'jax.distributed up: process {process_id}/'
                f'{num_processes}, {len(jax.devices())} global / '
                f'{len(jax.local_devices())} local devices.')


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj)
    sock.sendall(struct.pack('>I', len(data)) + data)


def _recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, 4)
    data = _recv_exact(sock, struct.unpack('>I', hdr)[0])
    return _SafeUnpickler(io.BytesIO(data)).load()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError('control channel closed')
        buf += chunk
    return buf


class ControlLeader:
    """Process 0's side: accept every follower (handshake-verified),
    then broadcast ops."""

    def __init__(self, coordinator: str, num_processes: int):
        host, port = control_address(coordinator)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(('0.0.0.0', port))
        srv.listen(num_processes)
        srv.settimeout(CONNECT_TIMEOUT_S)
        deadline = time.time() + CONNECT_TIMEOUT_S
        self._conns = []
        want = _MAGIC + hmac.new(_resolve_token().encode(), _MAGIC,
                                 'sha256').digest()
        while len(self._conns) < num_processes - 1:
            if time.time() > deadline:
                raise TimeoutError('not all followers handshook in time')
            conn, addr = srv.accept()
            try:
                conn.settimeout(10)
                got = _recv_exact(conn, len(want))
                if not hmac.compare_digest(got, want):
                    raise ConnectionError('bad handshake')
                # Leave a SEND timeout armed for the broadcast path: a
                # wedged follower (full TCP buffer) must surface as
                # OSError in send() — the fail-the-replica path — not
                # block the event-loop thread in sendall forever.
                conn.settimeout(SEND_TIMEOUT_S)
            except (OSError, ConnectionError) as e:
                logger.warning(f'rejecting connection from {addr}: {e}')
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            logger.info(f'control follower connected: {addr}')
        srv.close()

    def send(self, op: Tuple) -> None:
        """Broadcast; a dead OR wedged follower is FATAL — the
        replica's collectives can no longer complete, so exit loudly
        and let the slice driver restart the gang (the reference's
        multi-host vLLM replicas fail the same way). The per-conn send
        timeout (SEND_TIMEOUT_S) turns a stalled follower into
        socket.timeout (an OSError) instead of parking this thread —
        the serve batch loop — in sendall indefinitely."""
        for conn in self._conns:
            try:
                if failpoints_lib.ACTIVE:
                    # Simulates a dead/wedged follower socket (delay
                    # mode models a slow one). FailpointError is caught
                    # below alongside OSError so an env-armed firing
                    # takes the SAME fail-the-replica path a real
                    # socket error does.
                    failpoints_lib.fire('multihost.send')
                _send_msg(conn, op)
            except (OSError, failpoints_lib.FailpointError) as e:
                logger.error(f'control follower lost or wedged ({e}); '
                             f'failing the replica so the gang '
                             f'restarts.')
                os._exit(13)


class ControlFollower:
    def __init__(self, coordinator: str):
        host, port = control_address(coordinator)
        deadline = time.time() + CONNECT_TIMEOUT_S
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.sendall(_MAGIC + hmac.new(_resolve_token().encode(),
                                             _MAGIC, 'sha256').digest())
        # The connect timeout must NOT persist: ops arrive whenever
        # traffic does — an idle engine would kill the channel.
        self._sock.settimeout(None)

    def recv(self) -> Tuple:
        if failpoints_lib.ACTIVE:
            # A firing here models a torn/poisoned control channel —
            # follower_serve catches FailpointError next to
            # ConnectionError, so an env-armed firing takes the same
            # leader-gone exit path a real torn channel does.
            failpoints_lib.fire('multihost.recv')
        return _recv_msg(self._sock)


def strip_items(items) -> list:
    """Admit-group items minus the leader-private stream queue/future
    (followers publish to nobody)."""
    return [tuple(it[:-2]) + (None, None) for it in items]


def follower_serve(engine, coordinator: str) -> None:
    """Follower main loop: mirror every leader op until the channel
    closes. Device work happens inside the same engine methods the
    leader runs; an op that raises here raised on the leader too (same
    computation) — the leader follows up with a 'reset'."""
    chan = ControlFollower(coordinator)
    logger.info('follower ready; mirroring leader ops.')
    failed = False
    while True:
        try:
            op = chan.recv()
        except (ConnectionError, failpoints_lib.FailpointError):
            logger.info('leader gone; follower exiting.')
            return
        kind = op[0]
        if failed and kind != 'reset':
            # We failed an op the leader completed: our device state
            # has diverged (the failed jit was donated buffers), so the
            # next collective would hang every process forever. Fail
            # the gang instead — the slice driver restarts it.
            logger.error(f'follower diverged (local failure, leader '
                         f'sent {kind!r} not reset); exiting.')
            os._exit(13)
        try:
            if kind == 'warmup':
                engine._seed = op[2]   # leader-drawn sampling seed
                if len(op) > 3:
                    # Leader's attention backend (paged hot path):
                    # every process must build the same program
                    # family — a follower's local SKYTPU_ENGINE_ATTN
                    # must not be able to split the variant matrix.
                    engine.attn_backend = op[3]
                engine.warmup(buckets=op[1])
            elif kind == 'admit':
                # op[2] (paged mode): the leader's page-allocator
                # fingerprint BEFORE this admit — our mirrored
                # allocator must agree or page assignments have
                # diverged (KV corruption); _check_page_fp raises and
                # the divergence path below exits the gang loudly.
                engine._check_page_fp(op[2] if len(op) > 2 else None)
                engine._admit_group(op[1])
            elif kind == 'chunkstart':
                # Begin a chunked admission (paged mode): reserve the
                # slot + pages and run the first prefill chunk at the
                # same op-stream point the leader does.
                engine._check_page_fp(op[2] if len(op) > 2 else None)
                engine._start_chunked(op[1])
            elif kind == 'spill':
                # Spill one prefix entry to the host tier (KV memory
                # hierarchy). The leader's idle sweep is CLOCK-driven
                # (leader-private), so unlike pressure spills — which
                # replay deterministically inside admit ops — each
                # idle spill rides an explicit op carrying the entry
                # key and the allocator fingerprint. The mirrored
                # host stores then hold identical blobs, so a later
                # wake replays deterministically inside its admit op.
                engine._check_page_fp(op[2] if len(op) > 2 else None)
                engine._spill_key(op[1])
            elif kind == 'chunk':
                # Advance one prefill chunk for the named slot (the
                # leader's round-robin choice is leader-private — the
                # slot index rides the op).
                engine._advance_chunk(op[1])
            elif kind == 'step':
                # DISPATCH only (pipelined): the leader broadcasts a
                # separate ('collect',) before it consumes the
                # outputs, so a lookahead dispatch lands here with the
                # previous step still uncollected — exactly like the
                # leader. A speculative round (host-synchronous,
                # drained points only) is derived from mirrored state
                # inside _step_or_dispatch, same as the leader.
                engine._step_or_dispatch(op[1])
            elif kind == 'collect':
                # Consume the OLDEST in-flight step's outputs at the
                # same op-stream point the leader does — host
                # bookkeeping (stop/length finishes, the device-last
                # resync) must advance in lockstep or the next reap
                # would free different slots on each process.
                engine._collect_step()
            elif kind == 'reap':
                # The leader broadcasts this at every _publish, so
                # finished slots free at EXACTLY the same point in the
                # op stream on every process — a divergent free-slot
                # choice would route the next admit to different cache
                # rows on each process.
                engine._publish()
            elif kind == 'cancel':
                # Mark only; the slot frees at the reap after the next
                # device op — the same point the leader frees it. Every
                # OTHER op records its flight events inside the shared
                # engine methods, so follower rings mirror the leader's
                # interleaving for free; cancel is applied inline here,
                # so its ring event is too (comparing rings across
                # hosts shows where a follower fell behind).
                from skypilot_tpu.observe import flight as flight_lib
                s = engine.slots[op[1]]
                if s is not None and s['finish'] is None:
                    s['finish'] = 'stop'
                    engine.flight.record(flight_lib.CANCEL, op[1])
            elif kind == 'reset':
                engine._fail_all(RuntimeError('leader reset'))
            elif kind == 'stop':
                return
            else:
                raise ValueError(f'unknown control op {kind!r}')
            failed = False
        except Exception as e:  # pylint: disable=broad-except
            # If the leader hit the same failure it broadcasts 'reset'
            # next and both sides rebuild; any OTHER next op means the
            # failure was local-only → exit (checked above).
            logger.warning(f'follower op {kind} failed: {e}')
            failed = True
