"""HTTP load balancer: reverse proxy with per-request replica selection.

Reference analog: sky/serve/load_balancer.py (FastAPI proxy). aiohttp here
(already the API server's stack). The LB runs inside the service controller
process (serve/controller.py) and is told the ready-replica set after every
reconcile pass; it feeds request timestamps to the autoscaler.

Failure containment (docs/ROBUSTNESS.md):
  - Split upstream timeouts: a CONNECT timeout detects a dead replica in
    seconds, a SOCK_READ (between-bytes) timeout catches a stalled or
    slow-loris upstream — and there is NO total cap, so a legitimate
    long streaming response is never killed at an arbitrary wall-clock
    mark (the old ``ClientTimeout(total=300)`` did both wrong).
  - Per-replica CIRCUIT BREAKER: closed → open after
    ``SKYTPU_LB_BREAKER_THRESHOLD`` consecutive upstream failures
    (traffic reroutes around it) → half-open after
    ``SKYTPU_LB_BREAKER_COOLDOWN`` seconds (exactly ONE probe request)
    → closed on success. Transitions are journaled (``lb_breaker``
    events) and counted per state in ``skytpu_lb_breaker_state``.
  - Bounded RETRY of idempotent-safe attempts: a request whose response
    has not started streaming to the client (connect failure, upstream
    disconnect before headers, read timeout before headers, breaker
    open) is retried with backoff on a different replica, up to
    ``SKYTPU_LB_RETRIES`` times (``skytpu_lb_retries_total{reason}``).
    Once response bytes have reached the client, a failure truncates —
    never silently rewrites — the stream.

Control endpoints live under /-/lb/ and /-/fleet/ (anything else is
proxied verbatim):
  GET /-/lb/health  → {ready_replicas: N}
  GET /-/lb/metrics → Prometheus exposition (per-policy request
                      counters + latency histograms, autoscaler gauges,
                      probe outcome counters — everything this
                      controller process registered)
  GET /-/lb/events  → the trace-correlated event journal (this
                      service's replica transitions included)
  GET /-/lb/trace/<trace_id>
                    → this service's span tree for one trace (the
                      lb.request → lb.pick / lb.upstream hops),
                      entity-scoped like /-/lb/events
  GET /-/fleet/metrics
                    → the MERGED fleet exposition: every fresh
                      replica's scraped /metrics, counters/gauges
                      summed and histograms merged bucket-wise
                      (observe/promtext.py) — "fleet TTFT p95" is a
                      histogram_quantile over THIS document
  GET /-/fleet/status
                    → per-replica scrape/saturation table (last
                      scrape age, queue depth, in-flight, free KV
                      pages) + current SLO states
"""
from __future__ import annotations

import asyncio
import os
import random
import time
import typing
from typing import Dict, List, Optional, Tuple

import aiohttp
from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import request_class
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.utils import failpoints as failpoints_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import autoscalers

logger = sky_logging.init_logger(__name__)

# Label bounds: policies come from the static registry (populated by
# the lb_policies import above), outcomes/reasons/states are these
# closed sets.
_OUTCOMES = ('proxied', 'upstream_error', 'no_replica', 'breaker_open',
             'client_abort')
_LB_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Load-balanced requests by policy and outcome.',
    labels={'policy': tuple(registry.LB_POLICY_REGISTRY.keys()),
            'outcome': _OUTCOMES})
_LB_LATENCY = metrics_lib.histogram(
    'skytpu_lb_request_seconds',
    'End-to-end proxy latency (body read to upstream EOF).',
    labels={'policy': tuple(registry.LB_POLICY_REGISTRY.keys())})
_RETRY_REASONS = ('connect_error', 'disconnected', 'timeout',
                  'breaker_open')
_LB_RETRIES = metrics_lib.counter(
    'skytpu_lb_retries_total',
    'Upstream attempts retried on another replica, by the failure '
    'reason that caused the retry (idempotent-safe attempts only: no '
    'response bytes had reached the client).',
    labels={'reason': _RETRY_REASONS})
_LB_CLASS_REQUESTS = metrics_lib.counter(
    'skytpu_lb_class_requests_total',
    'Requests entering the LB by declared request class '
    '(X-Skytpu-Class, clamped through the closed class registry '
    'before it can reach any label set) — the offered-load side the '
    'loadgen scorecard reconciles against engine-side goodput.',
    labels={'cls': request_class.CLASSES})
# Disaggregated two-stage routing (serve/disagg; docs/serving.md):
# per-stage outcomes of the prefill→handoff→decode pipeline. 'retry'
# counts attempts reroute/re-run; 'fallback' counts eligible requests
# served single-stage because a pool was empty.
_LB_HANDOFF = metrics_lib.counter(
    'skytpu_lb_handoff_total',
    'Two-stage disaggregated requests by pipeline stage and outcome.',
    labels={'stage': ('prefill', 'decode'),
            'outcome': ('ok', 'retry', 'error', 'fallback')})
_LB_HANDOFF_SECONDS = metrics_lib.histogram(
    'skytpu_lb_handoff_seconds',
    'Stage-1 wall time of the disagg pipeline: pick → prefill replica '
    'prefills + ships pages → handoff ack (the end-to-end handoff '
    'overhead a monolithic pool does not pay).')
_BREAKER_STATES = ('closed', 'open', 'half_open')
_LB_BREAKER_STATE = metrics_lib.gauge(
    'skytpu_lb_breaker_state',
    'Replicas currently in each circuit-breaker state. Per-replica '
    'detail rides the journal lb_breaker events (replica URLs are '
    'unbounded; metric label sets must stay declared and finite).',
    labels={'state': _BREAKER_STATES})

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'upgrade',
                'proxy-authenticate', 'proxy-authorization', 'te',
                'trailers', 'host', 'content-length'}


class _ClientAborted(Exception):
    """Internal sentinel: the CLIENT side of the proxy (the downstream
    response transport) failed — prepare/write raised. Distinct from
    upstream failures by construction so a user closing their laptop
    can never count against a healthy replica's circuit breaker."""


async def _downstream(coro):
    """Await a client-side (downstream) response operation, converting
    its connection failures into the _ClientAborted sentinel.
    ConnectionError ⊂ OSError covers the transport-reset shapes aiohttp
    raises from prepare/write on a dead client connection."""
    try:
        return await coro
    except OSError as e:
        raise _ClientAborted() from e


# Affinity keys truncate to a SHORT FIXED head: two prompts sharing at
# least this much prefix must produce IDENTICAL keys, or the chat
# pattern (a history that grows every turn) would never co-locate —
# turn 1's 100-token prompt and turn 2's 300-token prompt both key on
# their first 64 units. Matches the engine's PREFIX_MIN_TOKENS.
_AFFINITY_HEAD = 64


def _affinity_key(request: web.Request, body: bytes) -> Optional[str]:
    """Routing hint for affinity-aware policies: the fixed-length head
    of the request's prompt (str prompt / token ids / first chat
    message), so requests sharing a prefix — the chat pattern — land on
    the replica whose prefix KV cache already holds it. None for
    anything that isn't a generation POST (policies then fall back to
    load)."""
    if request.method != 'POST' or not body:
        return None
    try:
        import json
        payload = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    prompt = payload.get('prompt')
    if isinstance(prompt, str):
        return prompt[:_AFFINITY_HEAD]
    tokens = payload.get('tokens') or (
        prompt if isinstance(prompt, list) else None)
    if isinstance(tokens, list):
        return ','.join(str(t) for t in tokens[:_AFFINITY_HEAD])
    messages = payload.get('messages')
    if (isinstance(messages, list) and messages and
            isinstance(messages[0], dict)):
        first = messages[0]
        return (f"{first.get('role', '')}:"
                f"{str(first.get('content', ''))[:_AFFINITY_HEAD]}")
    return None


class CircuitBreaker:
    """One replica's breaker. All methods run on the LB's event loop —
    no locking. ``routable`` is a PURE check; ``begin_attempt`` is the
    mutating half that consumes the half-open probe token, so scanning
    candidates never burns probes."""

    def __init__(self, threshold: int, cooldown: float):
        self.threshold = threshold
        self.cooldown = cooldown
        self.state = 'closed'
        self.consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    def routable(self, now: float) -> bool:
        if self.state == 'closed':
            return True
        if self.state == 'open':
            return now - self._opened_at >= self.cooldown
        return not self._probing            # half_open: one probe only

    def begin_attempt(self, now: float) -> Optional[Tuple[str, str]]:
        """Mark an attempt started; returns the (old, new) transition
        when the open→half_open edge fires."""
        edge = None
        if self.state == 'open' and \
                now - self._opened_at >= self.cooldown:
            edge = ('open', 'half_open')
            self.state = 'half_open'
            self._probing = False
        if self.state == 'half_open':
            self._probing = True
        return edge

    def abort_attempt(self) -> None:
        """Release the half-open probe token without judging the
        replica (client abort / handler cancellation mid-attempt):
        half-open allows exactly ONE probe, so leaking the token here
        would wedge the breaker half-open — and the replica out of
        routing — forever."""
        self._probing = False

    def record_success(self) -> Optional[Tuple[str, str]]:
        old = self.state
        self.state = 'closed'
        self.consecutive = 0
        self._probing = False
        return (old, 'closed') if old != 'closed' else None

    def record_failure(self, now: float) -> Optional[Tuple[str, str]]:
        old = self.state
        self.consecutive += 1
        self._probing = False
        if old == 'half_open' or (old == 'closed' and
                                  self.consecutive >= self.threshold):
            self.state = 'open'
            self._opened_at = now
            return (old, 'open')
        if old == 'open':
            # A failure while open (raced in before the breaker saw the
            # last one) re-arms the cooldown.
            self._opened_at = now
        return None


class LoadBalancer:

    def __init__(self, policy_name: str,
                 autoscaler: Optional['autoscalers.Autoscaler'] = None,
                 service_name: Optional[str] = None):
        policy_cls = registry.LB_POLICY_REGISTRY.type_from_str(policy_name)
        self.policy: lb_policies.LoadBalancingPolicy = policy_cls()
        # Canonical registry key (aliases resolved) — the declared,
        # bounded metric label value.
        self.policy_name = next(
            k for k in registry.LB_POLICY_REGISTRY.keys()
            if registry.LB_POLICY_REGISTRY.type_from_str(k) is policy_cls)
        # When set, /-/lb/events is scoped to THIS service's entities:
        # the LB port faces end users and must not leak the rest of
        # the shared control-plane journal.
        self.service_name = service_name
        self.autoscaler = autoscaler
        # Span sampling rate in [0, 1] (default 1 = trace everything).
        # Every traced proxied request persists ~7 span rows (lb.*
        # here, engine.* on the replica); at high rps that churns
        # gc_spans' row cap — this knob sheds that write load.
        self._span_sample = min(1.0, max(0.0, knobs.get_float(
            'SKYTPU_LB_SPAN_SAMPLE')))
        self._session: Optional[aiohttp.ClientSession] = None
        # Upstream timeout shape (docs/ROBUSTNESS.md): connect bounds
        # dead-replica detection, sock_read bounds the gap BETWEEN
        # bytes (slow-loris / stalled upstream), and total stays None
        # so long legitimate streams are never killed mid-flight.
        self._connect_timeout = knobs.get_float('SKYTPU_LB_CONNECT_TIMEOUT')
        self._read_timeout = knobs.get_float('SKYTPU_LB_READ_TIMEOUT')
        # Bounded retry of idempotent-safe attempts + per-replica
        # breakers.
        self._retries = max(0, knobs.get_int('SKYTPU_LB_RETRIES'))
        self._retry_backoff = max(0.0, knobs.get_float(
            'SKYTPU_LB_RETRY_BACKOFF'))
        self._breaker_threshold = max(1, knobs.get_int(
            'SKYTPU_LB_BREAKER_THRESHOLD'))
        self._breaker_cooldown = max(0.0, knobs.get_float(
            'SKYTPU_LB_BREAKER_COOLDOWN'))
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._ready: List[str] = []
        self._fallback_rr = 0
        # Fleet telemetry (observe/scrape.py + slo.py), attached by
        # the controller when it owns a scrape loop; None leaves the
        # /-/fleet/ endpoints answering 503 (a standalone LB has no
        # scraper).
        self._scraper = None
        self._slo_engine = None
        self._cost_meter = None
        # Disaggregated pools (serve/disagg): set by the controller
        # when the service declares prefill/decode pools. None = every
        # request routes single-stage over the _ready set.
        self._pools: Optional[lb_policies.PoolRouter] = None

    def attach_fleet(self, scraper, slo_engine=None,
                     cost_meter=None) -> None:
        """Give the /-/fleet/ endpoints their data sources (the
        controller's Scraper, SLOEngine and CostMeter)."""
        self._scraper = scraper
        self._slo_engine = slo_engine
        self._cost_meter = cost_meter

    def set_replica_saturation(self,
                               queue_depths: Dict[str, float]) -> None:
        """Controller scrape-round hook → the policy's tie-breaker."""
        self.policy.set_replica_saturation(queue_depths)
        if self._pools is not None:
            self._pools.set_saturation(queue_depths)

    def set_pool_replicas(self, prefill_urls: List[str],
                          decode_urls: List[str]) -> None:
        """Disaggregated pools (controller reconcile thread): eligible
        generation traffic routes two-stage — class/length-aware pick
        over the prefill pool, session-ring pick over the decode pool
        — while everything else proxies single-stage over the _ready
        set (the controller points that at the decode pool, whose
        replicas are full engines). Reference swaps only, like
        set_ready_replicas."""
        if self._pools is None:
            self._pools = lb_policies.PoolRouter()
        self._pools.set_pools(prefill_urls, decode_urls)

    def set_ready_replicas(self, urls: List[str]) -> None:
        """Called from the controller's reconcile THREAD: only swaps
        references. The breaker dict is event-loop-owned — entries are
        created lazily by _breaker() and pruned by
        _refresh_breaker_gauge(), both of which only run on the LB's
        loop, so no cross-thread dict mutation races a loop-side
        iteration."""
        self._ready = list(urls)
        self.policy.set_ready_replicas(urls)

    # ------------------------------------------------------- breakers
    def _breaker(self, url: str) -> CircuitBreaker:
        breaker = self._breakers.get(url)
        if breaker is None:
            breaker = CircuitBreaker(self._breaker_threshold,
                                     self._breaker_cooldown)
            self._breakers[url] = breaker
        return breaker

    def _refresh_breaker_gauge(self) -> None:
        """Event-loop only. Also the pruning point for breakers whose
        replicas left the ready set (drained, replaced, scaled down) —
        pruning here instead of in set_ready_replicas keeps every
        mutation of the dict on the loop."""
        ready = set(self._ready)
        for url in [u for u in self._breakers if u not in ready]:
            del self._breakers[url]
        counts = {s: 0 for s in _BREAKER_STATES}
        for breaker in self._breakers.values():
            counts[breaker.state] += 1
        # Ready replicas that never needed a breaker entry are closed.
        counts['closed'] += len(ready - set(self._breakers))
        for state, n in counts.items():
            _LB_BREAKER_STATE.set(n, state=state)

    async def _breaker_edge(self, url: str,
                            edge: Optional[Tuple[str, str]]) -> None:
        """Publish a breaker transition: journal event (the per-replica
        record the bounded-label gauge cannot carry) + gauge refresh.
        The journal write opens a sqlite connection (with a retried
        WAL pragma that can sleep) — it runs in a worker thread so a
        contended journal never stalls the proxy loop; the gauge
        refresh stays on the loop (it mutates loop-only state)."""
        if edge is None:
            return
        old, new = edge
        logger.warning(f'Breaker for {url}: {old} -> {new}.')
        await asyncio.to_thread(
            journal_lib.record_event,
            'lb_breaker', entity=self.service_name,
            reason=f'{old}->{new}', data={'replica': url})
        self._refresh_breaker_gauge()

    async def _record_upstream_failure(self, url: str,
                                       now: float) -> None:
        await self._breaker_edge(url,
                                 self._breaker(url).record_failure(now))

    async def _record_upstream_success(self, url: str) -> None:
        await self._breaker_edge(url,
                                 self._breaker(url).record_success())

    def _pick(self, key: Optional[str], excluded: set,
              now: float) -> Optional[str]:
        """The policy's choice when it is routable (breaker allows, not
        already tried this request); otherwise any routable replica by
        rotation. None when nothing is routable right now."""
        choice = self.policy.select(key)
        if (choice is not None and choice not in excluded and
                self._breaker(choice).routable(now)):
            return choice
        candidates = [u for u in self._ready
                      if u not in excluded and
                      self._breaker(u).routable(now)]
        if not candidates:
            return None
        self._fallback_rr = (self._fallback_rr + 1) % len(candidates)
        return candidates[self._fallback_rr]

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        if self.autoscaler is not None:
            self.autoscaler.record_request()
        # Serving-plane trace ingress: honor a well-formed client
        # X-Skytpu-Trace-Id (one chat turn can then join its LB hop,
        # engine spans and any control-plane events under one id) or
        # mint one. The trace + this request's span id are FORWARDED
        # to the replica, so engine-side spans parent under lb.upstream
        # and /v1/traces shows lb → engine.queue → prefill → decode.
        offered = request.headers.get('X-Skytpu-Trace-Id', '')
        client_traced = trace_lib.is_valid_trace_id(offered)
        tid = offered if client_traced else trace_lib.new_trace_id()
        offered_parent = request.headers.get('X-Skytpu-Parent-Span', '')
        parent = (offered_parent
                  if trace_lib.is_valid_trace_id(offered_parent)
                  else None)
        # Sampling: a client-offered trace id is ALWAYS recorded
        # (explicit debugging intent); organic traffic persists spans
        # at SKYTPU_LB_SPAN_SAMPLE. A sampled-out request runs under
        # spans.suppress() — same code path, nothing persisted, no
        # carriers exported (so the replica's engine records nothing
        # either); metrics/histograms still move.
        if (client_traced or self._span_sample >= 1.0 or
                random.random() < self._span_sample):
            with trace_lib.trace_context(tid):
                with spans_lib.span('lb.request', parent_id=parent,
                                    entity=self.service_name,
                                    attrs={'path': request.rel_url.path,
                                           'policy': self.policy_name}
                                    ) as root:
                    return await self._proxy_traced(request, root)
        with spans_lib.suppress():
            with trace_lib.trace_context(tid):
                with spans_lib.span('lb.request', parent_id=parent,
                                    entity=self.service_name,
                                    attrs={'path': request.rel_url.path,
                                           'policy': self.policy_name}
                                    ) as root:
                    return await self._proxy_traced(request, root)

    @staticmethod
    def _classify(err: BaseException) -> str:
        """Failure reason for retry accounting — one of _RETRY_REASONS
        (breaker_open is assigned at the pick, not here)."""
        if isinstance(err, failpoints_lib.FailpointError):
            return ('disconnected' if 'read' in err.failpoint
                    else 'connect_error')
        if isinstance(err, (aiohttp.ServerTimeoutError,
                            asyncio.TimeoutError)):
            return 'timeout'
        if isinstance(err, aiohttp.ClientConnectorError):
            return 'connect_error'
        if isinstance(err, (aiohttp.ServerDisconnectedError,
                            aiohttp.ClientPayloadError)):
            return 'disconnected'
        if isinstance(err, OSError):
            return 'connect_error'
        return 'disconnected'

    async def _proxy_traced(self, request: web.Request,
                            root: 'spans_lib.Span') -> web.StreamResponse:
        if not self.policy.has_replicas():
            # Reject BEFORE buffering the body: a scaled-to-zero service
            # must not hold dead multi-MB uploads in RAM.
            _LB_REQUESTS.inc(policy=self.policy_name,
                             outcome='no_replica')
            root.set_attr('outcome', 'no_replica')
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        t0 = time.monotonic()
        body = await request.read()
        # Request class: clamp the client-supplied X-Skytpu-Class
        # through the closed registry HERE, at the trust boundary —
        # an unknown value becomes 'other', never a new label value
        # (the X-Skytpu-Trace-Id hardening precedent). The clamped
        # value is counted as offered load and re-stamped on the
        # upstream call below (the raw header is stripped with the
        # rest of x-skytpu-*).
        cls = request_class.from_headers(request.headers)
        _LB_CLASS_REQUESTS.inc(cls=cls)
        root.set_attr('cls', cls)
        with spans_lib.span('lb.pick', entity=self.service_name):
            # Key extraction (a JSON parse) only when the policy uses
            # it; the replica actually chosen is recorded per attempt
            # on the lb.upstream span (retries may reroute). An
            # explicit session id (X-Skytpu-Session) beats the
            # prompt-head heuristic: the consistent-hash ring then
            # pins the whole session even when its prompts diverge
            # past the affinity head.
            key = None
            if self.policy.wants_affinity_key:
                session = request.headers.get('X-Skytpu-Session',
                                              '').strip()
                key = (session[:128] if session
                       else _affinity_key(request, body))
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=None, connect=self._connect_timeout,
                    sock_connect=self._connect_timeout,
                    sock_read=self._read_timeout))
        # Strip any client-supplied X-Skytpu-* before stamping our own:
        # forwarding them would DUPLICATE the headers (dict stamping
        # can't replace a differently-cased client key), and the
        # engine's multidict .get() returns the client's value first —
        # letting a client spoof the entity (planting spans inside
        # another service's scoped /-/lb/trace view) or detach engine
        # spans from the LB's trace.
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS
                   and not k.lower().startswith('x-skytpu-')}
        # Stamp the CLAMPED class (the raw client header was stripped
        # above): the engine labels its per-class TTFT/TPOT/goodput
        # off this value, and normalizes again on arrival.
        headers[request_class.HEADER] = cls
        # Disaggregated two-stage routing: eligible generation POSTs
        # (single prompt, long enough — PoolRouter.plan is the
        # class/length-aware gate) run prefill-pool-first with a KV
        # page handoff to the ring-pinned decode replica. Everything
        # else falls through to the single-stage proxy over _ready
        # (the decode pool — its replicas are full engines).
        plan = None
        if self._pools is not None and self._pools.has_pools() and \
                self._pools.eligible(request.method,
                                     request.rel_url.path):
            import json
            try:
                payload = json.loads(body) if body else None
            except (ValueError, UnicodeDecodeError):
                payload = None
            plan = self._pools.plan(request.method,
                                    request.rel_url.path, payload, cls)
        try:
            if plan is not None:
                return await self._disagg_attempts(request, root, body,
                                                   headers, plan)
            return await self._proxy_attempts(request, root, key,
                                              body, headers)
        finally:
            _LB_LATENCY.observe(time.monotonic() - t0,
                                policy=self.policy_name)

    async def _proxy_attempts(self, request: web.Request,
                              root: 'spans_lib.Span',
                              key: Optional[str], body: bytes,
                              headers: Dict[str, str]
                              ) -> web.StreamResponse:
        """The bounded attempt loop: pick (breaker-aware) → proxy →
        on an idempotent-safe failure (no response bytes sent to the
        client yet) reroute with backoff. A failure after streaming
        started truncates the stream — the only honest option left."""
        tried: set = set()
        last_err: Optional[BaseException] = None
        attempts = self._retries + 1
        for attempt in range(attempts):
            now = time.monotonic()
            target = self._pick(key, tried, now)
            if target is None and tried:
                # Every untried replica is breaker-blocked; widen to
                # the tried set before giving up (a flapping replica
                # may still beat a 502).
                tried = set()
                target = self._pick(key, tried, now)
            if target is None:
                if attempt + 1 < attempts:
                    # Nothing routable RIGHT NOW (breakers open): wait
                    # out the backoff — a cooldown may elapse or the
                    # reconcile loop may deliver a fresh replica.
                    _LB_RETRIES.inc(reason='breaker_open')
                    await asyncio.sleep(
                        self._retry_backoff * (2 ** attempt))
                    continue
                _LB_REQUESTS.inc(policy=self.policy_name,
                                 outcome='breaker_open')
                root.set_attr('outcome', 'breaker_open')
                return web.json_response(
                    {'error': 'all replicas unavailable (circuit '
                              'breakers open); retry shortly',
                     'retriable': True}, status=503,
                    headers={'Retry-After': '1'})
            tried.add(target)
            breaker = self._breaker(target)
            await self._breaker_edge(target, breaker.begin_attempt(now))
            self.policy.request_started(target)
            url = target.rstrip('/') + request.rel_url.path_qs
            resp: Optional[web.StreamResponse] = None
            # Every exit of the try below must disposition the breaker
            # (success, failure, or abort) — `judged` tracks it, and
            # the finally releases the half-open probe token for ANY
            # unanticipated exception type, or the breaker would wedge
            # half-open and the replica never route again.
            judged = False
            try:
                with spans_lib.span('lb.upstream',
                                    entity=self.service_name,
                                    attrs={'replica': target,
                                           'attempt': attempt}) as up:
                    if not spans_lib.suppressed():
                        headers['X-Skytpu-Trace-Id'] = up.trace_id or ''
                        headers['X-Skytpu-Parent-Span'] = up.span_id
                        # The engine stamps this entity on its request
                        # spans so they fall inside /-/lb/trace/<id>'s
                        # entity scope.
                        if self.service_name:
                            headers['X-Skytpu-Entity'] = self.service_name
                    if failpoints_lib.ACTIVE:
                        await failpoints_lib.afire('lb.upstream_connect')
                    async with self._session.request(
                            request.method, url, headers=headers,
                            data=body) as upstream:
                        up.set_attr('status', upstream.status)
                        resp = web.StreamResponse(status=upstream.status)
                        for k, v in upstream.headers.items():
                            if k.lower() not in _HOP_HEADERS:
                                resp.headers[k] = v
                        await _downstream(resp.prepare(request))
                        # Stream the body through: LLM replies are long
                        # and incremental (SSE/chunked) — never buffer
                        # them whole. Upstream reads and client writes
                        # are wrapped SEPARATELY: a failure reading the
                        # replica is an upstream fault (breaker,
                        # retry/truncate); a failure writing to the
                        # client is a client abort (neither).
                        while True:
                            if failpoints_lib.ACTIVE:
                                await failpoints_lib.afire('lb.upstream_read')
                            chunk = await upstream.content.readany()
                            if not chunk:
                                break
                            await _downstream(resp.write(chunk))
                        await _downstream(resp.write_eof())
                        await self._record_upstream_success(target)
                        judged = True
                        _LB_REQUESTS.inc(policy=self.policy_name,
                                         outcome='proxied')
                        root.set_attr('outcome', 'proxied')
                        return resp
            except asyncio.CancelledError:
                # aiohttp CANCELS the handler task when the client
                # drops the connection — same disposition as
                # _ClientAborted below (count it, never blame the
                # replica), but cancellation must RE-RAISE. The probe
                # token releases in the finally (judged stays False).
                _LB_REQUESTS.inc(policy=self.policy_name,
                                 outcome='client_abort')
                root.set_attr('outcome', 'client_abort')
                raise
            except _ClientAborted as e:
                # The CLIENT went away mid-proxy: nothing to retry,
                # nobody left to answer — and the replica did nothing
                # wrong, so its breaker must not move (the finally
                # releases the probe token). The upstream read (still
                # streaming a reply nobody wants) is torn down by
                # leaving the `async with` block.
                logger.debug(f'Client aborted while proxying to '
                             f'{target}: {e.__cause__}')
                _LB_REQUESTS.inc(policy=self.policy_name,
                                 outcome='client_abort')
                root.set_attr('outcome', 'client_abort')
                if resp is not None and resp.prepared:
                    resp.force_close()
                    return resp
                return web.Response(status=499)   # nobody will see it
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    failpoints_lib.FailpointError) as e:
                last_err = e
                await self._record_upstream_failure(target, time.monotonic())
                judged = True
                if resp is not None and resp.prepared:
                    # Response bytes already reached the client: not
                    # idempotent-safe — truncate the stream instead of
                    # silently retrying into a duplicated reply. The
                    # transport is closed DIRECTLY: merely returning
                    # the response would let aiohttp write the chunked
                    # terminator, making the truncated body look like
                    # a well-formed complete reply.
                    logger.warning(f'Upstream {target} failed '
                                   f'mid-stream: {e}')
                    resp.force_close()
                    if request.transport is not None:
                        request.transport.close()
                    _LB_REQUESTS.inc(policy=self.policy_name,
                                     outcome='upstream_error')
                    root.set_attr('outcome', 'upstream_error')
                    return resp
                reason = self._classify(e)
                if attempt + 1 < attempts:
                    logger.info(f'Upstream {target} failed before '
                                f'response start ({reason}: {e}); '
                                f'retrying on another replica.')
                    _LB_RETRIES.inc(reason=reason)
                    await asyncio.sleep(
                        self._retry_backoff * (2 ** attempt))
                    continue
            finally:
                if not judged:
                    # Any exit that neither blamed nor credited the
                    # replica (client abort, cancellation, an
                    # unanticipated exception type): release the
                    # half-open probe token so the breaker can't wedge.
                    breaker.abort_attempt()
                self.policy.request_finished(target)
        _LB_REQUESTS.inc(policy=self.policy_name,
                         outcome='upstream_error')
        root.set_attr('outcome', 'upstream_error')
        return web.json_response(
            {'error': f'upstream failed after {attempts} attempt(s): '
                      f'{last_err}',
             'retriable': True}, status=502)

    # ------------------------------------------------------------------
    # Disaggregated two-stage pipeline (serve/disagg; docs/serving.md)
    # ------------------------------------------------------------------
    async def _disagg_attempts(self, request: web.Request,
                               root: 'spans_lib.Span', body: bytes,
                               headers: Dict[str, str],
                               plan: Dict[str, typing.Any]
                               ) -> web.StreamResponse:
        """Bounded retry loop over the whole prefill→handoff→decode
        pipeline. Stage-1 failures (prefill replica dead, handoff.send
        armed, mid-handoff kill) reroute to ANOTHER prefill replica —
        nothing has streamed to the client, so the retry is
        idempotent-safe. Stage-2 pre-header failures (handoff_missing:
        the pages never arrived or expired; decode 5xx) re-run the
        WHOLE pipeline — the handoff is consumed-at-most-once, so a
        fresh prefill mints a fresh one. A failure after response
        bytes reached the client truncates honestly, exactly like the
        single-stage proxy. Exhausted attempts surface a structured
        retriable 502."""
        root.set_attr('disagg', True)
        session = request.headers.get('X-Skytpu-Session', '').strip()
        key = session[:128] if session else _affinity_key(request, body)
        attempts = self._retries + 1
        tried_prefill: set = set()
        tried_decode: set = set()
        last_err = 'no pool replica available'
        for attempt in range(attempts):
            prefill_url = self._pools.pick_prefill(tried_prefill)
            if prefill_url is None and tried_prefill:
                # Every prefill replica already failed this request:
                # widen rather than 502 while one may have recovered.
                tried_prefill = set()
                prefill_url = self._pools.pick_prefill()
            decode_url = self._pools.pick_decode(key, tried_decode)
            if decode_url is None and tried_decode:
                tried_decode = set()
                decode_url = self._pools.pick_decode(key)
            if prefill_url is None or decode_url is None:
                break
            self._pools.request_started(prefill_url, decode_url)
            try:
                kind, value = await self._disagg_one(
                    request, root, body, headers, plan, prefill_url,
                    decode_url, attempt)
            finally:
                self._pools.request_finished(prefill_url, decode_url)
            if kind == 'response':
                return value
            last_err = value
            if kind == 'stage1_retry':
                tried_prefill.add(prefill_url)
                _LB_HANDOFF.inc(stage='prefill', outcome='retry')
            else:
                # Step the pipeline re-run off this decode replica
                # too: the ring pick is deterministic, so a dead
                # replica would otherwise be re-picked every attempt.
                # (handoff_missing also lands here — moving one
                # request off its session home is harmless; the
                # pages ship fresh wherever the retry prefills.)
                tried_decode.add(decode_url)
                _LB_HANDOFF.inc(stage='decode', outcome='retry')
            if attempt + 1 < attempts:
                await asyncio.sleep(self._retry_backoff * (2 ** attempt))
        _LB_REQUESTS.inc(policy=self.policy_name,
                         outcome='upstream_error')
        root.set_attr('outcome', 'upstream_error')
        return web.json_response(
            {'error': f'disaggregated pipeline failed after '
                      f'{attempts} attempt(s): {last_err}',
             'retriable': True}, status=502,
            headers={'Retry-After': '1'})

    async def _disagg_one(self, request: web.Request,
                          root: 'spans_lib.Span', body: bytes,
                          headers: Dict[str, str],
                          plan: Dict[str, typing.Any],
                          prefill_url: str, decode_url: str,
                          attempt: int) -> tuple:
        """One pipeline attempt. Returns ('response', resp) when a
        final answer (success or non-retriable refusal) exists,
        ('stage1_retry', why) to reroute prefill, or
        ('pipeline_retry', why) to re-run both stages."""
        from skypilot_tpu.serve.disagg import handoff as handoff_lib
        orig = plan['path']
        h_host, h_port = handoff_lib.handoff_addr_for_url(decode_url)
        s1_headers = dict(headers)
        s1_headers['X-Skytpu-Handoff-Target'] = f'{h_host}:{h_port}'
        t0 = time.monotonic()
        with spans_lib.span('lb.prefill', entity=self.service_name,
                            attrs={'replica': prefill_url,
                                   'attempt': attempt}):
            try:
                if failpoints_lib.ACTIVE:
                    await failpoints_lib.afire('lb.upstream_connect')
                async with self._session.post(
                        prefill_url.rstrip('/') +
                        f'/disagg/prefill?orig={orig}',
                        data=body, headers=s1_headers) as r1:
                    status1 = r1.status
                    try:
                        doc = await r1.json(content_type=None)
                    except ValueError:
                        doc = None
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    failpoints_lib.FailpointError) as e:
                return ('stage1_retry',
                        f'prefill {prefill_url}: '
                        f'{type(e).__name__}: {e}')
        _LB_HANDOFF_SECONDS.observe(time.monotonic() - t0)
        if status1 == 200 and isinstance(doc, dict) and 'done' in doc:
            # Completed at prefill admission (stop-id first token /
            # max_new == 1): no decode stage.
            _LB_HANDOFF.inc(stage='prefill', outcome='ok')
            _LB_REQUESTS.inc(policy=self.policy_name,
                             outcome='proxied')
            root.set_attr('outcome', 'proxied')
            return ('response', await self._disagg_done_response(
                request, plan, doc['done']))
        if status1 != 200 or not isinstance(doc, dict) or \
                'handoff' not in doc:
            if status1 in (429, 502, 503):
                return ('stage1_retry',
                        f'prefill {prefill_url} answered {status1}')
            if status1 == 200:
                # 200 with a body that is neither 'done' nor
                # 'handoff': a broken replica (or intermediary) —
                # never hand the client a 200-wrapped error doc.
                return ('stage1_retry',
                        f'prefill {prefill_url} answered 200 with '
                        f'an unrecognizable body')
            # Non-retriable refusal (bad request, spec mismatch):
            # the client must see it.
            _LB_HANDOFF.inc(stage='prefill', outcome='error')
            root.set_attr('outcome', 'upstream_error')
            return ('response', web.json_response(
                doc if isinstance(doc, dict) else
                {'error': f'prefill replica answered {status1}'},
                status=status1))
        _LB_HANDOFF.inc(stage='prefill', outcome='ok')
        payload = {'handoff_id': doc['handoff']['id'],
                   'stream': plan['stream']}
        resp: Optional[web.StreamResponse] = None
        with spans_lib.span('lb.decode', entity=self.service_name,
                            attrs={'replica': decode_url,
                                   'attempt': attempt}):
            try:
                async with self._session.post(
                        decode_url.rstrip('/') +
                        f'/disagg/continue?orig={orig}',
                        json=payload, headers=headers) as upstream:
                    if upstream.status != 200:
                        try:
                            doc2 = await upstream.json(content_type=None)
                        except ValueError:
                            doc2 = {'error': f'decode replica answered '
                                             f'{upstream.status}'}
                        if upstream.status in (429, 502, 503):
                            return ('pipeline_retry',
                                    f'decode {decode_url} answered '
                                    f'{upstream.status}')
                        _LB_HANDOFF.inc(stage='decode', outcome='error')
                        root.set_attr('outcome', 'upstream_error')
                        return ('response', web.json_response(
                            doc2, status=upstream.status))
                    resp = web.StreamResponse(status=200)
                    for k, v in upstream.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            resp.headers[k] = v
                    await _downstream(resp.prepare(request))
                    while True:
                        if failpoints_lib.ACTIVE:
                            await failpoints_lib.afire('lb.upstream_read')
                        chunk = await upstream.content.readany()
                        if not chunk:
                            break
                        await _downstream(resp.write(chunk))
                    await _downstream(resp.write_eof())
                    _LB_HANDOFF.inc(stage='decode', outcome='ok')
                    _LB_REQUESTS.inc(policy=self.policy_name,
                                     outcome='proxied')
                    root.set_attr('outcome', 'proxied')
                    return ('response', resp)
            except _ClientAborted:
                _LB_REQUESTS.inc(policy=self.policy_name,
                                 outcome='client_abort')
                root.set_attr('outcome', 'client_abort')
                if resp is not None and resp.prepared:
                    resp.force_close()
                    return ('response', resp)
                return ('response', web.Response(status=499))
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    failpoints_lib.FailpointError) as e:
                if resp is not None and resp.prepared:
                    # Mid-stream: truncate honestly (never a silent
                    # replay — tokens already reached the client).
                    logger.warning(f'Decode {decode_url} failed '
                                   f'mid-stream: {e}')
                    resp.force_close()
                    if request.transport is not None:
                        request.transport.close()
                    _LB_HANDOFF.inc(stage='decode', outcome='error')
                    _LB_REQUESTS.inc(policy=self.policy_name,
                                     outcome='upstream_error')
                    root.set_attr('outcome', 'upstream_error')
                    return ('response', resp)
                return ('pipeline_retry',
                        f'decode {decode_url}: '
                        f'{type(e).__name__}: {e}')

    async def _disagg_done_response(self, request: web.Request,
                                    plan: Dict[str, typing.Any],
                                    done_doc: Dict[str, typing.Any]
                                    ) -> web.StreamResponse:
        """Render a completed-at-prefill result. Non-stream: the doc
        IS the original endpoint's response body. Stream: fabricate
        the one-chunk SSE the client expects (first token == last
        token)."""
        if not plan['stream']:
            return web.json_response(done_doc)
        import json
        resp = web.StreamResponse()
        resp.headers['Content-Type'] = 'text/event-stream'
        resp.headers['Cache-Control'] = 'no-cache'
        await _downstream(resp.prepare(request))
        chunk = {k: done_doc.get(k)
                 for k in ('id', 'object', 'created', 'model')}
        chunk['choices'] = done_doc.get('choices', [])
        await _downstream(resp.write(
            b'data: ' + json.dumps(chunk).encode() + b'\n\n'))
        await _downstream(resp.write(b'data: [DONE]\n\n'))
        await _downstream(resp.write_eof())
        return resp

    async def _health(self, request: web.Request) -> web.Response:
        del request
        ready = len(self.policy._replicas)  # pylint: disable=protected-access
        return web.json_response({'ready_replicas': ready})

    async def _metrics(self, request: web.Request) -> web.Response:
        """This controller process's whole registry: LB counters and
        latency histograms, autoscaler gauges, replica-probe outcome
        counters — one scrape target per service."""
        del request
        self._refresh_breaker_gauge()
        return web.Response(text=metrics_lib.render(),
                            content_type='text/plain')

    async def _events(self, request: web.Request) -> web.Response:
        """Journal query, same filter surface as the API server's
        /v1/events — one shared parser (journal.filters_from_query) so
        the two endpoints cannot diverge. Scoped: the LB port faces
        end users, so with a bound service_name only THIS service's
        entities (the service row + its ``svc/<id>`` replicas) are
        visible, not the rest of the shared journal. The scan runs
        off-loop: this event loop is also carrying live proxied
        traffic."""
        try:
            kwargs = journal_lib.filters_from_query(request.query)
        except ValueError:
            return web.json_response(
                {'error': 'since/limit must be numbers'}, status=400)
        if self.service_name is not None:
            kwargs['entity_scope'] = self.service_name
        result = await asyncio.to_thread(journal_lib.query, **kwargs)
        return web.json_response({'events': result})

    async def _trace(self, request: web.Request) -> web.Response:
        """Span tree for one trace (``/-/lb/trace/<trace_id>``) —
        entity-SCOPED like /-/lb/events: the LB port faces end users,
        so with a bound service_name only spans stamped with this
        service's entities (the lb.request/pick/upstream hops this
        process recorded) are visible, not the rest of the shared
        spans table. Off-loop: the read flushes the write-behind queue
        and scans sqlite."""
        trace_id = request.match_info.get('trace_id', '')
        if not trace_lib.is_valid_trace_id(trace_id):
            return web.json_response(
                {'error': f'bad trace id {trace_id!r}'}, status=400)
        # A None service_name disables entity scoping entirely — only
        # legitimate for a standalone LB owning its whole journal DB.
        result = await asyncio.to_thread(
            spans_lib.tree, trace_id, self.service_name)
        return web.json_response(result)

    async def _fleet_metrics(self, request: web.Request) -> web.Response:
        """The merged fleet exposition document: every FRESH scraped
        replica's families, counters/gauges summed, histograms merged
        bucket-wise. 503 (retriable) without a scraper or while no
        replica has been scraped yet — an empty 200 would read as "a
        healthy fleet with zero traffic". Off-loop: the merge walks
        every shard's parsed families."""
        del request
        if self._scraper is None:
            return web.json_response(
                {'error': 'no fleet scraper attached'}, status=503)

        def _render() -> str:
            return promtext.render(self._scraper.fleet_families())

        try:
            text = await asyncio.to_thread(_render)
        except ValueError as e:
            # BucketMismatchError ⊂ ValueError: replicas disagree on a
            # histogram's bucket layout (mid rolling update) — a
            # structured refusal, not an unhandled 500. Per-replica
            # raw text stays scrapable on each replica directly.
            return web.json_response(
                {'error': f'fleet merge refused: {e}',
                 'retriable': True}, status=503,
                headers={'Retry-After': '30'})
        if not text:
            return web.json_response(
                {'error': 'no replica scraped yet', 'retriable': True},
                status=503, headers={'Retry-After': '5'})
        return web.Response(text=text, content_type='text/plain')

    def _class_table(self) -> Dict[str, Dict[str, object]]:
        """Per-class scorecard columns from the merged fleet families:
        goodput good/slow totals, goodput fraction, TTFT/TPOT p95 —
        every read is a tolerant .get, because a class with no traffic
        yet simply has no label set in the merged document (and the
        table must render, not KeyError). Burn columns join from the
        SLO engine when one is attached."""
        try:
            fams = self._scraper.fleet_families()
        except ValueError:
            # BucketMismatchError mid rolling update: no class table
            # this round rather than a 500 on the status endpoint.
            return {}
        counts: Dict[str, Dict[str, float]] = {}
        goodput = fams.get('skytpu_engine_goodput_total')
        if goodput is not None:
            for s in goodput.samples:
                labels = dict(s.labels)
                c, outcome = labels.get('cls'), labels.get('outcome')
                if c is None or outcome is None:
                    continue
                per = counts.setdefault(c, {})
                per[outcome] = per.get(outcome, 0.0) + s.value
        hists = {
            short: promtext.extract_histograms(fams, family)
            for family, short in
            (('skytpu_engine_class_ttft_seconds', 'ttft'),
             ('skytpu_engine_class_tpot_seconds', 'tpot'))}
        burns = (self._slo_engine.burn_summary()
                 if self._slo_engine is not None else {})
        out: Dict[str, Dict[str, object]] = {}
        for cls in request_class.CLASSES:
            per = counts.get(cls, {})
            good = per.get('good', 0.0)
            slow = per.get('slow', 0.0)
            row: Dict[str, object] = {'good': good, 'slow': slow}
            total = good + slow
            row['goodput'] = (round(good / total, 4) if total else None)
            for short, by_label in hists.items():
                hist = by_label.get((('cls', cls),))
                if hist is None:
                    continue
                v = promtext.histogram_quantile(hist, 0.95)
                if v == v:                        # not NaN
                    row[f'{short}_p95_ms'] = round(v * 1e3, 2)
            burn = burns.get(f'goodput_{cls}')
            if burn is not None:
                row.update({'state': burn.get('state'),
                            'burn_fast': burn.get('burn_fast'),
                            'burn_slow': burn.get('burn_slow')})
            out[cls] = row
        return out

    async def _fleet_status(self, request: web.Request) -> web.Response:
        """Per-replica scrape/saturation table + SLO states + the
        per-class goodput/burn scorecard columns — the ``observe
        fleet`` CLI's data source."""
        del request
        if self._scraper is None:
            return web.json_response(
                {'error': 'no fleet scraper attached'}, status=503)
        replicas = await asyncio.to_thread(self._scraper.status)
        doc = {'service': self.service_name, 'replicas': replicas}
        if self._slo_engine is not None:
            doc['slo'] = self._slo_engine.states()
        doc['classes'] = await asyncio.to_thread(self._class_table)
        return web.json_response(doc)

    async def _fleet_costs(self, request: web.Request) -> web.Response:
        """The cost meter's windowed summary (observe/costs.py):
        per-pool dollars, $/token joins, spot discount and budget
        states. The meter is constructed with this service's entity
        scope, so a shared observe DB never leaks another service's
        spend here — the same boundary /-/lb/events enforces."""
        del request
        if self._cost_meter is None:
            return web.json_response(
                {'error': 'no cost meter attached'}, status=503)
        doc = await asyncio.to_thread(self._cost_meter.summary)
        return web.json_response(doc)

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get('/-/lb/health', self._health)
        app.router.add_get('/-/lb/metrics', self._metrics)
        app.router.add_get('/-/lb/events', self._events)
        app.router.add_get('/-/lb/trace/{trace_id}', self._trace)
        app.router.add_get('/-/fleet/metrics', self._fleet_metrics)
        app.router.add_get('/-/fleet/status', self._fleet_status)
        app.router.add_get('/-/fleet/costs', self._fleet_costs)
        app.router.add_route('*', '/{tail:.*}', self._proxy)

        async def _cleanup(app_):
            del app_
            if self._session is not None:
                await self._session.close()

        app.on_cleanup.append(_cleanup)
        return app
