"""HTTP load balancer: reverse proxy with per-request replica selection.

Reference analog: sky/serve/load_balancer.py (FastAPI proxy). aiohttp here
(already the API server's stack). The LB runs inside the service controller
process (serve/controller.py) and is told the ready-replica set after every
reconcile pass; it feeds request timestamps to the autoscaler.

Control endpoints live under /-/lb/ (anything else is proxied verbatim):
  GET /-/lb/health → {ready_replicas: N}
"""
from __future__ import annotations

import asyncio
import typing
from typing import List, Optional

import aiohttp
from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import autoscalers

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'upgrade',
                'proxy-authenticate', 'proxy-authorization', 'te',
                'trailers', 'host', 'content-length'}


class LoadBalancer:

    def __init__(self, policy_name: str,
                 autoscaler: Optional['autoscalers.Autoscaler'] = None):
        self.policy: lb_policies.LoadBalancingPolicy = (
            registry.LB_POLICY_REGISTRY.type_from_str(policy_name)())
        self.autoscaler = autoscaler
        self._session: Optional[aiohttp.ClientSession] = None

    def set_ready_replicas(self, urls: List[str]) -> None:
        self.policy.set_ready_replicas(urls)

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        if self.autoscaler is not None:
            self.autoscaler.record_request()
        target = self.policy.select()
        if target is None:
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300))
        url = target.rstrip('/') + request.rel_url.path_qs
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        body = await request.read()
        self.policy.request_started(target)
        try:
            async with self._session.request(request.method, url,
                                             headers=headers,
                                             data=body) as upstream:
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        resp.headers[k] = v
                await resp.prepare(request)
                # Stream the body through: LLM replies are long and
                # incremental (SSE/chunked) — never buffer them whole.
                async for chunk in upstream.content.iter_chunked(16384):
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return web.json_response(
                {'error': f'upstream {target} failed: {e}'}, status=502)
        finally:
            self.policy.request_finished(target)

    async def _health(self, request: web.Request) -> web.Response:
        del request
        ready = len(self.policy._replicas)  # pylint: disable=protected-access
        return web.json_response({'ready_replicas': ready})

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get('/-/lb/health', self._health)
        app.router.add_route('*', '/{tail:.*}', self._proxy)

        async def _cleanup(app_):
            del app_
            if self._session is not None:
                await self._session.close()

        app.on_cleanup.append(_cleanup)
        return app
