"""HTTP load balancer: reverse proxy with per-request replica selection.

Reference analog: sky/serve/load_balancer.py (FastAPI proxy). aiohttp here
(already the API server's stack). The LB runs inside the service controller
process (serve/controller.py) and is told the ready-replica set after every
reconcile pass; it feeds request timestamps to the autoscaler.

Control endpoints live under /-/lb/ (anything else is proxied verbatim):
  GET /-/lb/health → {ready_replicas: N}
"""
from __future__ import annotations

import asyncio
import typing
from typing import List, Optional

import aiohttp
from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import autoscalers

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'upgrade',
                'proxy-authenticate', 'proxy-authorization', 'te',
                'trailers', 'host', 'content-length'}


# Affinity keys truncate to a SHORT FIXED head: two prompts sharing at
# least this much prefix must produce IDENTICAL keys, or the chat
# pattern (a history that grows every turn) would never co-locate —
# turn 1's 100-token prompt and turn 2's 300-token prompt both key on
# their first 64 units. Matches the engine's PREFIX_MIN_TOKENS.
_AFFINITY_HEAD = 64


def _affinity_key(request: web.Request, body: bytes) -> Optional[str]:
    """Routing hint for affinity-aware policies: the fixed-length head
    of the request's prompt (str prompt / token ids / first chat
    message), so requests sharing a prefix — the chat pattern — land on
    the replica whose prefix KV cache already holds it. None for
    anything that isn't a generation POST (policies then fall back to
    load)."""
    if request.method != 'POST' or not body:
        return None
    try:
        import json
        payload = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    prompt = payload.get('prompt')
    if isinstance(prompt, str):
        return prompt[:_AFFINITY_HEAD]
    tokens = payload.get('tokens') or (
        prompt if isinstance(prompt, list) else None)
    if isinstance(tokens, list):
        return ','.join(str(t) for t in tokens[:_AFFINITY_HEAD])
    messages = payload.get('messages')
    if (isinstance(messages, list) and messages and
            isinstance(messages[0], dict)):
        first = messages[0]
        return (f"{first.get('role', '')}:"
                f"{str(first.get('content', ''))[:_AFFINITY_HEAD]}")
    return None


class LoadBalancer:

    def __init__(self, policy_name: str,
                 autoscaler: Optional['autoscalers.Autoscaler'] = None):
        self.policy: lb_policies.LoadBalancingPolicy = (
            registry.LB_POLICY_REGISTRY.type_from_str(policy_name)())
        self.autoscaler = autoscaler
        self._session: Optional[aiohttp.ClientSession] = None

    def set_ready_replicas(self, urls: List[str]) -> None:
        self.policy.set_ready_replicas(urls)

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        if self.autoscaler is not None:
            self.autoscaler.record_request()
        if not self.policy.has_replicas():
            # Reject BEFORE buffering the body: a scaled-to-zero service
            # must not hold dead multi-MB uploads in RAM.
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        body = await request.read()
        # Key extraction (a JSON parse) only when the policy uses it.
        key = (_affinity_key(request, body)
               if self.policy.wants_affinity_key else None)
        target = self.policy.select(key)
        if target is None:
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300))
        url = target.rstrip('/') + request.rel_url.path_qs
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        self.policy.request_started(target)
        try:
            async with self._session.request(request.method, url,
                                             headers=headers,
                                             data=body) as upstream:
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        resp.headers[k] = v
                await resp.prepare(request)
                # Stream the body through: LLM replies are long and
                # incremental (SSE/chunked) — never buffer them whole.
                async for chunk in upstream.content.iter_chunked(16384):
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return web.json_response(
                {'error': f'upstream {target} failed: {e}'}, status=502)
        finally:
            self.policy.request_finished(target)

    async def _health(self, request: web.Request) -> web.Response:
        del request
        ready = len(self.policy._replicas)  # pylint: disable=protected-access
        return web.json_response({'ready_replicas': ready})

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get('/-/lb/health', self._health)
        app.router.add_route('*', '/{tail:.*}', self._proxy)

        async def _cleanup(app_):
            del app_
            if self._session is not None:
                await self._session.close()

        app.on_cleanup.append(_cleanup)
        return app
