"""HTTP load balancer: reverse proxy with per-request replica selection.

Reference analog: sky/serve/load_balancer.py (FastAPI proxy). aiohttp here
(already the API server's stack). The LB runs inside the service controller
process (serve/controller.py) and is told the ready-replica set after every
reconcile pass; it feeds request timestamps to the autoscaler.

Control endpoints live under /-/lb/ (anything else is proxied verbatim):
  GET /-/lb/health  → {ready_replicas: N}
  GET /-/lb/metrics → Prometheus exposition (per-policy request
                      counters + latency histograms, autoscaler gauges,
                      probe outcome counters — everything this
                      controller process registered)
  GET /-/lb/events  → the trace-correlated event journal (this
                      service's replica transitions included)
  GET /-/lb/trace/<trace_id>
                    → this service's span tree for one trace (the
                      lb.request → lb.pick / lb.upstream hops),
                      entity-scoped like /-/lb/events
"""
from __future__ import annotations

import asyncio
import os
import random
import time
import typing
from typing import List, Optional

import aiohttp
from aiohttp import web

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import autoscalers

logger = sky_logging.init_logger(__name__)

# Label bounds: policies come from the static registry (populated by
# the lb_policies import above), outcomes are this closed set.
_OUTCOMES = ('proxied', 'upstream_error', 'no_replica')
_LB_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Load-balanced requests by policy and outcome.',
    labels={'policy': tuple(registry.LB_POLICY_REGISTRY.keys()),
            'outcome': _OUTCOMES})
_LB_LATENCY = metrics_lib.histogram(
    'skytpu_lb_request_seconds',
    'End-to-end proxy latency (body read to upstream EOF).',
    labels={'policy': tuple(registry.LB_POLICY_REGISTRY.keys())})

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding', 'upgrade',
                'proxy-authenticate', 'proxy-authorization', 'te',
                'trailers', 'host', 'content-length'}


# Affinity keys truncate to a SHORT FIXED head: two prompts sharing at
# least this much prefix must produce IDENTICAL keys, or the chat
# pattern (a history that grows every turn) would never co-locate —
# turn 1's 100-token prompt and turn 2's 300-token prompt both key on
# their first 64 units. Matches the engine's PREFIX_MIN_TOKENS.
_AFFINITY_HEAD = 64


def _affinity_key(request: web.Request, body: bytes) -> Optional[str]:
    """Routing hint for affinity-aware policies: the fixed-length head
    of the request's prompt (str prompt / token ids / first chat
    message), so requests sharing a prefix — the chat pattern — land on
    the replica whose prefix KV cache already holds it. None for
    anything that isn't a generation POST (policies then fall back to
    load)."""
    if request.method != 'POST' or not body:
        return None
    try:
        import json
        payload = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    prompt = payload.get('prompt')
    if isinstance(prompt, str):
        return prompt[:_AFFINITY_HEAD]
    tokens = payload.get('tokens') or (
        prompt if isinstance(prompt, list) else None)
    if isinstance(tokens, list):
        return ','.join(str(t) for t in tokens[:_AFFINITY_HEAD])
    messages = payload.get('messages')
    if (isinstance(messages, list) and messages and
            isinstance(messages[0], dict)):
        first = messages[0]
        return (f"{first.get('role', '')}:"
                f"{str(first.get('content', ''))[:_AFFINITY_HEAD]}")
    return None


class LoadBalancer:

    def __init__(self, policy_name: str,
                 autoscaler: Optional['autoscalers.Autoscaler'] = None,
                 service_name: Optional[str] = None):
        policy_cls = registry.LB_POLICY_REGISTRY.type_from_str(policy_name)
        self.policy: lb_policies.LoadBalancingPolicy = policy_cls()
        # Canonical registry key (aliases resolved) — the declared,
        # bounded metric label value.
        self.policy_name = next(
            k for k in registry.LB_POLICY_REGISTRY.keys()
            if registry.LB_POLICY_REGISTRY.type_from_str(k) is policy_cls)
        # When set, /-/lb/events is scoped to THIS service's entities:
        # the LB port faces end users and must not leak the rest of
        # the shared control-plane journal.
        self.service_name = service_name
        self.autoscaler = autoscaler
        # Span sampling rate in [0, 1] (default 1 = trace everything).
        # Every traced proxied request persists ~7 span rows (lb.*
        # here, engine.* on the replica); at high rps that churns
        # gc_spans' row cap — this knob sheds that write load.
        try:
            self._span_sample = min(1.0, max(0.0, float(
                os.environ.get('SKYTPU_LB_SPAN_SAMPLE', '1') or 1)))
        except ValueError:
            self._span_sample = 1.0
        self._session: Optional[aiohttp.ClientSession] = None

    def set_ready_replicas(self, urls: List[str]) -> None:
        self.policy.set_ready_replicas(urls)

    # ------------------------------------------------------------------
    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        if self.autoscaler is not None:
            self.autoscaler.record_request()
        # Serving-plane trace ingress: honor a well-formed client
        # X-Skytpu-Trace-Id (one chat turn can then join its LB hop,
        # engine spans and any control-plane events under one id) or
        # mint one. The trace + this request's span id are FORWARDED
        # to the replica, so engine-side spans parent under lb.upstream
        # and /v1/traces shows lb → engine.queue → prefill → decode.
        offered = request.headers.get('X-Skytpu-Trace-Id', '')
        client_traced = trace_lib.is_valid_trace_id(offered)
        tid = offered if client_traced else trace_lib.new_trace_id()
        offered_parent = request.headers.get('X-Skytpu-Parent-Span', '')
        parent = (offered_parent
                  if trace_lib.is_valid_trace_id(offered_parent)
                  else None)
        # Sampling: a client-offered trace id is ALWAYS recorded
        # (explicit debugging intent); organic traffic persists spans
        # at SKYTPU_LB_SPAN_SAMPLE. A sampled-out request runs under
        # spans.suppress() — same code path, nothing persisted, no
        # carriers exported (so the replica's engine records nothing
        # either); metrics/histograms still move.
        if (client_traced or self._span_sample >= 1.0 or
                random.random() < self._span_sample):
            with trace_lib.trace_context(tid):
                with spans_lib.span('lb.request', parent_id=parent,
                                    entity=self.service_name,
                                    attrs={'path': request.rel_url.path,
                                           'policy': self.policy_name}
                                    ) as root:
                    return await self._proxy_traced(request, root)
        with spans_lib.suppress():
            with trace_lib.trace_context(tid):
                with spans_lib.span('lb.request', parent_id=parent,
                                    entity=self.service_name,
                                    attrs={'path': request.rel_url.path,
                                           'policy': self.policy_name}
                                    ) as root:
                    return await self._proxy_traced(request, root)

    async def _proxy_traced(self, request: web.Request,
                            root: 'spans_lib.Span') -> web.StreamResponse:
        if not self.policy.has_replicas():
            # Reject BEFORE buffering the body: a scaled-to-zero service
            # must not hold dead multi-MB uploads in RAM.
            _LB_REQUESTS.inc(policy=self.policy_name,
                             outcome='no_replica')
            root.set_attr('outcome', 'no_replica')
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        t0 = time.monotonic()
        body = await request.read()
        with spans_lib.span('lb.pick', entity=self.service_name) as pick:
            # Key extraction (a JSON parse) only when the policy uses
            # it.
            key = (_affinity_key(request, body)
                   if self.policy.wants_affinity_key else None)
            target = self.policy.select(key)
            if target is not None:
                pick.set_attr('replica', target)
        if target is None:
            _LB_REQUESTS.inc(policy=self.policy_name,
                             outcome='no_replica')
            root.set_attr('outcome', 'no_replica')
            return web.json_response(
                {'error': 'no ready replicas'}, status=503)
        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=300))
        url = target.rstrip('/') + request.rel_url.path_qs
        # Strip any client-supplied X-Skytpu-* before stamping our own:
        # forwarding them would DUPLICATE the headers (dict stamping
        # can't replace a differently-cased client key), and the
        # engine's multidict .get() returns the client's value first —
        # letting a client spoof the entity (planting spans inside
        # another service's scoped /-/lb/trace view) or detach engine
        # spans from the LB's trace.
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS
                   and not k.lower().startswith('x-skytpu-')}
        self.policy.request_started(target)
        try:
            with spans_lib.span('lb.upstream', entity=self.service_name,
                                attrs={'replica': target}) as up_span:
                if not spans_lib.suppressed():
                    headers['X-Skytpu-Trace-Id'] = up_span.trace_id or ''
                    headers['X-Skytpu-Parent-Span'] = up_span.span_id
                    # The engine stamps this entity on its request
                    # spans so they fall inside /-/lb/trace/<id>'s
                    # entity scope.
                    if self.service_name:
                        headers['X-Skytpu-Entity'] = self.service_name
                async with self._session.request(request.method, url,
                                                 headers=headers,
                                                 data=body) as upstream:
                    up_span.set_attr('status', upstream.status)
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            resp.headers[k] = v
                    await resp.prepare(request)
                    # Stream the body through: LLM replies are long and
                    # incremental (SSE/chunked) — never buffer them
                    # whole.
                    async for chunk in upstream.content.iter_chunked(
                            16384):
                        await resp.write(chunk)
                    await resp.write_eof()
                    _LB_REQUESTS.inc(policy=self.policy_name,
                                     outcome='proxied')
                    root.set_attr('outcome', 'proxied')
                    return resp
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            _LB_REQUESTS.inc(policy=self.policy_name,
                             outcome='upstream_error')
            root.set_attr('outcome', 'upstream_error')
            return web.json_response(
                {'error': f'upstream {target} failed: {e}'}, status=502)
        finally:
            self.policy.request_finished(target)
            _LB_LATENCY.observe(time.monotonic() - t0,
                                policy=self.policy_name)

    async def _health(self, request: web.Request) -> web.Response:
        del request
        ready = len(self.policy._replicas)  # pylint: disable=protected-access
        return web.json_response({'ready_replicas': ready})

    async def _metrics(self, request: web.Request) -> web.Response:
        """This controller process's whole registry: LB counters and
        latency histograms, autoscaler gauges, replica-probe outcome
        counters — one scrape target per service."""
        del request
        return web.Response(text=metrics_lib.render(),
                            content_type='text/plain')

    async def _events(self, request: web.Request) -> web.Response:
        """Journal query, same filter surface as the API server's
        /v1/events — one shared parser (journal.filters_from_query) so
        the two endpoints cannot diverge. Scoped: the LB port faces
        end users, so with a bound service_name only THIS service's
        entities (the service row + its ``svc/<id>`` replicas) are
        visible, not the rest of the shared journal. The scan runs
        off-loop: this event loop is also carrying live proxied
        traffic."""
        try:
            kwargs = journal_lib.filters_from_query(request.query)
        except ValueError:
            return web.json_response(
                {'error': 'since/limit must be numbers'}, status=400)
        if self.service_name is not None:
            kwargs['entity_scope'] = self.service_name
        result = await asyncio.to_thread(journal_lib.query, **kwargs)
        return web.json_response({'events': result})

    async def _trace(self, request: web.Request) -> web.Response:
        """Span tree for one trace (``/-/lb/trace/<trace_id>``) —
        entity-SCOPED like /-/lb/events: the LB port faces end users,
        so with a bound service_name only spans stamped with this
        service's entities (the lb.request/pick/upstream hops this
        process recorded) are visible, not the rest of the shared
        spans table. Off-loop: the read flushes the write-behind queue
        and scans sqlite."""
        trace_id = request.match_info.get('trace_id', '')
        if not trace_lib.is_valid_trace_id(trace_id):
            return web.json_response(
                {'error': f'bad trace id {trace_id!r}'}, status=400)
        # A None service_name disables entity scoping entirely — only
        # legitimate for a standalone LB owning its whole journal DB.
        result = await asyncio.to_thread(
            spans_lib.tree, trace_id, self.service_name)
        return web.json_response(result)

    # ------------------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get('/-/lb/health', self._health)
        app.router.add_get('/-/lb/metrics', self._metrics)
        app.router.add_get('/-/lb/events', self._events)
        app.router.add_get('/-/lb/trace/{trace_id}', self._trace)
        app.router.add_route('*', '/{tail:.*}', self._proxy)

        async def _cleanup(app_):
            del app_
            if self._session is not None:
                await self._session.close()

        app.on_cleanup.append(_cleanup)
        return app
