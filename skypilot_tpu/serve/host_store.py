"""Host-RAM spill tier for the block-paged KV cache (docs/ENGINE.md,
"KV memory hierarchy").

The engine's prefix store holds page REFS — device HBM. A cold
session's continuation state is exactly its prefix-store snapshot, so
when a session goes idle (SKYTPU_ENGINE_KV_IDLE_SPILL_S) or page
pressure evicts an entry, the engine exports the entry's pages
(models/paging.py export_pages), frees the device pages immediately,
and parks the page CONTENTS here. A later request extending the same
prefix wakes the entry: fresh pages come from the allocator, the blob
scatters back in (import_pages), and admission proceeds through the
normal shared-prefix path — the 2-4x sessions-per-replica lever the
KV-hierarchy bench measures.

Wire format / integrity discipline:
  - Entries are framed-npy blobs (utils/framed.py _encode_payload):
    one npy block per pool field — k/v or c_kv/k_rope, plus the int8
    scale sidecars when the pool is quantized — with a JSON meta head
    recording the page count and a sha256 content fingerprint
    (serve/disagg/handoff.py kv_fingerprint). decode verifies the
    fingerprint, so a corrupted blob raises instead of waking garbage
    KV. fp16 pools round-trip BIT-identically (property-tested).
  - Keys are the engine's prefix-store keys (token tuples). One copy
    of an entry lives at a time: spilling removes it from the device
    prefix store, waking pops it from here.

Budgeting: LRU by BYTES against SKYTPU_ENGINE_KV_HOST_MB (0 disables
the tier). Eviction here is a plain drop — the entry's device pages
were already freed at spill time, so the session just re-prefills like
any cache miss. Thread-safety mirrors HandoffStore: every access under
one lock; occupancy() is the /health snapshot.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import framed

logger = sky_logging.init_logger(__name__)


def _kv_fingerprint(arrays: Dict[str, Any]) -> str:
    # serve (this layer) may not import serve/disagg at module level —
    # the content-fingerprint helper is a sanctioned runtime bridge,
    # reached lazily like the handoff client itself.
    from skypilot_tpu.serve.disagg import handoff as handoff_lib
    return handoff_lib.kv_fingerprint(arrays)


class HostPageStore:
    """Byte-budgeted LRU of spilled KV page blobs, keyed by prefix
    token key. All methods are thread-safe (the batch loop spills and
    wakes from its worker threads; /health reads occupancy from the
    event loop)."""

    def __init__(self, budget_mb: int):
        self.budget_bytes = int(budget_mb) * (1 << 20)
        self._lock = threading.Lock()
        # key -> (blob bytes, n_pages). Insertion order IS the LRU
        # order (move_to_end on get-miss never happens: a hit pops).
        self._entries: 'Dict[Tuple[int, ...], Tuple[bytes, int]]' = {}
        self._order: List[Tuple[int, ...]] = []
        self._bytes = 0
        self._pages = 0

    def put(self, key, arrays: Dict[str, Any], n_pages: int) -> bool:
        """Park one spilled entry. Returns False (and stores nothing)
        when the blob alone exceeds the whole budget; otherwise evicts
        LRU entries until it fits. A duplicate key is refreshed — the
        caller re-exported the same immutable pages, so last-write-wins
        is safe."""
        meta = {'n_pages': int(n_pages),
                'kv_sha256': _kv_fingerprint(arrays)}
        blob = framed._encode_payload(meta, arrays)
        if len(blob) > self.budget_bytes:
            return False
        with self._lock:
            self._pop_locked(key)
            while self._bytes + len(blob) > self.budget_bytes:
                old = self._order[0]
                dropped = self._pop_locked(old)
                assert dropped is not None
                logger.debug(f'host tier evicted a {dropped[1]}-page '
                             f'entry for space')
            self._entries[key] = (blob, int(n_pages))
            self._order.append(key)
            self._bytes += len(blob)
            self._pages += int(n_pages)
        return True

    def pop(self, key) -> Optional[Dict[str, Any]]:
        """Wake: remove and decode the entry (one copy lives at a
        time — the caller re-admits it to the device prefix store).
        Returns the page arrays, or None on a miss. Raises
        framed.RemoteError(kind='integrity') when the blob's content
        fingerprint no longer matches — waking corrupted KV would
        silently poison every sharer of the prefix."""
        with self._lock:
            entry = self._pop_locked(key)
        if entry is None:
            return None
        meta, arrays = framed._decode_payload(entry[0])
        got = _kv_fingerprint(arrays)
        if got != meta.get('kv_sha256'):
            raise framed.RemoteError(
                'spilled KV blob failed its content fingerprint',
                kind='integrity')
        return arrays

    def _pop_locked(self, key) -> Optional[Tuple[bytes, int]]:
        entry = self._entries.pop(key, None)
        if entry is None:
            return None
        self._order.remove(key)
        self._bytes -= len(entry[0])
        self._pages -= entry[1]
        return entry

    def clear(self) -> None:
        """Drop every entry (prefix-store wipes and poisoned-state
        resets distrust everything; re-prefill is always correct)."""
        with self._lock:
            self._entries.clear()
            self._order.clear()
            self._bytes = 0
            self._pages = 0

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def pages_spilled(self) -> int:
        """Device pages' worth of KV currently parked here (the
        skytpu_engine_kv_pages_spilled gauge, sampled at scrape)."""
        with self._lock:
            return self._pages

    def occupancy(self) -> Dict[str, int]:
        """Host-tier occupancy for /health: entry count, resident
        bytes, page count, and the byte budget."""
        with self._lock:
            return {'entries': len(self._entries),
                    'bytes': self._bytes,
                    'pages': self._pages,
                    'budget_bytes': self.budget_bytes}
