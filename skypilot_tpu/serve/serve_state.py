"""Service + replica state DB (control-plane side).

Reference analog: sky/serve/serve_state.py (service/replica tables).
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.analysis import state_machines
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils
from skypilot_tpu.utils import vclock

logger = sky_logging.init_logger(__name__)

_DB_PATH_ENV = 'SKYTPU_SERVE_DB'


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'    # controller up, no replica READY yet
    READY = 'READY'                  # ≥1 replica READY behind the LB
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    SHUTDOWN = 'SHUTDOWN'            # terminal

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.SHUTDOWN, ServiceStatus.FAILED)

    def colored_str(self) -> str:
        color = {'READY': '\x1b[32m', 'FAILED': '\x1b[31m'}.get(
            self.value, '\x1b[33m')
        return f'{color}{self.value}\x1b[0m'


class ReplicaStatus(enum.Enum):
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'            # cluster up, app not ready yet
    READY = 'READY'
    NOT_READY = 'NOT_READY'          # probe failing; grace period
    DRAINING = 'DRAINING'            # no new traffic; in-flight finishes
    FAILED = 'FAILED'
    PREEMPTED = 'PREEMPTED'
    SHUTTING_DOWN = 'SHUTTING_DOWN'

    def is_serving(self) -> bool:
        # DRAINING is deliberately NOT serving: the LB stops routing
        # to a draining replica the moment the transition commits —
        # that is what lets its in-flight requests finish.
        return self is ReplicaStatus.READY

    def colored_str(self) -> str:
        color = {'READY': '\x1b[32m', 'FAILED': '\x1b[31m',
                 'PREEMPTED': '\x1b[31m'}.get(self.value, '\x1b[33m')
        return f'{color}{self.value}\x1b[0m'


def _db_path() -> str:
    path = os.path.expanduser(knobs.get_str(_DB_PATH_ENV))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    conn = sqlite_utils.connect_wal(_db_path())
    conn.execute("""
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            task_config TEXT,
            spec TEXT,
            status TEXT,
            lb_port INTEGER,
            controller_pid INTEGER,
            created_at REAL,
            failure_reason TEXT,
            version INTEGER DEFAULT 1,
            update_mode TEXT DEFAULT 'rolling',
            trace_id TEXT
        )""")
    for col, decl in (('version', 'INTEGER DEFAULT 1'),
                      ('update_mode', "TEXT DEFAULT 'rolling'"),
                      ('controller_restarts', 'INTEGER DEFAULT 0'),
                      ('trace_id', 'TEXT')):
        try:
            conn.execute(f'ALTER TABLE services ADD COLUMN {col} {decl}')
        except sqlite3.OperationalError:
            pass
    conn.execute("""
        CREATE TABLE IF NOT EXISTS replicas (
            service TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            url TEXT,
            launched_at REAL,
            consecutive_failures INTEGER DEFAULT 0,
            job_id INTEGER,
            version INTEGER DEFAULT 1,
            PRIMARY KEY (service, replica_id)
        )""")
    # Pre-pool / pre-update databases lack these columns.
    for col, decl in (('job_id', 'INTEGER'), ('version',
                                              'INTEGER DEFAULT 1')):
        try:
            conn.execute(f'ALTER TABLE replicas ADD COLUMN {col} {decl}')
        except sqlite3.OperationalError:
            pass
    return conn


def controller_log_path(service: str) -> str:
    d = os.path.expanduser('~/.skytpu/serve')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'controller_{service}.log')


# ---------------------------------------------------------------------------
# Services
# ---------------------------------------------------------------------------
def add_service(name: str, task_config: Dict[str, Any],
                spec: Dict[str, Any], lb_port: int) -> bool:
    # The up-request's trace sticks to the row: the controller (a
    # detached process) adopts it at startup so its journal entries
    # correlate back to the request that created the service.
    trace_id = trace_lib.get()
    with _conn() as conn:
        try:
            conn.execute(
                'INSERT INTO services (name, task_config, spec, status, '
                'lb_port, created_at, trace_id) '
                'VALUES (?, ?, ?, ?, ?, ?, ?)',
                (name, json.dumps(task_config), json.dumps(spec),
                 ServiceStatus.CONTROLLER_INIT.value, lb_port,
                 time.time(), trace_id))
        except sqlite3.IntegrityError:
            return False
    journal_lib.record_transition(
        'service', name, None, ServiceStatus.CONTROLLER_INIT.value,
        trace_id=trace_id)
    return True


def update_service(name: str, **cols: Any) -> None:
    sets = ', '.join(f'{k} = ?' for k in cols)
    with _conn() as conn:
        conn.execute(f'UPDATE services SET {sets} WHERE name = ?',
                     (*cols.values(), name))


def _guarded_transition(table: str, enum_cls, transitions,
                        where_sql: str, where_params: tuple,
                        status, set_sql: str = '',
                        set_params: tuple = (),
                        machine: str = '', entity: str = '',
                        reason: Optional[str] = None) -> bool:
    """Shared guarded status write: SELECT current status, check the
    declared transition table, UPDATE — all under BEGIN IMMEDIATE, so
    a concurrent terminal writer cannot slip between the check and the
    write. Returns False when refused (row gone or undeclared edge).

    The winning write (alone, after commit, and only for a real edge —
    not a self-loop re-write) is published to the observe journal, so
    every committed transition of docs/STATE_MACHINES.md appears there
    exactly once."""
    conn = _conn()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            f'SELECT status FROM {table} WHERE {where_sql}',
            where_params).fetchone()
        if row is None:
            return False
        cur = enum_cls(row[0])
        if not state_machines.can_transition(transitions, cur.name,
                                             status.name):
            logger.warning(
                f'{table} {where_params}: refusing undeclared '
                f'transition {cur.value} -> {status.value} (see '
                f'analysis/state_machines.py).')
            return False
        conn.execute(
            f'UPDATE {table} SET status = ?{set_sql} '
            f'WHERE {where_sql}',
            (status.value, *set_params, *where_params))
        # Inside the write lock (journal = different DB, no deadlock):
        # journal order matches commit order even when a preempted
        # winner races a later writer's journal call.
        if machine and cur is not status:
            journal_lib.record_transition(machine, entity, cur.value,
                                          status.value, reason=reason)
    return True


def set_service_status(name: str, status: ServiceStatus,
                       failure_reason: Optional[str] = None) -> bool:
    """Guarded transition per state_machines.SERVICE_TRANSITIONS: a
    `serve down` racing a crashing controller cannot have its terminal
    SHUTDOWN overwritten by a late FAILED (nor a SHUTDOWN service
    resurrected). Returns False when refused."""
    return _guarded_transition(
        'services', ServiceStatus, state_machines.SERVICE_TRANSITIONS,
        'name = ?', (name,), status,
        set_sql=', failure_reason = ?', set_params=(failure_reason,),
        machine='service', entity=name, reason=failure_reason)


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        row = conn.execute('SELECT * FROM services WHERE name = ?',
                           (name,)).fetchone()
        return _service_row(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        rows = conn.execute(
            'SELECT * FROM services ORDER BY created_at').fetchall()
        return [_service_row(r) for r in rows]


def remove_service(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM services WHERE name = ?', (name,))
        conn.execute('DELETE FROM replicas WHERE service = ?', (name,))


def _service_row(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['status'] = ServiceStatus(d['status'])
    d['task_config'] = json.loads(d['task_config'] or '{}')
    d['spec'] = json.loads(d['spec'] or '{}')
    return d


# ---------------------------------------------------------------------------
# Replicas
# ---------------------------------------------------------------------------
def add_replica(service: str, replica_id: int, cluster_name: str,
                version: int = 1, url: str = '') -> bool:
    """Register a fresh replica in its initial PROVISIONING state (the
    only legal entry point of the replica state machine). Returns False
    when the id is already taken — never overwrites an existing row."""
    with _conn() as conn:
        cur = conn.execute(
            'INSERT INTO replicas (service, replica_id, cluster_name, '
            'status, url, launched_at, version) VALUES (?, ?, ?, ?, ?, '
            '?, ?) ON CONFLICT(service, replica_id) DO NOTHING',
            (service, replica_id, cluster_name,
             ReplicaStatus.PROVISIONING.value, url, vclock.now(),
             version))
        created = cur.rowcount > 0
    if created:
        journal_lib.record_transition(
            'replica', f'{service}/{replica_id}', None,
            ReplicaStatus.PROVISIONING.value)
    return created


def upsert_replica(service: str, replica_id: int, **cols: Any) -> None:
    """Raw column upsert for NON-status replica columns (url, job_id,
    cluster_name, ...). Status changes must go through
    set_replica_status / add_replica so the declared transition table
    applies — skylint's state-machine checker enforces that for
    package code (tests may still seed arbitrary states here)."""
    cols.setdefault('launched_at', vclock.now())
    names = ', '.join(cols)
    ph = ', '.join('?' * len(cols))
    updates = ', '.join(f'{k}=excluded.{k}' for k in cols)
    with _conn() as conn:
        conn.execute(
            f'INSERT INTO replicas (service, replica_id, {names}) '
            f'VALUES (?, ?, {ph}) '
            f'ON CONFLICT(service, replica_id) DO UPDATE SET {updates}',
            (service, replica_id, *cols.values()))


def set_replica_status(service: str, replica_id: int,
                       status: ReplicaStatus) -> bool:
    """Guarded transition per state_machines.REPLICA_TRANSITIONS: a
    stale launch thread can never flip a FAILED/SHUTTING_DOWN replica
    back to STARTING (the terminal-overwrite bug class). Returns False
    when refused (row gone — e.g. terminated mid-launch — or an
    undeclared edge)."""
    return _guarded_transition(
        'replicas', ReplicaStatus, state_machines.REPLICA_TRANSITIONS,
        'service = ? AND replica_id = ?', (service, replica_id), status,
        machine='replica', entity=f'{service}/{replica_id}')


def bump_replica_failures(service: str, replica_id: int) -> int:
    with _conn() as conn:
        conn.execute(
            'UPDATE replicas SET consecutive_failures = '
            'consecutive_failures + 1 WHERE service = ? AND replica_id = ?',
            (service, replica_id))
        row = conn.execute(
            'SELECT consecutive_failures FROM replicas WHERE service = ? '
            'AND replica_id = ?', (service, replica_id)).fetchone()
        return int(row[0]) if row else 0


def reset_replica_failures(service: str, replica_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE replicas SET consecutive_failures = 0 WHERE '
            'service = ? AND replica_id = ?', (service, replica_id))


def remove_replica(service: str, replica_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            'DELETE FROM replicas WHERE service = ? AND replica_id = ?',
            (service, replica_id))


def get_replicas(service: str) -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        rows = conn.execute(
            'SELECT * FROM replicas WHERE service = ? ORDER BY replica_id',
            (service,)).fetchall()
        out = []
        for r in rows:
            d = dict(r)
            d['status'] = ReplicaStatus(d['status'])
            out.append(d)
        return out


def acquire_worker(service: str, job_id: int) -> Optional[Dict[str, Any]]:
    """Atomically claim one READY, unassigned pool worker for a managed
    job. Returns its replica record, or None when every worker is busy
    (the caller queues). sqlite_utils.immediate takes sqlite's single
    write lock up front (and fails loudly on an already-open
    transaction), so the SELECT-then-UPDATE is atomic against
    concurrent controllers (and portable: sqlite < 3.35 has no
    UPDATE...RETURNING)."""
    conn = _conn()
    conn.row_factory = sqlite3.Row
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT rowid AS _rowid, * FROM replicas WHERE service = ? '
            "AND status = 'READY' AND job_id IS NULL ORDER BY replica_id "
            'LIMIT 1', (service,)).fetchone()
        if row is None:
            return None
        conn.execute('UPDATE replicas SET job_id = ? WHERE rowid = ?',
                     (job_id, row['_rowid']))
        d = dict(row)
        d.pop('_rowid')
        d['job_id'] = job_id
        d['status'] = ReplicaStatus(d['status'])
        return d


def release_worker(service: str, job_id: int) -> None:
    """Return a managed job's worker to the idle set."""
    with _conn() as conn:
        conn.execute(
            'UPDATE replicas SET job_id = NULL WHERE service = ? AND '
            'job_id = ?', (service, job_id))


def next_replica_id(service: str) -> int:
    with _conn() as conn:
        row = conn.execute(
            'SELECT MAX(replica_id) FROM replicas WHERE service = ?',
            (service,)).fetchone()
    return (int(row[0]) if row and row[0] is not None else 0) + 1
