"""TpuSliceBackend: the cluster-lifecycle + gang-execution heart, Ray-free.

Reference analog: sky/backends/cloud_vm_ray_backend.py (6.5k LoC):
- `RetryingVmProvisioner:1293` → `_FailoverProvisioner` here (region/cloud
  failover + blocklist; the per-zone loop lives in provisioner.bulk_provision)
- `RayCodeGen:344` (placement-group gang scheduling) → job-spec JSON executed
  by skylet/slice_driver.py on the head host (SPMD gang, no Ray)
- `CloudVmRayResourceHandle:2407` (pickled) → `SliceResourceHandle` (JSON)
- `_execute_task_n_nodes:6439` TPU-pod host fan-out → ordered_instances() of
  the slice (hosts are first-class, no num_ips_per_node fixup needed)
"""
from __future__ import annotations

import base64
import json
import os
import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.backends import backend as backend_lib
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner as provisioner_lib
from skypilot_tpu.utils import command_runner as command_runner_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import locks
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import timeline
from skypilot_tpu.utils.status_lib import ClusterStatus, JobStatus

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

from skypilot_tpu.skylet.constants import WORKDIR_NAME  # noqa: E402


class SliceResourceHandle:
    """JSON-serializable record of a live cluster (analog :2407)."""

    def __init__(self, *, cluster_name: str, cloud: str, region: str,
                 zone: Optional[str],
                 launched_resources: Dict[str, Any],
                 provider_config: Dict[str, Any]):
        self.cluster_name = cluster_name
        self.cloud = cloud
        self.region = region
        self.zone = zone
        self.launched_resources = launched_resources
        self.provider_config = provider_config

    def to_dict(self) -> Dict[str, Any]:
        return {
            'cluster_name': self.cluster_name,
            'cloud': self.cloud,
            'region': self.region,
            'zone': self.zone,
            'launched_resources': self.launched_resources,
            'provider_config': self.provider_config,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'SliceResourceHandle':
        return cls(cluster_name=d['cluster_name'], cloud=d['cloud'],
                   region=d['region'], zone=d.get('zone'),
                   launched_resources=d.get('launched_resources', {}),
                   provider_config=d.get('provider_config', {}))

    def get_cluster_info(self) -> provision_common.ClusterInfo:
        return provision.get_cluster_info(self.cloud, self.region,
                                          self.cluster_name,
                                          self.provider_config)

    def launched_resources_obj(self) -> 'resources_lib.Resources':
        from skypilot_tpu import resources as resources_lib
        res = resources_lib.Resources.from_yaml_config(
            self.launched_resources)
        assert isinstance(res, resources_lib.Resources)
        return res

    @property
    def num_hosts(self) -> int:
        res = self.launched_resources_obj()
        return res.tpu.total_hosts if res.tpu else 1


class _FailoverProvisioner:
    """Region/cloud failover with blocklist (analog RetryingVmProvisioner:1293).

    Zone-level failover happens inside provisioner.bulk_provision; when a
    whole region is exhausted the failed resources are blocklisted and the
    optimizer re-runs to pick the next region/cloud (FailoverCloudErrorHandler
    analog: error classification happens in the provisioners themselves).
    """

    def __init__(self, cluster_name: str):
        self._cluster_name = cluster_name
        self._history: List[Exception] = []

    def provision_with_failover(
        self, to_provision: 'resources_lib.Resources',
        task: 'task_lib.Task',
        ports_to_open: Optional[List[str]],
    ) -> 'tuple[provision_common.ProvisionRecord, resources_lib.Resources]':
        from skypilot_tpu import optimizer as optimizer_lib
        from skypilot_tpu import dag as dag_lib
        blocked: List['resources_lib.Resources'] = []
        current = to_provision
        while True:
            cloud = current.cloud
            assert cloud is not None
            regions = cloud.regions_with_offering(current)
            for region in regions:
                try:
                    record = provisioner_lib.bulk_provision(
                        cloud, region.name, self._cluster_name, current,
                        ports_to_open=ports_to_open)
                    return record, current.copy(region=region.name,
                                                zone=record.zone)
                except exceptions.ResourcesUnavailableError as e:
                    self._history.extend(e.failover_history)
                    if e.no_failover:
                        raise
                    logger.warning(
                        f'Region {region.name} exhausted; failing over.')
            # Whole cloud exhausted for this resource: blocklist and re-plan.
            blocked.append(current.copy(region=None, zone=None))
            mini_dag = dag_lib.Dag()
            mini_dag.add(task)
            try:
                optimizer_lib.Optimizer.optimize(
                    mini_dag, blocked_resources=blocked, quiet=True)
            except exceptions.ResourcesUnavailableError as e:
                raise exceptions.ResourcesUnavailableError(
                    f'Failed to provision {self._cluster_name!r} on all '
                    f'feasible clouds/regions/zones.',
                    failover_history=self._history) from e
            assert task.best_resources is not None
            current = task.best_resources


class TpuSliceBackend(backend_lib.Backend[SliceResourceHandle]):
    """Provisions TPU slices and gang-executes jobs on them."""

    NAME = 'tpuslice'

    # ------------------------------------------------------------------
    # Provision
    # ------------------------------------------------------------------
    @timeline.event
    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool,
                  cluster_name: str,
                  retry_until_up: bool = False
                  ) -> Optional[SliceResourceHandle]:
        assert to_provision is not None and to_provision.is_launchable(), (
            'provision requires launchable resources (run the optimizer '
            'first).')
        if dryrun:
            logger.info(f'Dryrun: would provision {to_provision!r} as '
                        f'{cluster_name!r}.')
            return None
        with locks.cluster_status_lock(cluster_name, timeout=600):
            existing = global_state.get_cluster(cluster_name)
            if existing is not None and existing['status'] == ClusterStatus.UP:
                handle = SliceResourceHandle.from_dict(existing['handle'])
                launched = handle.launched_resources_obj()
                if not to_provision.less_demanding_than(launched):
                    raise exceptions.ResourcesMismatchError(
                        f'Cluster {cluster_name!r} exists with '
                        f'{launched.format_brief()}, which cannot serve '
                        f'{to_provision.format_brief()}. Use a new cluster '
                        f'name or `skytpu down {cluster_name}` first.')
                logger.info(f'Reusing existing cluster {cluster_name!r}.')
                return handle

            # retry_until_up: when every cloud/region/zone is exhausted,
            # sleep and restart the whole failover sweep instead of failing
            # (reference: `sky launch --retry-until-up`). Gap is env-tunable
            # so tests don't wait minutes.
            gap = knobs.get_float('SKYTPU_RETRY_UNTIL_UP_GAP')
            while True:
                try:
                    record, final_res = _FailoverProvisioner(
                        cluster_name).provision_with_failover(
                            to_provision, task,
                            ports_to_open=to_provision.ports)
                    break
                except exceptions.ResourcesUnavailableError as e:
                    if not retry_until_up or e.no_failover:
                        raise
                    logger.warning(
                        f'No capacity anywhere for {cluster_name!r}; '
                        f'--retry-until-up: retrying in {gap:.0f}s '
                        f'({len(e.failover_history)} failures so far).')
                    time.sleep(gap)
            handle = SliceResourceHandle(
                cluster_name=cluster_name,
                cloud=record.provider_name,
                region=record.region,
                zone=record.zone,
                launched_resources=final_res.to_yaml_config(),
                provider_config=final_res.make_deploy_variables(
                    record.region, [record.zone] if record.zone else [],
                    cluster_name),
            )
            global_state.add_or_update_cluster(cluster_name,
                                               handle.to_dict(),
                                               ClusterStatus.INIT,
                                               is_launch=True)
            cluster_info = handle.get_cluster_info()
            provisioner_lib.wait_for_connection(cluster_info)
            provisioner_lib.post_provision_runtime_setup(
                cluster_name, cluster_info)
            # Arm autostop if requested.
            autostop = final_res.autostop
            if autostop is not None:
                self.set_autostop(handle, autostop['idle_minutes'],
                                  autostop['down'])
            global_state.add_or_update_cluster(cluster_name,
                                               handle.to_dict(),
                                               ClusterStatus.UP)
            logger.info(f'Cluster {cluster_name!r} is UP '
                        f'({cluster_info.num_instances} hosts).')
            return handle

    # ------------------------------------------------------------------
    # Sync / setup
    # ------------------------------------------------------------------
    def _runners(self, handle: SliceResourceHandle
                 ) -> List[command_runner_lib.CommandRunner]:
        return provisioner_lib.get_command_runners(handle.get_cluster_info())

    @timeline.event
    def sync_workdir(self, handle: SliceResourceHandle, workdir: str) -> None:
        runners = self._runners(handle)

        def _sync(runner: command_runner_lib.CommandRunner) -> None:
            runner.rsync(os.path.join(os.path.expanduser(workdir), ''),
                         f'{WORKDIR_NAME}/', up=True,
                         excludes=['.git'])

        logger.info(f'Syncing workdir {workdir!r} to '
                    f'{len(runners)} host(s)...')
        subprocess_utils.run_in_parallel(_sync, runners)

    @timeline.event
    def sync_file_mounts(self, handle: SliceResourceHandle,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        if all_file_mounts:
            from skypilot_tpu import cloud_stores
            from skypilot_tpu.data import storage as storage_lib
            runners = self._runners(handle)
            for dst, src in all_file_mounts.items():
                store = cloud_stores.get_storage_from_path(src)
                if store is not None:
                    # URL source (gs://, s3://, https://): each host pulls
                    # it directly — no control-plane round trip. On the
                    # local fake cloud the path lands inside the host's
                    # workdir, where the job's cwd is.
                    def _fetch(runner: command_runner_lib.CommandRunner,
                               store=store, src=src, dst=dst) -> None:
                        resolved = storage_lib.resolve_local_dst(runner, dst)
                        cmd = store.make_sync_command(src, resolved)
                        rc = runner.run(cmd, log_path='/dev/null')
                        if rc != 0:
                            raise exceptions.StorageError(
                                f'Failed to fetch file mount {dst} on '
                                f'{runner.node_id}.')

                    subprocess_utils.run_in_parallel(_fetch, runners)
                    continue

                def _sync(runner: command_runner_lib.CommandRunner,
                          dst=dst, src=src) -> None:
                    if isinstance(runner,
                                  command_runner_lib.LocalProcessCommandRunner):
                        from skypilot_tpu.skylet import constants
                        dst = f'{WORKDIR_NAME}/{constants.workdir_rel(dst)}'
                    runner.rsync(os.path.expanduser(src), dst, up=True)

                subprocess_utils.run_in_parallel(_sync, runners)
        if storage_mounts:
            from skypilot_tpu.data import storage as storage_lib
            storage_lib.execute_storage_mounts(handle, storage_mounts)

    @timeline.event
    def setup(self, handle: SliceResourceHandle, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        if failpoints.ACTIVE:
            # A firing surfaces as a setup failure mid-launch: first
            # launches class it FAILED_PRECHECKS, recovery rounds class
            # it like any other failed attempt (backoff + failover).
            failpoints.fire('jobs.setup')
        if task.setup is None:
            return
        runners = self._runners(handle)
        setup_log = os.path.expanduser(
            f'~/.skytpu/logs/{handle.cluster_name}/setup.log')
        logger.info(f'Running setup on {len(runners)} host(s)...')
        from skypilot_tpu.utils import docker_utils
        launched = handle.launched_resources_obj()
        docker_image = docker_utils.docker_image_of(launched.image_id)

        def _setup(runner: command_runner_lib.CommandRunner) -> None:
            cmd = f'cd {WORKDIR_NAME} 2>/dev/null; {task.setup}'
            if docker_image:
                # image_id: docker:<img> — setup runs INSIDE the task
                # container (started here, reused by the run phase). Env
                # must be baked into the wrapped command: the host-shell
                # exports from runner.run(env=...) don't cross the docker
                # exec boundary (same pattern as slice_driver's rank
                # commands).
                import shlex as shlex_lib
                exports = ' '.join(
                    f'export {k}={shlex_lib.quote(str(v))};'
                    for k, v in task.envs_and_secrets.items())
                inner = f'{exports} {task.setup}'
                cmd = (f'{docker_utils.bootstrap_cmd(docker_image)} && '
                       f'{docker_utils.wrap(inner, WORKDIR_NAME)}')
            rc = runner.run(cmd, env=task.envs_and_secrets,
                            log_path=setup_log)
            if rc != 0:
                raise exceptions.ClusterSetupError(
                    f'Setup failed on {runner.node_id} (exit {rc}). '
                    f'See {setup_log}.')

        subprocess_utils.run_in_parallel(_setup, runners)

    # ------------------------------------------------------------------
    # Execute (gang)
    # ------------------------------------------------------------------
    def _head_runner(self, cluster_info: provision_common.ClusterInfo
                     ) -> command_runner_lib.CommandRunner:
        return provisioner_lib.get_command_runners(cluster_info)[0]

    def _remote_py(self, cluster_info: provision_common.ClusterInfo) -> str:
        return provisioner_lib.remote_python(cluster_info)

    def _run_on_head_json(self, cluster_info, cmd: str) -> Dict[str, Any]:
        head = self._head_runner(cluster_info)
        rc, stdout, _ = head.run(cmd, require_outputs=True,
                                 log_path='/dev/null')
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, stdout)
        line = stdout.strip().splitlines()[-1] if stdout.strip() else '{}'
        return json.loads(line)

    @timeline.event
    def execute(self, handle: SliceResourceHandle, task: 'task_lib.Task',
                detach_run: bool = False) -> Optional[int]:
        if task.run is None:
            logger.info('Task has no run command; nothing to execute.')
            return None
        assert isinstance(task.run, str), (
            'callable run sections are executed via the python API only.')
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        launched = handle.launched_resources_obj()
        sl = launched.tpu

        # 1. Register the job in the on-cluster queue.
        from skypilot_tpu.utils import common_utils
        import shlex
        add_cmd = (f'{py} -m skypilot_tpu.skylet.job_lib add '
                   f'--name {shlex.quote(task.name or "task")} '
                   f'--user {shlex.quote(common_utils.get_user())} '
                   f'--run-cmd {shlex.quote(task.run[:500])} '
                   f'--num-hosts {handle.num_hosts}')
        job_id = int(self._run_on_head_json(cluster_info, add_cmd)['job_id'])

        # 2. Build the gang job spec (the RayCodeGen analog).
        hosts: List[Dict[str, Any]] = []
        for inst in cluster_info.ordered_instances():
            if cluster_info.provider_name == 'local':
                host_dir = cluster_info.host_dirs[inst.instance_id]
                hosts.append({
                    'kind': 'local',
                    'ip': inst.internal_ip,
                    'slice_index': inst.slice_index,
                    'worker_id': inst.worker_id,
                    'workdir': os.path.join(host_dir, WORKDIR_NAME),
                })
            elif cluster_info.provider_name == 'kubernetes':
                # Pods have no sshd. The driver runs ON the head pod: its
                # own rank is a plain local process; peer pods are reached
                # over the pod network via the exec agent that runtime
                # setup started on them (skylet/exec_agent.py) — stock
                # images work: no kubectl binary, no pods/exec RBAC.
                # SKYTPU_K8S_KUBECTL_EXEC=1 restores the old in-cluster
                # kubectl-exec fan-out (image must ship kubectl + RBAC).
                pc = cluster_info.provider_config or {}
                is_head = (inst.slice_index == 0 and inst.worker_id == 0)
                use_kubectl = knobs.get_bool('SKYTPU_K8S_KUBECTL_EXEC')
                kind = ('local' if is_head
                        else ('k8s' if use_kubectl else 'agent'))
                host: Dict[str, Any] = {
                    'kind': kind,
                    'ip': inst.internal_ip,
                    'slice_index': inst.slice_index,
                    'worker_id': inst.worker_id,
                    'workdir': f'/root/{WORKDIR_NAME}',
                }
                if kind == 'k8s':
                    host['k8s'] = {
                        'pod': inst.instance_id,
                        'namespace': pc.get('namespace', 'default'),
                    }
                elif kind == 'agent':
                    from skypilot_tpu.skylet import exec_agent
                    host['agent'] = {
                        'ip': inst.internal_ip,
                        'port': int(pc.get('exec_agent_port',
                                           exec_agent.DEFAULT_PORT)),
                    }
                hosts.append(host)
            else:
                hosts.append({
                    'kind': 'ssh',
                    'ip': inst.get_feasible_ip(),
                    'slice_index': inst.slice_index,
                    'worker_id': inst.worker_id,
                    'workdir': f'~/{WORKDIR_NAME}',
                    'ssh': {
                        'user': cluster_info.ssh_user,
                        'ip': inst.get_feasible_ip(),
                        'port': inst.ssh_port,
                        # Head-to-worker hops reuse the cluster key, which
                        # runtime setup installs at this fixed path.
                        'private_key': '~/.ssh/skytpu-cluster-key',
                    },
                })
        # Exit flush barrier for MOUNT_CACHED storage (reference:
        # cloud_vm_ray_backend.py:763-790): the driver runs these on every
        # host after the gang succeeds, before the job is marked done.
        epilogue: List[str] = []
        if task.storage_mounts:
            from skypilot_tpu.data import storage as storage_lib
            epilogue = list(storage_lib.flush_commands(
                handle, task.storage_mounts).values())
        from skypilot_tpu.observe import spans as spans_lib
        from skypilot_tpu.observe import trace as trace_lib
        spec = {
            'job_id': job_id,
            'cluster_name': handle.cluster_name,
            'hosts': hosts,
            'run_cmd': task.run,
            'envs': task.envs_and_secrets,
            'chips_per_host': sl.chips_per_host if sl else 1,
            'num_slices': sl.num_slices if sl else 1,
            'epilogue_cmds': epilogue,
            # The control-plane trace AND span parent cross to the
            # cluster inside the spec (env does not survive the
            # ssh/detach boundary); the driver re-exports both into
            # every rank via gang_env, so on-cluster spans nest under
            # the launching request's tree in /v1/traces.
            'trace_id': trace_lib.get(),
            'parent_span_id': spans_lib.current(),
        }
        from skypilot_tpu.utils import docker_utils
        docker_image = docker_utils.docker_image_of(launched.image_id)
        if docker_image and cluster_info.provider_name != 'kubernetes':
            # k8s excepted: there the pod image IS the task image.
            spec['docker'] = {'image': docker_image,
                              'cmd': docker_utils.docker_cmd()}

        # 3. Ship the spec to the head host and start the driver detached.
        head = self._head_runner(cluster_info)
        spec_b64 = base64.b64encode(json.dumps(spec).encode()).decode()
        remote_spec = f'/tmp/skytpu_job_{handle.cluster_name}_{job_id}.json'
        write_cmd = f'echo {spec_b64} | base64 -d > {remote_spec}'
        rc = head.run(write_cmd, log_path='/dev/null')
        if rc != 0:
            raise exceptions.CommandError(rc, 'ship job spec', '')
        driver_cmd = (f'{py} -m skypilot_tpu.skylet.slice_driver '
                      f'--spec {remote_spec}')
        head.run(driver_cmd, detach=True,
                 log_path=os.path.expanduser(
                     f'~/.skytpu/logs/{handle.cluster_name}/'
                     f'driver_{job_id}.log'))
        logger.info(f'Job {job_id} submitted on {handle.cluster_name!r} '
                    f'({len(hosts)} host(s), gang-scheduled).')
        if not detach_run:
            self.tail_logs(handle, job_id, follow=True)
        return job_id

    # ------------------------------------------------------------------
    # Logs / queue / cancel
    # ------------------------------------------------------------------
    def tail_logs(self, handle: SliceResourceHandle, job_id: Optional[int],
                  follow: bool = True) -> int:
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        head = self._head_runner(cluster_info)
        if job_id is None:
            jobs = self.queue(handle)
            if not jobs:
                logger.info('No jobs on this cluster.')
                return 0
            job_id = jobs[0]['job_id']
        cmd = (f'{py} -m skypilot_tpu.skylet.log_lib --job-id {job_id}'
               f'{" --follow" if follow else ""}')
        rc = head.run(cmd, stream_logs=True, log_path='/dev/null')
        return int(rc)

    def capture_logs(self, handle: SliceResourceHandle, job_id: int,
                     lines: int = 200) -> str:
        """Non-follow log fetch returning the tail as a STRING (the
        dashboard's poll-based live tail; `tail_logs` streams to the
        caller's stdout instead). Only the tail crosses the wire
        (log_lib --tail). rc 100 is log_lib's job-STATUS convention
        (non-SUCCEEDED job), not a fetch failure — a live tail of a
        RUNNING or FAILED job is the whole point."""
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        head = self._head_runner(cluster_info)
        rc, out, err = head.run(
            f'{py} -m skypilot_tpu.skylet.log_lib '
            f'--job-id {int(job_id)} --tail {int(lines)}',
            require_outputs=True)
        if rc not in (0, 100):
            raise RuntimeError(f'log fetch failed (rc={rc}): '
                               f'{(err or out)[-500:]}')
        return out

    def queue(self, handle: SliceResourceHandle) -> List[Dict[str, Any]]:
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        out = self._run_on_head_json(
            cluster_info, f'{py} -m skypilot_tpu.skylet.job_lib list')
        return out.get('jobs', [])

    def cancel_jobs(self, handle: SliceResourceHandle,
                    job_ids: Optional[List[int]] = None) -> List[int]:
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        if job_ids is None:
            jobs = self.queue(handle)
            job_ids = [
                j['job_id'] for j in jobs
                if not JobStatus(j['status']).is_terminal()
            ]
        cancelled = []
        for jid in job_ids:
            out = self._run_on_head_json(
                cluster_info,
                f'{py} -m skypilot_tpu.skylet.job_lib cancel --job-id {jid}')
            if out.get('cancelled'):
                cancelled.append(jid)
        return cancelled

    def job_status(self, handle: SliceResourceHandle,
                   job_id: int) -> Optional[JobStatus]:
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        out = self._run_on_head_json(
            cluster_info,
            f'{py} -m skypilot_tpu.skylet.job_lib status --job-id {job_id}')
        return JobStatus(out['status']) if out.get('status') else None

    # ------------------------------------------------------------------
    # Autostop / teardown
    # ------------------------------------------------------------------
    def set_autostop(self, handle: SliceResourceHandle,
                     idle_minutes: Optional[int], down: bool) -> None:
        cluster_info = handle.get_cluster_info()
        py = self._remote_py(cluster_info)
        import shlex
        code = (
            'from skypilot_tpu.skylet import autostop_lib; '
            f'autostop_lib.set_autostop({idle_minutes!r}, {down!r}, '
            f'{handle.cloud!r}, {handle.region!r}, '
            f'{handle.cluster_name!r}, {handle.provider_config!r})')
        head = self._head_runner(cluster_info)
        rc = head.run(f'{py} -c {shlex.quote(code)}', log_path='/dev/null')
        if rc != 0:
            raise exceptions.ClusterSetupError(
                f'Failed to set autostop on {handle.cluster_name}.')
        global_state.set_cluster_autostop(
            handle.cluster_name,
            None if idle_minutes is None else {'idle_minutes': idle_minutes,
                                               'down': down})

    @timeline.event
    def teardown(self, handle: SliceResourceHandle,
                 terminate: bool = False) -> None:
        with locks.cluster_status_lock(handle.cluster_name, timeout=600):
            provisioner_lib.teardown_cluster(
                handle.cloud, handle.region, handle.cluster_name,
                handle.provider_config, terminate=terminate)
            if terminate:
                global_state.remove_cluster(handle.cluster_name)
            else:
                global_state.set_cluster_status(handle.cluster_name,
                                                ClusterStatus.STOPPED)
        logger.info(f'Cluster {handle.cluster_name!r} '
                    f'{"terminated" if terminate else "stopped"}.')
