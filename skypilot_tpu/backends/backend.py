"""Abstract Backend: cluster lifecycle + job execution API.

Reference analog: sky/backends/backend.py:48-162 (provision / sync_workdir /
sync_file_mounts / setup / execute / teardown).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Generic, Optional, TypeVar

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

_HandleType = TypeVar('_HandleType')


class Backend(Generic[_HandleType]):
    NAME = 'backend'

    # --- Cluster lifecycle -------------------------------------------------
    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool,
                  cluster_name: str,
                  retry_until_up: bool = False) -> Optional[_HandleType]:
        raise NotImplementedError

    def sync_workdir(self, handle: _HandleType, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: _HandleType,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def setup(self, handle: _HandleType, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        raise NotImplementedError

    # --- Job execution -----------------------------------------------------
    def execute(self, handle: _HandleType, task: 'task_lib.Task',
                detach_run: bool = False) -> Optional[int]:
        """Submit the task; returns job id (None for dryrun)."""
        raise NotImplementedError

    def tail_logs(self, handle: _HandleType, job_id: Optional[int],
                  follow: bool = True) -> int:
        raise NotImplementedError

    # --- Teardown ----------------------------------------------------------
    def teardown(self, handle: _HandleType, terminate: bool = False) -> None:
        raise NotImplementedError

    def post_execute(self, handle: _HandleType, down: bool) -> None:
        del handle, down

    def register_info(self, **kwargs) -> None:
        """Optimizer → backend info channel (analog backend.py register_info)."""
        del kwargs
