from skypilot_tpu.backends.backend import Backend  # noqa: F401
from skypilot_tpu.backends.slice_backend import (  # noqa: F401
    SliceResourceHandle,
    TpuSliceBackend,
)
