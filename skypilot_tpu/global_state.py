"""Control-plane state DB: clusters, handles, launch history.

Reference analog: sky/global_user_state.py (SQLAlchemy sqlite with pickled
cluster handles, tables at :72-93). Plain sqlite3 here (no SQLAlchemy in the
image); handles are JSON, not pickles, so the DB is inspectable and
version-tolerant.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils
from skypilot_tpu.utils.status_lib import ClusterStatus

logger = sky_logging.init_logger(__name__)

_DB_PATH_ENV = 'SKYTPU_STATE_DB'
_local = threading.local()


def _db_path() -> str:
    path = knobs.get_str(_DB_PATH_ENV)
    path = os.path.expanduser(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    # One connection per thread; sqlite locks handle cross-process safety.
    conn = getattr(_local, 'conn', None)
    if conn is None or getattr(_local, 'path', None) != _db_path():
        conn = sqlite_utils.connect_wal(_db_path())
        _create_tables(conn)
        _local.conn = conn
        _local.path = _db_path()
    return conn


def _create_tables(conn: sqlite3.Connection) -> None:
    conn.execute("""
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at REAL,
            handle TEXT,
            last_use TEXT,
            status TEXT,
            autostop TEXT,
            owner TEXT,
            launch_cost REAL DEFAULT 0.0,
            workspace TEXT
        )""")
    try:
        conn.execute('ALTER TABLE clusters ADD COLUMN workspace TEXT')
    except sqlite3.OperationalError:
        pass   # pre-workspace DBs
    conn.execute("""
        CREATE TABLE IF NOT EXISTS cluster_history (
            row_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            launched_at REAL,
            duration_seconds REAL,
            resources TEXT,
            cost REAL,
            user TEXT
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            launched_at REAL,
            handle TEXT,
            status TEXT
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS volumes (
            name TEXT PRIMARY KEY,
            created_at REAL,
            handle TEXT,
            status TEXT
        )""")
    conn.commit()


# ---------------------------------------------------------------------------
# Clusters
# ---------------------------------------------------------------------------
def add_or_update_cluster(cluster_name: str,
                          handle: Dict[str, Any],
                          status: ClusterStatus,
                          is_launch: bool = False) -> None:
    conn = _conn()
    now = time.time()
    existing = get_cluster(cluster_name)
    launched_at = (now if is_launch or existing is None
                   else existing['launched_at'])
    from skypilot_tpu import workspaces
    conn.execute(
        'INSERT INTO clusters (name, launched_at, handle, last_use, status, '
        'owner, workspace) VALUES (?, ?, ?, ?, ?, ?, ?) '
        'ON CONFLICT(name) DO UPDATE SET handle=excluded.handle, '
        'status=excluded.status, last_use=excluded.last_use, '
        'launched_at=excluded.launched_at',
        (cluster_name, launched_at, json.dumps(handle),
         common_utils.get_user(), status.value, common_utils.get_user_hash(),
         workspaces.get_active_workspace()))
    conn.commit()


def set_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    conn = _conn()
    conn.execute('UPDATE clusters SET status = ? WHERE name = ?',
                 (status.value, cluster_name))
    conn.commit()


def set_cluster_autostop(cluster_name: str,
                         autostop: Optional[Dict[str, Any]]) -> None:
    conn = _conn()
    conn.execute('UPDATE clusters SET autostop = ? WHERE name = ?',
                 (json.dumps(autostop) if autostop else None, cluster_name))
    conn.commit()


def get_cluster(cluster_name: str) -> Optional[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    row = conn.execute('SELECT * FROM clusters WHERE name = ?',
                       (cluster_name,)).fetchone()
    conn.row_factory = None
    return _cluster_row_to_dict(row) if row else None


def get_clusters() -> List[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    rows = conn.execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    conn.row_factory = None
    return [_cluster_row_to_dict(r) for r in rows]


def _cluster_row_to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['handle'] = json.loads(d['handle']) if d.get('handle') else None
    d['status'] = ClusterStatus(d['status'])
    if d.get('autostop'):
        d['autostop'] = json.loads(d['autostop'])
    return d


def remove_cluster(cluster_name: str) -> None:
    cluster = get_cluster(cluster_name)
    conn = _conn()
    if cluster is not None:
        duration = time.time() - (cluster['launched_at'] or time.time())
        handle = cluster.get('handle') or {}
        conn.execute(
            'INSERT INTO cluster_history (name, launched_at, '
            'duration_seconds, resources, cost, user) '
            'VALUES (?, ?, ?, ?, ?, ?)',
            (cluster_name, cluster['launched_at'], duration,
             json.dumps(handle.get('launched_resources')),
             _estimate_cost(handle, duration), cluster.get('last_use')))
    conn.execute('DELETE FROM clusters WHERE name = ?', (cluster_name,))
    conn.commit()


def _estimate_cost(handle: Dict[str, Any], duration_seconds: float) -> float:
    res_cfg = (handle or {}).get('launched_resources')
    if not res_cfg:
        return 0.0
    try:
        from skypilot_tpu import resources as resources_lib
        res = resources_lib.Resources.from_yaml_config(res_cfg)
        if isinstance(res, resources_lib.Resources):
            return res.get_cost(duration_seconds)
    except Exception as e:  # pylint: disable=broad-except
        # Cost is best-effort display data, but a silent 0.0 makes the
        # cost report quietly wrong — leave a trace.
        logger.debug(f'cost estimate failed for {res_cfg!r}: {e}')
    return 0.0


def get_cost_report() -> List[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    rows = conn.execute('SELECT * FROM cluster_history '
                        'ORDER BY launched_at DESC').fetchall()
    conn.row_factory = None
    out = []
    for r in rows:
        d = dict(r)
        if d.get('resources'):
            d['resources'] = json.loads(d['resources'])
        out.append(d)
    return out


# ---------------------------------------------------------------------------
# Storage
# ---------------------------------------------------------------------------
def add_or_update_storage(name: str, handle: Dict[str, Any],
                          status: str) -> None:
    conn = _conn()
    conn.execute(
        'INSERT INTO storage (name, launched_at, handle, status) '
        'VALUES (?, ?, ?, ?) ON CONFLICT(name) DO UPDATE SET '
        'handle=excluded.handle, status=excluded.status',
        (name, time.time(), json.dumps(handle), status))
    conn.commit()


def get_storage(name: str) -> Optional[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    row = conn.execute('SELECT * FROM storage WHERE name = ?',
                       (name,)).fetchone()
    conn.row_factory = None
    if row is None:
        return None
    d = dict(row)
    d['handle'] = json.loads(d['handle']) if d.get('handle') else None
    return d


def get_storages() -> List[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    rows = conn.execute('SELECT * FROM storage').fetchall()
    conn.row_factory = None
    out = []
    for r in rows:
        d = dict(r)
        d['handle'] = json.loads(d['handle']) if d.get('handle') else None
        out.append(d)
    return out


def remove_storage(name: str) -> None:
    conn = _conn()
    conn.execute('DELETE FROM storage WHERE name = ?', (name,))
    conn.commit()


# ---------------------------------------------------------------------------
# Volumes
# ---------------------------------------------------------------------------
def add_or_update_volume(name: str, handle: Dict[str, Any],
                         status: str) -> None:
    conn = _conn()
    conn.execute(
        'INSERT INTO volumes (name, created_at, handle, status) '
        'VALUES (?, ?, ?, ?) ON CONFLICT(name) DO UPDATE SET '
        'handle=excluded.handle, status=excluded.status',
        (name, time.time(), json.dumps(handle), status))
    conn.commit()


def get_volume(name: str) -> Optional[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    row = conn.execute('SELECT * FROM volumes WHERE name = ?',
                       (name,)).fetchone()
    conn.row_factory = None
    if row is None:
        return None
    d = dict(row)
    d['handle'] = json.loads(d['handle']) if d.get('handle') else None
    return d


def get_volumes() -> List[Dict[str, Any]]:
    conn = _conn()
    conn.row_factory = sqlite3.Row
    rows = conn.execute('SELECT * FROM volumes ORDER BY created_at').fetchall()
    conn.row_factory = None
    out = []
    for r in rows:
        d = dict(r)
        d['handle'] = json.loads(d['handle']) if d.get('handle') else None
        out.append(d)
    return out


def remove_volume(name: str) -> None:
    conn = _conn()
    conn.execute('DELETE FROM volumes WHERE name = ?', (name,))
    conn.commit()
