"""Task life-cycle driver: OPTIMIZE → PROVISION → SYNC → SETUP → EXEC → DOWN.

Reference analog: sky/execution.py (`Stage:40`, `_execute:104`,
`_execute_dag:231`, `launch:529`, `exec:726`).
"""
from __future__ import annotations

import enum
import typing
from typing import List, Optional, Tuple

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils.status_lib import ClusterStatus

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

logger = sky_logging.init_logger(__name__)


class Stage(enum.Enum):
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _as_dag(entrypoint) -> dag_lib.Dag:
    if isinstance(entrypoint, dag_lib.Dag):
        return entrypoint
    assert isinstance(entrypoint, task_lib.Task), entrypoint
    dag = dag_lib.Dag()
    dag.add(entrypoint)
    return dag


def _execute(
    task: task_lib.Task,
    *,
    cluster_name: str,
    stages: List[Stage],
    dryrun: bool = False,
    detach_run: bool = False,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    down: bool = False,
    retry_until_up: bool = False,
    blocked_resources: Optional[List['resources_lib.Resources']] = None,
) -> Tuple[Optional[int], Optional[slice_backend.SliceResourceHandle]]:
    """Run the requested stages for a single task. Returns (job_id, handle)."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.observe import spans
    from skypilot_tpu.observe import trace

    def _run():
        with config_lib.override(task.config_overrides):
            return _execute_inner(
                task, cluster_name=cluster_name, stages=stages, dryrun=dryrun,
                detach_run=detach_run, optimize_target=optimize_target,
                down=down, retry_until_up=retry_until_up,
                blocked_resources=blocked_resources)

    if trace.get() is not None:
        # Server mode (or a controller): the API ingress already minted
        # the trace and the executor opened the root span.
        return _run()
    # Client-side ingress: the CLI/SDK called straight into the library
    # (hermetic local mode) — without a root minted here, every
    # optimize/provision/setup span lands traceless and orphaned.
    with trace.trace_context():
        with spans.span('client.execute', attrs={'cluster': cluster_name}):
            return _run()


def _execute_inner(
    task: task_lib.Task,
    *,
    cluster_name: str,
    stages: List[Stage],
    dryrun: bool,
    detach_run: bool,
    optimize_target: optimizer_lib.OptimizeTarget,
    down: bool,
    retry_until_up: bool,
    blocked_resources: Optional[List['resources_lib.Resources']] = None,
) -> Tuple[Optional[int], Optional[slice_backend.SliceResourceHandle]]:
    backend = slice_backend.TpuSliceBackend()

    if Stage.OPTIMIZE in stages:
        dag = _as_dag(task)
        optimizer_lib.Optimizer.optimize(dag, minimize=optimize_target,
                                         blocked_resources=blocked_resources,
                                         quiet=dryrun)

    to_provision = task.best_resources
    if to_provision is None:
        res_list = task.resources_list()
        if res_list and res_list[0].is_launchable():
            to_provision = res_list[0]
        else:
            raise exceptions.ResourcesUnavailableError(
                'Task has no launchable resources; run with OPTIMIZE or '
                'pass a concrete cloud + TPU slice.')

    handle: Optional[slice_backend.SliceResourceHandle] = None
    if Stage.PROVISION in stages:
        # Fail fast on features the chosen cloud cannot deliver (e.g.
        # autostop on a TPU generation without stop support).
        assert to_provision.cloud is not None
        type(to_provision.cloud).check_features_are_supported(
            to_provision, to_provision.get_required_cloud_features())
        handle = backend.provision(task, to_provision, dryrun=dryrun,
                                   cluster_name=cluster_name,
                                   retry_until_up=retry_until_up)
    if dryrun or handle is None:
        logger.info('Dryrun complete.')
        return None, None

    if Stage.SYNC_WORKDIR in stages and task.workdir is not None:
        task.validate_workdir()
        backend.sync_workdir(handle, task.workdir)
    if Stage.SYNC_FILE_MOUNTS in stages and (task.file_mounts or
                                             task.storage_mounts):
        backend.sync_file_mounts(handle, task.file_mounts,
                                 task.storage_mounts)
    if Stage.SETUP in stages:
        backend.setup(handle, task)

    job_id: Optional[int] = None
    if Stage.EXEC in stages:
        job_id = backend.execute(handle, task, detach_run=detach_run)

    if Stage.DOWN in stages and down:
        if detach_run and job_id is not None:
            # The job is still running — tearing down now would kill it.
            # Arm autostop-down instead: the skylet terminates the slice
            # once the job queue drains (reference: `--down` rides autostop).
            backend.set_autostop(handle, 0, down=True)
        else:
            backend.teardown(handle, terminate=True)
    return job_id, handle


from skypilot_tpu.usage import usage_lib


@usage_lib.tracked('launch')
def launch(
    entrypoint,
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    detach_run: bool = False,
    down: bool = False,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    retry_until_up: bool = False,
    no_setup: bool = False,
    blocked_resources: Optional[List['resources_lib.Resources']] = None,
) -> Tuple[Optional[int], Optional[slice_backend.SliceResourceHandle]]:
    """Provision (or reuse) a cluster and run the task on it.

    `blocked_resources` excludes placements from the optimizer's choice —
    the managed-jobs eager-failover strategy uses it to avoid the region
    that just preempted the job.

    Reference analog: sky/execution.py:529.
    """
    dag = _as_dag(entrypoint)
    if len(dag.tasks) != 1:
        raise NotImplementedError(
            'Multi-task DAG launch goes through the managed-jobs plane '
            '(skytpu jobs launch); `launch` takes a single task.')
    task = dag.tasks[0]
    from skypilot_tpu import admin_policy
    task = admin_policy.apply(task, 'launch', cluster_name=cluster_name,
                              dryrun=dryrun)
    if task.service_spec is not None:
        # A `service:` section means replicas/autoscaling/LB — silently
        # launching one bare cluster would ignore all of it.
        raise ValueError(
            "Task has a 'service:' section; use `skytpu serve up` "
            "(skypilot_tpu.serve.up) to deploy it, or remove the section "
            "to launch it as a plain cluster.")
    if cluster_name is None:
        cluster_name = common_utils.generate_cluster_name()
    common_utils.check_cluster_name_is_valid(cluster_name)
    stages = [
        Stage.OPTIMIZE, Stage.PROVISION, Stage.SYNC_WORKDIR,
        Stage.SYNC_FILE_MOUNTS, Stage.SETUP, Stage.EXEC, Stage.DOWN,
    ]
    if no_setup:
        stages.remove(Stage.SETUP)
    return _execute(task, cluster_name=cluster_name, stages=stages,
                    dryrun=dryrun, detach_run=detach_run,
                    optimize_target=optimize_target, down=down,
                    retry_until_up=retry_until_up,
                    blocked_resources=blocked_resources)


def exec(  # pylint: disable=redefined-builtin
    entrypoint,
    cluster_name: str,
    *,
    detach_run: bool = False,
    dryrun: bool = False,
) -> Tuple[Optional[int], Optional[slice_backend.SliceResourceHandle]]:
    """Run a task on an existing cluster, skipping provision/setup.

    Reference analog: sky/execution.py:726.
    """
    dag = _as_dag(entrypoint)
    assert len(dag.tasks) == 1
    task = dag.tasks[0]
    from skypilot_tpu import admin_policy
    task = admin_policy.apply(task, 'exec', cluster_name=cluster_name,
                              dryrun=dryrun)
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist; use launch.')
    if record['status'] != ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}.')
    handle = slice_backend.SliceResourceHandle.from_dict(record['handle'])
    launched = handle.launched_resources_obj()
    for want in task.resources_list():
        if not want.less_demanding_than(launched):
            raise exceptions.ResourcesMismatchError(
                f'Task requires {want.format_brief()}, but cluster has '
                f'{launched.format_brief()}.')
    task.best_resources = launched
    backend = slice_backend.TpuSliceBackend()
    if dryrun:
        logger.info(f'Dryrun: would exec on {cluster_name!r}.')
        return None, handle
    if task.workdir is not None:
        task.validate_workdir()
        backend.sync_workdir(handle, task.workdir)
    job_id = backend.execute(handle, task, detach_run=detach_run)
    return job_id, handle
