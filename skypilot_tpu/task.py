"""Declarative Task: resources, setup/run, envs, mounts, YAML round-trip.

Reference analog: sky/task.py (`Task:226`, `from_yaml_config:527`,
`set_resources:1128`). The YAML surface keeps the reference's field names
(`resources`, `num_nodes`, `setup`, `run`, `envs`, `secrets`, `workdir`,
`file_mounts`, `config`) so reference task YAMLs parse unchanged; `num_nodes`
is optional for TPU tasks because the slice shape already fixes the host
fan-out (a mismatch is an error, not silently ignored).
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

_VALID_NAME_REGEX = re.compile(r'^[a-zA-Z0-9]+(?:[._-]{1,2}[a-zA-Z0-9]+)*$')
_VALID_ENV_VAR = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*$')

ResourcesSpec = Union[resources_lib.Resources,
                      List[resources_lib.Resources],
                      Set[resources_lib.Resources]]

_RunFn = Callable[[int, List[str]], Optional[str]]


def _fill_in_env_vars(yaml_field: Any, task_envs: Dict[str, str]) -> Any:
    """Substitute `$VAR`/`${VAR}` with task env values inside a YAML field.

    Reference analog: sky/task.py:68 — applied to `file_mounts`, `service`
    and `workdir` so recipes can parameterize bucket names, probe payloads
    and paths by env (e.g. llm/llama-3_1-finetuning/lora.yaml's
    `name: $CHECKPOINT_BUCKET_NAME`). Only vars present in `task_envs` are
    substituted; anything else is left for the remote shell. Substitution
    walks the parsed structure string-by-string (never a serialized blob)
    so env values containing quotes/backslashes can't corrupt anything."""
    if not task_envs or yaml_field is None:
        return yaml_field

    def _sub(s: str) -> str:
        for name, value in task_envs.items():
            if value is None:
                continue
            text = str(value)
            s = s.replace('${' + name + '}', text)
            # Replacement via lambda: a literal value, never a re template
            # (a value like 'C:\temp' must not be parsed for escapes).
            s = re.sub(r'\$' + re.escape(name) + r'\b', lambda _m: text, s)
        return s

    def _walk(x: Any) -> Any:
        if isinstance(x, str):
            return _sub(x)
        if isinstance(x, dict):
            return {_walk(k): _walk(v) for k, v in x.items()}
        if isinstance(x, list):
            return [_walk(v) for v in x]
        return x

    return _walk(yaml_field)


class Task:
    """A coarse-grained stage of computation on one TPU slice (or CPU node)."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: Optional[Union[str, _RunFn]] = None,
        envs: Optional[Dict[str, str]] = None,
        secrets: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
    ):
        self.name = name
        if name is not None and not _VALID_NAME_REGEX.fullmatch(name):
            raise ValueError(f'Invalid task name {name!r}.')
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = dict(envs or {})
        self._secrets = dict(secrets or {})
        for key in list(self._envs) + list(self._secrets):
            if not _VALID_ENV_VAR.fullmatch(key):
                raise ValueError(f'Invalid env var name {key!r}.')
        self._num_nodes = num_nodes
        self.resources: ResourcesSpec = resources_lib.Resources()
        self.file_mounts: Dict[str, str] = {}
        self.storage_mounts: Dict[str, Any] = {}
        # Per-task config overrides ('config:' section).
        self.config_overrides: Dict[str, Any] = {}
        self.service_spec: Optional[Dict[str, Any]] = None
        self.best_resources: Optional[resources_lib.Resources] = None
        # Optimizer time/egress model inputs (YAML `estimated:` section):
        #   duration_seconds — wall-clock guess for TIME optimization;
        #   total_flops — model FLOPs, converted to time per candidate slice;
        #   output_gb — data shipped to children (egress cost on DAG edges).
        self.estimated_duration_seconds: Optional[float] = None
        self.estimated_total_flops: Optional[float] = None
        self.estimated_output_gb: float = 0.0

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None
                         ) -> 'Task':
        config = dict(config or {})
        # Shape validation first: dotted-path type errors beat tracebacks
        # from half-built objects (utils/schemas.py).
        from skypilot_tpu.utils import schemas
        schemas.validate_task_config(config)
        envs = dict(config.get('envs') or {})
        if env_overrides:
            envs.update(env_overrides)
        # ${VAR} substitution in setup/run using envs, like the reference's
        # env interpolation.
        task = cls(
            name=config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            envs=envs,
            secrets=dict(config.get('secrets') or {}),
            workdir=_fill_in_env_vars(config.get('workdir'), envs),
            num_nodes=config.get('num_nodes'),
        )
        task.set_resources(
            resources_lib.Resources.from_yaml_config(config.get('resources')))
        file_mounts = _fill_in_env_vars(config.get('file_mounts') or {}, envs)
        plain_mounts: Dict[str, str] = {}
        for dst, src in file_mounts.items():
            if isinstance(src, dict):
                # storage mount spec: {name:, source:, mode:, store:}
                task.storage_mounts[dst] = src
            else:
                plain_mounts[dst] = src
        if plain_mounts:
            task.set_file_mounts(plain_mounts)
        task.config_overrides = dict(config.get('config') or {})
        task.service_spec = _fill_in_env_vars(config.get('service'), envs)
        pool_cfg = config.get('pool')
        if pool_cfg is not None:
            # `pool:` is sugar for a pool-mode service spec (reference:
            # sky/serve/service_spec.py:182-190 — pools and services share
            # one spec). `workers: N` is the only knob plus spot_placer.
            if task.service_spec is not None:
                raise ValueError("Use either 'service:' or 'pool:', "
                                 'not both.')
            task.service_spec = {'pool': True, **dict(pool_cfg)}
        # Shape/unknown-key checks already ran in validate_task_config.
        est = config.get('estimated') or {}
        if est.get('duration_seconds') is not None:
            task.estimated_duration_seconds = float(est['duration_seconds'])
        if est.get('total_flops') is not None:
            task.estimated_total_flops = float(est['total_flops'])
        task.estimated_output_gb = float(est.get('output_gb') or 0.0)
        task.validate()
        return task

    @classmethod
    def from_yaml(cls, yaml_path: str,
                  env_overrides: Optional[Dict[str, str]] = None) -> 'Task':
        config = common_utils.read_yaml(os.path.expanduser(yaml_path))
        if not isinstance(config, dict):
            raise ValueError(f'{yaml_path} is not a YAML mapping.')
        return cls.from_yaml_config(config, env_overrides)

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        if self.name:
            cfg['name'] = self.name
        res = self.resources
        if isinstance(res, resources_lib.Resources):
            cfg['resources'] = res.to_yaml_config()
        elif isinstance(res, list):
            cfg['resources'] = {'ordered': [r.to_yaml_config() for r in res]}
        else:
            cfg['resources'] = {'any_of': [r.to_yaml_config() for r in res]}
        if self._num_nodes is not None:
            cfg['num_nodes'] = self._num_nodes
        if self.workdir is not None:
            cfg['workdir'] = self.workdir
        if self.setup is not None:
            cfg['setup'] = self.setup
        if isinstance(self.run, str):
            cfg['run'] = self.run
        if self._envs:
            cfg['envs'] = dict(self._envs)
        if self._secrets:
            cfg['secrets'] = dict(self._secrets)
        mounts: Dict[str, Any] = dict(self.file_mounts)
        mounts.update(self.storage_mounts)
        if mounts:
            cfg['file_mounts'] = mounts
        if self.config_overrides:
            cfg['config'] = dict(self.config_overrides)
        if self.service_spec:
            cfg['service'] = dict(self.service_spec)
        est: Dict[str, Any] = {}
        if self.estimated_duration_seconds is not None:
            est['duration_seconds'] = self.estimated_duration_seconds
        if self.estimated_total_flops is not None:
            est['total_flops'] = self.estimated_total_flops
        if self.estimated_output_gb:
            est['output_gb'] = self.estimated_output_gb
        if est:
            cfg['estimated'] = est
        return cfg

    # ------------------------------------------------------------------
    # Setters (builder style, like the reference)
    # ------------------------------------------------------------------
    def set_resources(self, resources: ResourcesSpec) -> 'Task':
        self.resources = resources
        return self

    def set_resources_override(self, override: Dict[str, Any]) -> 'Task':
        res = self.resources
        if isinstance(res, resources_lib.Resources):
            self.resources = res.copy(**override)
        elif isinstance(res, list):
            self.resources = [r.copy(**override) for r in res]
        else:
            self.resources = {r.copy(**override) for r in res}
        return self

    def set_file_mounts(self, file_mounts: Optional[Dict[str, str]]) -> 'Task':
        if file_mounts is None:
            self.file_mounts = {}
            return self
        for dst, src in file_mounts.items():
            if src.startswith(('gs://', 's3://', 'r2://')):
                self.storage_mounts[dst] = {'source': src, 'mode': 'COPY'}
            else:
                self.file_mounts[dst] = src
        return self

    def update_envs(self, envs: Dict[str, str]) -> 'Task':
        self._envs.update(envs)
        return self

    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    @property
    def secrets(self) -> Dict[str, str]:
        return dict(self._secrets)

    @property
    def envs_and_secrets(self) -> Dict[str, str]:
        out = dict(self._envs)
        out.update(self._secrets)
        return out

    # ------------------------------------------------------------------
    # Node/host accounting
    # ------------------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        """Host count: from the TPU slice if concrete, else num_nodes field."""
        res = self._any_resources()
        if res is not None and res.tpu is not None:
            return res.tpu.total_hosts
        return self._num_nodes or 1

    def _any_resources(self) -> Optional[resources_lib.Resources]:
        res = self.resources
        if isinstance(res, resources_lib.Resources):
            return res
        for r in res:
            return r
        return None

    def resources_list(self) -> List[resources_lib.Resources]:
        res = self.resources
        if isinstance(res, resources_lib.Resources):
            return [res]
        return list(res)

    # ------------------------------------------------------------------
    # Validation
    # ------------------------------------------------------------------
    def validate(self) -> None:
        # workdir existence is deliberately NOT checked here: parsing a
        # task YAML from outside its repo (e.g. reading a recipe file)
        # must succeed; the check runs at launch, right before the sync
        # would fail anyway (reference parses the same way).
        self.validate_run()
        self._validate_num_nodes()

    def validate_run(self) -> None:
        if self.run is not None and not isinstance(self.run, str) and not callable(self.run):
            raise ValueError('run must be a shell string or a callable.')

    def validate_workdir(self) -> None:
        if self.workdir is None:
            return
        workdir = os.path.expanduser(self.workdir)
        if not os.path.isdir(workdir):
            raise ValueError(f'workdir {self.workdir!r} is not a directory.')

    def _validate_num_nodes(self) -> None:
        if self._num_nodes is None:
            return
        if self._num_nodes < 1:
            raise ValueError(f'num_nodes must be >= 1, got {self._num_nodes}')
        for res in self.resources_list():
            if res.tpu is not None and res.tpu.total_hosts != self._num_nodes:
                raise exceptions.ResourcesMismatchError(
                    f'num_nodes={self._num_nodes} conflicts with '
                    f'{res.tpu.name}, which spans {res.tpu.total_hosts} '
                    f'host(s). Drop num_nodes — the slice shape determines '
                    f'the host fan-out.')

    def __repr__(self) -> str:
        label = self.name or 'unnamed'
        res = self.resources_list()
        res_str = res[0].format_brief() if res else '?'
        if len(res) > 1:
            res_str += f' (+{len(res) - 1} candidates)'
        return f'Task({label}, {res_str}, nodes={self.num_nodes})'
