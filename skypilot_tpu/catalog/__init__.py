"""Catalog: TPU slice offerings, pricing, regions/zones.

Reference analog: sky/catalog/ (common.py CSV cache + gcp_catalog.py TPU
entries). The reference fetches hosted CSVs at runtime
(sky/catalog/common.py:211); we ship a static CSV in-package (zero egress)
with the same query surface.
"""
from skypilot_tpu.catalog.tpu_catalog import (  # noqa: F401
    list_accelerators,
    get_hourly_cost,
    get_regions,
    get_zones,
    validate_region_zone,
    get_host_vm_spec,
    accelerator_in_region_or_zone,
    HostVmSpec,
    InstanceTypeInfo,
)
