"""TPU catalog queries over the in-package static CSV.

Reference analogs:
- sky/catalog/common.py (CSV load/caching, per-cloud lazy load)
- sky/catalog/gcp_catalog.py:255-277 (TPU-VM price = TPU chip price only; the
  host VM is free for TPU-VM architecture — same policy here)
- sky/catalog/gcp_catalog.py:476-556 (TPU/GPU dataframe split; we are TPU-only)

Pricing data is approximate public GCP on-demand/spot per-chip-hour pricing;
the CSV is the single source of truth and trivially replaceable.
"""
from __future__ import annotations

import csv
import dataclasses
import functools
import os
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.tpu import topology

_CSV_PATH = os.path.join(os.path.dirname(__file__), 'data', 'tpu_catalog.csv')


@dataclasses.dataclass(frozen=True)
class CatalogRow:
    generation: str
    region: str
    zone: str
    price_per_chip_hour: float
    spot_price_per_chip_hour: float
    max_chips: int


@dataclasses.dataclass(frozen=True)
class HostVmSpec:
    """The host VM shape bundled with each TPU host (not separately billed).

    Reference analog: sky/clouds/gcp.py:739-768 TPU host vCPU/mem fixups.
    """
    vcpus: int
    memory_gb: int


# Approximate public TPU-VM host shapes per generation.
_HOST_VMS: Dict[str, HostVmSpec] = {
    'v2': HostVmSpec(96, 335),
    'v3': HostVmSpec(96, 335),
    'v4': HostVmSpec(240, 407),
    'v5e': HostVmSpec(224, 400),
    'v5p': HostVmSpec(208, 448),
    'v6e': HostVmSpec(180, 720),
}


@dataclasses.dataclass(frozen=True)
class InstanceTypeInfo:
    """One catalog offering: a slice shape in a zone with pricing."""
    accelerator_name: str
    generation: str
    num_chips: int
    topology: str
    num_hosts: int
    region: str
    zone: str
    price: float          # $/hour for the whole slice, on-demand
    spot_price: float


@functools.lru_cache(maxsize=1)
def _load_rows(csv_path: str = _CSV_PATH) -> List[CatalogRow]:
    rows: List[CatalogRow] = []
    with open(csv_path, 'r', encoding='utf-8') as f:
        for rec in csv.DictReader(f):
            rows.append(
                CatalogRow(
                    generation=rec['generation'],
                    region=rec['region'],
                    zone=rec['zone'],
                    price_per_chip_hour=float(rec['price_per_chip_hour']),
                    spot_price_per_chip_hour=float(
                        rec['spot_price_per_chip_hour']),
                    max_chips=int(rec['max_chips']),
                ))
    return rows


def _rows_for(generation: str,
              region: Optional[str] = None,
              zone: Optional[str] = None) -> List[CatalogRow]:
    out = []
    for row in _load_rows():
        if row.generation != generation:
            continue
        if region is not None and row.region != region:
            continue
        if zone is not None and row.zone != zone:
            continue
        out.append(row)
    return out


def get_regions(tpu_slice: topology.TpuSlice) -> List[str]:
    """Regions offering this slice shape (capacity-aware), cheapest first."""
    rows = [r for r in _rows_for(tpu_slice.generation)
            if r.max_chips >= tpu_slice.total_chips]
    seen: Dict[str, float] = {}
    for r in rows:
        seen.setdefault(r.region, r.price_per_chip_hour)
    return sorted(seen, key=lambda reg: seen[reg])


def get_zones(tpu_slice: topology.TpuSlice, region: str) -> List[str]:
    return [r.zone for r in _rows_for(tpu_slice.generation, region=region)
            if r.max_chips >= tpu_slice.total_chips]


def accelerator_in_region_or_zone(tpu_slice: topology.TpuSlice,
                                  region: Optional[str] = None,
                                  zone: Optional[str] = None) -> bool:
    rows = _rows_for(tpu_slice.generation, region=region, zone=zone)
    return any(r.max_chips >= tpu_slice.total_chips for r in rows)


def validate_region_zone(region: Optional[str],
                         zone: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """Check (region, zone) exist in the catalog; infer region from zone."""
    if region is None and zone is None:
        return None, None
    rows = _load_rows()
    if zone is not None:
        matches = [r for r in rows if r.zone == zone]
        if not matches:
            raise ValueError(f'Zone {zone!r} not found in catalog.')
        inferred = matches[0].region
        if region is not None and region != inferred:
            raise ValueError(
                f'Zone {zone!r} is in region {inferred!r}, not {region!r}.')
        return inferred, zone
    if not any(r.region == region for r in rows):
        raise ValueError(f'Region {region!r} not found in catalog.')
    return region, None


def get_hourly_cost(tpu_slice: topology.TpuSlice,
                    use_spot: bool = False,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    """$/hour for the whole (multi-)slice. Host VMs are free with TPU-VM

    (reference policy: sky/catalog/gcp_catalog.py:255-277).
    """
    rows = _rows_for(tpu_slice.generation, region=region, zone=zone)
    rows = [r for r in rows if r.max_chips >= tpu_slice.total_chips]
    if not rows:
        where = zone or region or 'any region'
        raise exceptions.ResourcesUnavailableError(
            f'No catalog entry for {tpu_slice.name} in {where}.')
    per_chip = min((r.spot_price_per_chip_hour if use_spot
                    else r.price_per_chip_hour) for r in rows)
    return per_chip * tpu_slice.total_chips


def get_host_vm_spec(generation: str) -> HostVmSpec:
    return _HOST_VMS[generation]


def list_accelerators(
        name_filter: Optional[str] = None,
        region_filter: Optional[str] = None,
        max_chips: Optional[int] = None) -> Dict[str, List[InstanceTypeInfo]]:
    """All offerings, keyed by canonical accelerator name.

    Backs the `skytpu show-tpus` CLI (reference: `sky show-gpus`,
    sky/client/cli/command.py:3547).
    """
    out: Dict[str, List[InstanceTypeInfo]] = {}
    for gen in topology.GENERATIONS:
        for sl in topology.legal_slices(gen):
            if max_chips is not None and sl.num_chips > max_chips:
                continue
            if name_filter is not None and name_filter not in sl.name:
                continue
            for row in _rows_for(gen, region=region_filter):
                if row.max_chips < sl.num_chips:
                    continue
                out.setdefault(sl.name, []).append(
                    InstanceTypeInfo(
                        accelerator_name=sl.name,
                        generation=gen,
                        num_chips=sl.num_chips,
                        topology=sl.topology_str,
                        num_hosts=sl.num_hosts,
                        region=row.region,
                        zone=row.zone,
                        price=row.price_per_chip_hour * sl.num_chips,
                        spot_price=(row.spot_price_per_chip_hour *
                                    sl.num_chips),
                    ))
    return out
