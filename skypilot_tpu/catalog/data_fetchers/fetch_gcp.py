"""Regenerate tpu_catalog.csv from live GCP APIs.

Reference analog: sky/catalog/data_fetchers/fetch_gcp.py — which scrapes
the Cloud Billing Catalog for the TPU service (service id E000-3F24-B8AA,
fetch_gcp.py:38) and hardcodes prices GCP hides (v3 pods, :50-58). Same
sources here, emitting this framework's slice-first schema
(generation,chips,topology,hosts,region,zone,price,spot_price).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp \
        [--output tpu_catalog.csv]
Needs ADC credentials with cloudbilling + tpu API access; the seed CSV in
catalog/data/ is the checked-in fallback so the framework works offline.
"""
from __future__ import annotations

import argparse
import collections
import csv
import re
import sys
from typing import Dict, Iterable, List, Tuple

import requests

from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.tpu import topology as topo_lib

# Cloud Billing Catalog service id for Cloud TPU (fetch_gcp.py:38 analog).
TPU_BILLING_SERVICE_ID = 'E000-3F24-B8AA'
_BILLING_URL = (f'https://cloudbilling.googleapis.com/v1/services/'
                f'{TPU_BILLING_SERVICE_ID}/skus')
_TPU_LOCATIONS_URL = 'https://tpu.googleapis.com/v2/projects/{project}/locations'
_TPU_TYPES_URL = ('https://tpu.googleapis.com/v2/projects/{project}/'
                  'locations/{zone}/acceleratorTypes')

_SKU_RE = re.compile(
    r'Tpu[- ]?(?P<gen>v\d+[ep]?)\s*(?P<pod>pod)?', re.IGNORECASE)


def _headers() -> Dict[str, str]:
    return {'Authorization': f'Bearer {gcp_adaptor.get_access_token()}'}


def _paged(url: str, item_key: str, params=None) -> Iterable[dict]:
    token = None
    while True:
        p = dict(params or {})
        if token:
            p['pageToken'] = token
        resp = requests.get(url, headers=_headers(), params=p, timeout=60)
        resp.raise_for_status()
        data = resp.json()
        yield from data.get(item_key, [])
        token = data.get('nextPageToken')
        if not token:
            return


def fetch_hourly_prices() -> Dict[Tuple[str, str, bool], float]:
    """{(generation, region, is_spot): $/chip-hour} from the billing SKUs."""
    prices: Dict[Tuple[str, str, bool], float] = {}
    for sku in _paged(_BILLING_URL, 'skus'):
        desc = sku.get('description', '')
        m = _SKU_RE.search(desc)
        if not m:
            continue
        gen = m.group('gen').lower()
        spot = 'preemptible' in desc.lower() or 'spot' in desc.lower()
        for region in sku.get('serviceRegions', []):
            for pricing in sku.get('pricingInfo', []):
                expr = pricing.get('pricingExpression', {})
                for rate in expr.get('tieredRates', []):
                    unit = rate.get('unitPrice', {})
                    dollars = (float(unit.get('units', 0)) +
                               float(unit.get('nanos', 0)) / 1e9)
                    if dollars > 0:
                        prices[(gen, region, spot)] = dollars
    return prices


def fetch_zone_types(project: str) -> Dict[str, List[str]]:
    """{zone: [acceleratorType, ...]} from the TPU locations API."""
    out: Dict[str, List[str]] = collections.defaultdict(list)
    url = _TPU_LOCATIONS_URL.format(project=project)
    for loc in _paged(url, 'locations'):
        zone = loc['locationId']
        try:
            types_url = _TPU_TYPES_URL.format(project=project, zone=zone)
            for t in _paged(types_url, 'acceleratorTypes'):
                out[zone].append(t['type'])
        except requests.HTTPError:
            continue
    return dict(out)


def build_rows(prices: Dict[Tuple[str, str, bool], float],
               zone_types: Dict[str, List[str]]) -> List[dict]:
    rows = []
    for zone, types in sorted(zone_types.items()):
        region = zone.rsplit('-', 1)[0]
        for acc_type in sorted(set(types)):
            # acc_type like 'v5litepod-16' / 'v4-8' — same grammar the
            # user-facing names use, so one parser covers both.
            try:
                sl = topo_lib.parse_tpu_accelerator(acc_type)
            except Exception:  # pylint: disable=broad-except
                print(f'skip unknown accelerator type {acc_type!r}',
                      file=sys.stderr)
                continue
            on_demand = prices.get((sl.generation, region, False))
            spot = prices.get((sl.generation, region, True))
            if on_demand is None:
                continue
            rows.append({
                'generation': sl.generation,
                'chips': sl.total_chips,
                'topology': sl.topology_str,
                'hosts': sl.total_hosts,
                'region': region,
                'zone': zone,
                'price': round(on_demand * sl.total_chips, 2),
                'spot_price': round((spot or on_demand * 0.4) *
                                    sl.total_chips, 2),
            })
    return rows


def write_csv(rows: List[dict], path: str) -> None:
    fields = ['generation', 'chips', 'topology', 'hosts', 'region', 'zone',
              'price', 'spot_price']
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=fields)
        writer.writeheader()
        writer.writerows(rows)


def main() -> None:
    parser = argparse.ArgumentParser(prog='fetch_gcp')
    parser.add_argument('--output', default='tpu_catalog.csv')
    parser.add_argument('--project', default=None)
    args = parser.parse_args()
    project = args.project or gcp_adaptor.get_project_id()
    prices = fetch_hourly_prices()
    zone_types = fetch_zone_types(project)
    rows = build_rows(prices, zone_types)
    if not rows:
        print('No rows fetched; keeping the existing catalog.',
              file=sys.stderr)
        sys.exit(1)
    write_csv(rows, args.output)
    print(f'Wrote {len(rows)} rows to {args.output}')


if __name__ == '__main__':
    main()
