"""Catalog regeneration tools (reference analog: sky/catalog/data_fetchers/)."""
