"""Server-side implementations of status/start/stop/down/queue/cancel/logs.

Reference analog: sky/core.py (`status:99`, `start:525`, `down:603`,
`queue:806`, `cancel:900`, `tail_logs:997`) + the status-refresh logic of
sky/backends/backend_utils.py:2278.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.utils import locks
from skypilot_tpu.utils.status_lib import ClusterStatus

logger = sky_logging.init_logger(__name__)


def _handle_of(record: Dict[str, Any]) -> slice_backend.SliceResourceHandle:
    return slice_backend.SliceResourceHandle.from_dict(record['handle'])


def _refresh_one(record: Dict[str, Any]) -> Dict[str, Any]:
    """Reconcile DB status with the cloud's view (backend_utils.py:2278)."""
    name = record['name']
    handle = _handle_of(record)
    try:
        statuses = provision.query_instances(handle.cloud, handle.region,
                                             name, handle.provider_config)
    except exceptions.ClusterDoesNotExist:
        statuses = {}
    except Exception as e:  # pylint: disable=broad-except
        # Transient cloud-API failure: keep the record untouched rather than
        # dropping a possibly-live (billing!) slice from the DB.
        logger.warning(f'Status refresh for {name} failed (keeping current '
                       f'state): {e}')
        return record
    if not statuses:
        # Cloud says gone (e.g. preempted spot slice): drop from DB.
        global_state.remove_cluster(name)
        record = dict(record)
        record['status'] = None
        return record
    values = set(statuses.values())
    if values == {'running'} or values == {'READY'}:
        new_status = ClusterStatus.UP
    elif values <= {'stopped', 'STOPPED', 'STOPPING'}:
        new_status = ClusterStatus.STOPPED
    else:
        new_status = ClusterStatus.INIT
    if new_status != record['status']:
        global_state.set_cluster_status(name, new_status)
        record = dict(record)
        record['status'] = new_status
    return record


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False,
           all_workspaces: bool = False,
           workspace: Optional[str] = None) -> List[Dict[str, Any]]:
    """`workspace` overrides the active-workspace resolution — the API
    server passes the CLIENT's workspace here, since its own env is
    meaningless for the caller."""
    from skypilot_tpu import workspaces
    records = global_state.get_clusters()
    if cluster_names:
        # Explicit names bypass the workspace filter — a user asking for a
        # cluster by name should always find it.
        records = [r for r in records if r['name'] in cluster_names]
    else:
        records = workspaces.filter_records(records, all_workspaces,
                                            workspace=workspace)
    if refresh:
        refreshed = []
        for r in records:
            with locks.cluster_status_lock(r['name']):
                r = _refresh_one(r)
            if r['status'] is not None:
                refreshed.append(r)
        records = refreshed
    return records


def _get_up_handle(cluster_name: str) -> slice_backend.SliceResourceHandle:
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} not found.')
    if record['status'] != ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}, not UP.')
    return _handle_of(record)


def start(cluster_name: str) -> None:
    """Restart a STOPPED cluster (reference analog: core.py:525)."""
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} not found.')
    handle = _handle_of(record)
    from skypilot_tpu.provision import common as provision_common
    from skypilot_tpu.provision import provisioner as provisioner_lib
    config = provision_common.ProvisionConfig(
        provider_config=handle.provider_config,
        authentication_config={},
        count=1,
        tags={},
        resume_stopped_nodes=True,
    )
    provision.run_instances(handle.cloud, handle.region, handle.zone or '',
                            cluster_name, config)
    cluster_info = handle.get_cluster_info()
    provisioner_lib.wait_for_connection(cluster_info)
    provisioner_lib.post_provision_runtime_setup(cluster_name, cluster_info)
    global_state.set_cluster_status(cluster_name, ClusterStatus.UP)


def stop(cluster_name: str) -> None:
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} not found.')
    handle = _handle_of(record)
    backend = slice_backend.TpuSliceBackend()
    backend.teardown(handle, terminate=False)


def down(cluster_name: str) -> None:
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} not found.')
    handle = _handle_of(record)
    backend = slice_backend.TpuSliceBackend()
    backend.teardown(handle, terminate=True)


def autostop(cluster_name: str, idle_minutes: Optional[int],
             down_after: bool = False) -> None:
    handle = _get_up_handle(cluster_name)
    backend = slice_backend.TpuSliceBackend()
    backend.set_autostop(handle, idle_minutes, down_after)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    handle = _get_up_handle(cluster_name)
    backend = slice_backend.TpuSliceBackend()
    return backend.queue(handle)


def cancel(cluster_name: str,
           job_ids: Optional[List[int]] = None) -> List[int]:
    handle = _get_up_handle(cluster_name)
    backend = slice_backend.TpuSliceBackend()
    return backend.cancel_jobs(handle, job_ids)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True) -> int:
    handle = _get_up_handle(cluster_name)
    backend = slice_backend.TpuSliceBackend()
    return backend.tail_logs(handle, job_id, follow=follow)


def job_status(cluster_name: str, job_id: int):
    handle = _get_up_handle(cluster_name)
    backend = slice_backend.TpuSliceBackend()
    return backend.job_status(handle, job_id)


def cost_report() -> List[Dict[str, Any]]:
    return global_state.get_cost_report()
