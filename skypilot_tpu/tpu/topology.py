"""First-class TPU slice model: generations, legal ICI topologies, host counts.

This is the net-new core the reference lacks: SkyPilot treats a TPU only as an
opaque accelerator string handled inside GCP-specific code
(sky/clouds/utils/gcp_utils.py:30-57 `is_tpu/is_tpu_vm_pod`,
sky/clouds/gcp.py:509-545 deploy vars). Here the slice is a typed resource the
optimizer and provisioner reason about directly: chip count, ICI topology,
host fan-out, HBM and peak-FLOPs capacity, multi-slice (DCN) counts.

Naming convention accepted everywhere: `tpu-v5p-128` (reference style,
sky/resources.py `accelerators: tpu-v6e-8`) or the GCP accelerator-type style
`v5litepod-8` / `v5p-128` / `v6e-8`.

Count-unit subtlety (mirrors GCP): for v2/v3/v4/v5p the number in the name is
*TensorCores* (chips x 2 for v4/v5p, x2 for v2/v3); for v5e (v5litepod) and
v6e it is *chips*. `TpuSlice.num_chips` is always chips.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Static facts about one TPU generation."""
    name: str                       # 'v4', 'v5e', ...
    gcp_prefix: str                 # accelerator-type prefix, e.g. 'v5litepod'
    cores_per_chip: int             # TensorCores per chip
    count_unit: str                 # 'cores' | 'chips' (what the name counts)
    default_chips_per_host: int
    hbm_gib_per_chip: int
    peak_bf16_tflops_per_chip: float
    ici_dims: int                   # 2 = 2D torus, 3 = 3D torus
    ici_gbps_per_link: float        # per-direction per-link bandwidth (GB/s)
    default_runtime_version: str
    supports_stop: bool             # GCP allows stopping TPU VMs for these


# Peak-FLOPs / HBM numbers are the public per-chip specs; ICI link bandwidths
# are public approximations used only by the optimizer's time model.
GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', 'v2', 2, 'cores', 4, 16, 45.0, 2, 62.5,
                        'tpu-ubuntu2204-base', False),
    'v3': TpuGeneration('v3', 'v3', 2, 'cores', 4, 32, 123.0, 2, 81.25,
                        'tpu-ubuntu2204-base', False),
    'v4': TpuGeneration('v4', 'v4', 2, 'cores', 4, 32, 275.0, 3, 50.0,
                        'tpu-ubuntu2204-base', True),
    'v5e': TpuGeneration('v5e', 'v5litepod', 1, 'chips', 8, 16, 197.0, 2, 50.0,
                         'v2-alpha-tpuv5-lite', True),
    'v5p': TpuGeneration('v5p', 'v5p', 2, 'cores', 4, 95, 459.0, 3, 100.0,
                         'v2-alpha-tpuv5', True),
    'v6e': TpuGeneration('v6e', 'v6e', 1, 'chips', 8, 32, 918.0, 2, 100.0,
                         'v2-alpha-tpuv6e', True),
}

# Legal slice shapes per generation: name-count -> (chips, topology, hosts).
# Encodes the public GCP slice tables. Multi-host v5e/v6e slices use 4-chip
# hosts; single-host ones pack up to 8 chips on one host.
_Shape = Tuple[int, Tuple[int, ...], int]


def chips_of(topology: Tuple[int, ...]) -> int:
    n = 1
    for d in topology:
        n *= d
    return n


def _v4_like_shapes(max_chips: int, cores_per_chip: int = 2) -> Dict[int, _Shape]:
    """3D-torus generations (v4/v5p): name counts TensorCores, 4 chips/host."""
    shapes: Dict[int, _Shape] = {}
    # Canonical cube-ish topologies doubling the longest-dim each step.
    topo = [2, 2, 1]
    chips = 4
    while chips <= max_chips:
        t = tuple(sorted(topo))
        shapes[chips * cores_per_chip] = (chips, t, max(1, chips // 4))
        # grow smallest dimension by 2x
        i = topo.index(min(topo))
        topo[i] *= 2
        chips *= 2
    return shapes


_V5E_SHAPES: Dict[int, _Shape] = {
    1: (1, (1, 1), 1),
    2: (2, (1, 2), 1),
    4: (4, (2, 2), 1),
    8: (8, (2, 4), 1),
    16: (16, (4, 4), 4),
    32: (32, (4, 8), 8),
    64: (64, (8, 8), 16),
    128: (128, (8, 16), 32),
    256: (256, (16, 16), 64),
}

_V6E_SHAPES: Dict[int, _Shape] = dict(_V5E_SHAPES)  # same public table

_V2_SHAPES: Dict[int, _Shape] = {
    8: (4, (2, 2), 1),
    32: (16, (4, 4), 4),
    128: (64, (8, 8), 16),
    256: (128, (8, 16), 32),
    512: (256, (16, 16), 64),
}

_V3_SHAPES: Dict[int, _Shape] = {
    8: (4, (2, 2), 1),
    32: (16, (4, 4), 4),
    64: (32, (4, 8), 8),
    128: (64, (8, 8), 16),
    256: (128, (8, 16), 32),
    512: (256, (16, 16), 64),
    1024: (512, (16, 32), 128),
    2048: (1024, (32, 32), 256),
}

_SHAPES: Dict[str, Dict[int, _Shape]] = {
    'v2': _V2_SHAPES,
    'v3': _V3_SHAPES,
    'v4': _v4_like_shapes(4096),
    'v5e': _V5E_SHAPES,
    'v5p': _v4_like_shapes(6144),
    'v6e': _V6E_SHAPES,
}


@dataclasses.dataclass(frozen=True)
class TpuSlice:
    """A concrete, schedulable TPU slice (possibly multi-host, multi-slice).

    `num_slices > 1` models DCN-connected multi-slice jobs (MEGASCALE): the
    provisioner allocates `num_slices` independent slices in one zone and the
    runtime wires `MEGASCALE_*` env for cross-slice DCN collectives
    (SURVEY.md section 5 'Distributed comm backend').
    """
    generation: str                  # key into GENERATIONS
    count: int                       # the number in the accelerator name
    num_chips: int                   # chips per slice
    topology: Tuple[int, ...]        # ICI torus dims, e.g. (4, 4, 8)
    num_hosts: int                   # worker VMs per slice
    num_slices: int = 1              # DCN-connected slices

    @property
    def gen(self) -> TpuGeneration:
        return GENERATIONS[self.generation]

    @property
    def name(self) -> str:
        base = f'tpu-{self.generation}-{self.count}'
        if self.num_slices > 1:
            return f'{base}x{self.num_slices}'
        return base

    @property
    def gcp_accelerator_type(self) -> str:
        return f'{self.gen.gcp_prefix}-{self.count}'

    @property
    def topology_str(self) -> str:
        return 'x'.join(str(d) for d in self.topology)

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def total_chips(self) -> int:
        return self.num_chips * self.num_slices

    @property
    def total_hosts(self) -> int:
        return self.num_hosts * self.num_slices

    @property
    def chips_per_host(self) -> int:
        return self.num_chips // self.num_hosts

    @property
    def peak_bf16_tflops(self) -> float:
        return self.gen.peak_bf16_tflops_per_chip * self.total_chips

    @property
    def hbm_gib(self) -> int:
        return self.gen.hbm_gib_per_chip * self.total_chips

    def __str__(self) -> str:
        return (f'{self.name} ({self.total_chips} chips, '
                f'{self.topology_str} ICI, {self.total_hosts} hosts)')


_TPU_NAME_RE = re.compile(
    r'^(?:tpu-)?(?P<gen>v2|v3|v4|v5e|v5litepod|v5p|v6e)-(?P<count>\d+)'
    r'(?:x(?P<slices>\d+))?$', re.IGNORECASE)


def is_tpu_accelerator(name: str) -> bool:
    return _TPU_NAME_RE.fullmatch(name.strip()) is not None


def parse_tpu_accelerator(name: str,
                          topology: Optional[str] = None) -> TpuSlice:
    """Parse 'tpu-v5p-128', 'v5litepod-8', 'tpu-v6e-256x4' into a TpuSlice.

    `topology` optionally overrides the canonical topology for generations
    with multiple legal layouts for the same chip count (v4/v5p allow e.g.
    4x4x8 vs 2x8x16); it must multiply to the same chip count.
    """
    m = _TPU_NAME_RE.fullmatch(name.strip())
    if m is None:
        raise exceptions.InvalidTopologyError(
            f'Not a TPU accelerator name: {name!r}. Expected e.g. '
            f'tpu-v5p-128, v5litepod-8, tpu-v6e-256x4.')
    gen = m.group('gen').lower()
    if gen == 'v5litepod':
        gen = 'v5e'
    count = int(m.group('count'))
    num_slices = int(m.group('slices') or 1)
    shapes = _SHAPES[gen]
    if count not in shapes:
        raise exceptions.InvalidTopologyError(
            f'{name!r}: no legal {gen} slice with count {count}. '
            f'Legal counts: {sorted(shapes)}')
    chips, topo, hosts = shapes[count]
    if topology is not None:
        custom = tuple(int(d) for d in topology.lower().split('x'))
        if chips_of(custom) != chips:
            raise exceptions.InvalidTopologyError(
                f'Topology {topology} has {chips_of(custom)} chips; '
                f'{name} requires {chips}.')
        if len(custom) != GENERATIONS[gen].ici_dims:
            raise exceptions.InvalidTopologyError(
                f'{gen} slices use {GENERATIONS[gen].ici_dims}D ICI tori; '
                f'got topology {topology}.')
        topo = custom
    return TpuSlice(generation=gen, count=count, num_chips=chips,
                    topology=topo, num_hosts=hosts, num_slices=num_slices)


def legal_slices(generation: str) -> List[TpuSlice]:
    """All legal single-slice shapes for a generation, smallest first."""
    if generation not in _SHAPES:
        raise exceptions.InvalidTopologyError(
            f'Unknown TPU generation {generation!r}. '
            f'Known: {sorted(GENERATIONS)}')
    out = []
    for count in sorted(_SHAPES[generation]):
        chips, topo, hosts = _SHAPES[generation][count]
        out.append(TpuSlice(generation, count, chips, topo, hosts))
    return out


_DEVICE_KIND_TO_GEN = {
    'tpu v2': 'v2',
    'tpu v3': 'v3',
    'tpu v4': 'v4',
    'tpu v5 lite': 'v5e',
    'tpu v5': 'v5p',
    'tpu v5p': 'v5p',
    'tpu v6 lite': 'v6e',
    'tpu v6e': 'v6e',
    'tpu7x': 'v6e',
}


def generation_from_device_kind(device_kind: str) -> Optional[str]:
    """Map jax.devices()[i].device_kind to a generation ('TPU v5 lite'→v5e)."""
    k = device_kind.lower().strip()
    if k in _DEVICE_KIND_TO_GEN:
        return _DEVICE_KIND_TO_GEN[k]
    for prefix, gen in sorted(_DEVICE_KIND_TO_GEN.items(),
                              key=lambda kv: -len(kv[0])):
        if k.startswith(prefix):
            return gen
    return None


def peak_flops_for_device(device) -> float:
    """Best-effort peak bf16 FLOP/s for a jax device (for MFU accounting)."""
    gen = generation_from_device_kind(getattr(device, 'device_kind', ''))
    if gen is None:
        # CPU or unknown: use a nominal 1 TFLOP/s so MFU math stays defined.
        return 1e12
    return GENERATIONS[gen].peak_bf16_tflops_per_chip * 1e12
