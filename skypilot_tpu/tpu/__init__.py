"""TPU hardware model: generations, slice topologies, ICI/DCN facts."""
from skypilot_tpu.tpu.topology import (  # noqa: F401
    TpuGeneration,
    TpuSlice,
    GENERATIONS,
    parse_tpu_accelerator,
    legal_slices,
    generation_from_device_kind,
)
