"""SSH keypair management for cluster access.

Reference analog: sky/authentication.py (keypair generation + per-cloud key
upload). GCP TPU VMs receive the public key through instance metadata
('ssh-keys'), which the TPU VM guest agent installs for the login user.
"""
from __future__ import annotations

import os
import stat
import subprocess
from typing import Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

SSH_DIR = '~/.skytpu/ssh'
PRIVATE_KEY_PATH = f'{SSH_DIR}/skytpu-key'
PUBLIC_KEY_PATH = f'{SSH_DIR}/skytpu-key.pub'
SSH_USER = 'skytpu'


def get_or_generate_keys() -> Tuple[str, str]:
    """Return (private, public) key paths, generating once if absent."""
    private = os.path.expanduser(PRIVATE_KEY_PATH)
    public = os.path.expanduser(PUBLIC_KEY_PATH)
    if not os.path.exists(private):
        os.makedirs(os.path.dirname(private), exist_ok=True)
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f', private,
             '-C', 'skytpu'],
            check=True)
        os.chmod(private, stat.S_IRUSR | stat.S_IWUSR)
        logger.debug(f'Generated cluster SSH keypair at {private}.')
    return private, public


def public_key_openssh() -> str:
    _, public = get_or_generate_keys()
    with open(public, 'r', encoding='utf-8') as f:
        return f.read().strip()


def gcp_ssh_keys_metadata() -> str:
    """Value for GCP instance metadata key 'ssh-keys'."""
    return f'{SSH_USER}:{public_key_openssh()}'
