"""Cost/time-optimal assignment of concrete TPU slices to DAG tasks.

Reference analog: sky/optimizer.py (`Optimizer.optimize:109`,
`_optimize_by_dp:429`, `_optimize_by_ilp:490`,
`_estimate_nodes_cost_or_time:239`, `_optimize_dag:1035`).

Differences:
- Candidate enumeration is slice-shape aware: a partial request like
  `accelerators: tpu-v5p-128` fans out across regions/spot choices, and the
  feasibility check knows which chip counts form legal ICI tori
  (skypilot_tpu/tpu/topology.py) — the reference delegates this entirely to
  catalog string matches.
- The general-DAG path uses exact enumeration with branch-and-bound up to a
  size limit, then greedy (no ILP dependency in this environment). DAGs here
  are small (pipelines of a few stages), so exact search is the common case.
- The time model is analytical for TPU: if a task carries
  `estimated_total_flops`, runtime ≈ flops / (slice peak FLOPs × assumed
  MFU); egress cost between stages uses cloud egress pricing.
"""
from __future__ import annotations

import collections
import enum
import typing
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

# Assumed model FLOPs utilization when converting FLOPs → runtime, PER
# GENERATION: achievable MFU tracks memory bandwidth per peak FLOP, which
# differs across generations — a flat number ranks v5e vs v6e wrong (v6e
# has 4.7x the peak but nowhere near 4.7x the bandwidth). Values are
# coarse by design (the ranking, not the absolute runtime, is load-
# bearing); v5e's is this framework's own measured train MFU (bench.py).
_ASSUMED_MFU_BY_GEN = {
    'v2': 0.35, 'v3': 0.40, 'v4': 0.50, 'v5p': 0.50,
    'v5e': 0.55,            # measured 55.52%: BENCH_LAST_GOOD.json
    #                         (driver-captured, 2026-07-31; bench.py
    #                         Llama-1B class, bf16, TPU v5 lite)
    'v6e': 0.40,            # high peak / relatively lower HBM BW per FLOP
}
_ASSUMED_MFU_DEFAULT = 0.4
_DEFAULT_TASK_SECONDS = 3600.0
# Exact-search budget: beyond this many assignment combinations fall back to
# per-node greedy.
_EXACT_SEARCH_LIMIT = 200_000


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Optimizer:

    @staticmethod
    @timeline.event
    @spans_lib.traced('optimizer.plan')
    def optimize(dag: 'dag_lib.Dag',
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[
                     List['resources_lib.Resources']] = None,
                 quiet: bool = False) -> 'dag_lib.Dag':
        """Assign `task.best_resources` for every task in the dag."""
        dag.validate()
        candidates = _enumerate_candidates(dag, blocked_resources or [])
        if dag.is_chain():
            assignment, objective = _optimize_by_dp(dag, candidates, minimize)
        else:
            assignment, objective = _optimize_general(dag, candidates,
                                                      minimize)
        for task, res in assignment.items():
            task.best_resources = res
        if not quiet:
            _print_plan(dag, assignment, objective, minimize)
        return dag


# ---------------------------------------------------------------------------
# Candidate enumeration
# ---------------------------------------------------------------------------
def _estimate_seconds(task: 'task_lib.Task',
                      res: 'resources_lib.Resources') -> float:
    flops = getattr(task, 'estimated_total_flops', None)
    if flops and res.tpu is not None:
        peak = res.tpu.peak_bf16_tflops * 1e12
        mfu = _ASSUMED_MFU_BY_GEN.get(res.tpu.gen.name,
                                      _ASSUMED_MFU_DEFAULT)
        return max(1.0, flops / (peak * mfu))
    if task.estimated_duration_seconds is not None:
        return task.estimated_duration_seconds
    return _DEFAULT_TASK_SECONDS


def _candidate_cost_time(task: 'task_lib.Task',
                         res: 'resources_lib.Resources'
                         ) -> Tuple[float, float]:
    seconds = _estimate_seconds(task, res)
    return res.get_cost(seconds), seconds


def _is_blocked(res: 'resources_lib.Resources',
                blocked: List['resources_lib.Resources']) -> bool:
    return any(b.less_demanding_than(res) for b in blocked)


def _enumerate_candidates(
    dag: 'dag_lib.Dag', blocked: List['resources_lib.Resources']
) -> Dict['task_lib.Task', List['resources_lib.Resources']]:
    enabled = check_lib.get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access=True)
    per_task: Dict['task_lib.Task', List['resources_lib.Resources']] = {}
    for task in dag.tasks:
        cands: List['resources_lib.Resources'] = []
        fuzzy: List[str] = []
        for want in task.resources_list():
            clouds_to_try: List[cloud_lib.Cloud]
            if want.cloud is not None:
                if not cloud_lib.cloud_in_iterable(want.cloud, enabled):
                    fuzzy.append(f'{want.cloud} not enabled')
                    continue
                clouds_to_try = [want.cloud]
            else:
                clouds_to_try = enabled
            for cloud in clouds_to_try:
                feasible, near = cloud.get_feasible_launchable_resources(want)
                fuzzy.extend(near)
                for res in feasible:
                    if not _is_blocked(res, blocked):
                        cands.append(res)
        if not cands:
            hint = ''
            if fuzzy:
                uniq = sorted(set(fuzzy))[:6]
                hint = f' Did you mean / try: {", ".join(uniq)}?'
            raise exceptions.ResourcesUnavailableError(
                f'No feasible resources for {task!r} among enabled clouds '
                f'{[repr(c) for c in enabled]}.{hint}')
        per_task[task] = cands
    return per_task


# ---------------------------------------------------------------------------
# Chain DP (analog: sky/optimizer.py:429)
# ---------------------------------------------------------------------------
def _edge_cost(parent_res: 'resources_lib.Resources',
               child_res: 'resources_lib.Resources',
               gigabytes: float) -> float:
    """Egress $ if a stage boundary crosses clouds/regions."""
    if gigabytes <= 0 or parent_res.cloud is None or child_res.cloud is None:
        return 0.0
    same_cloud = parent_res.cloud.is_same_cloud(child_res.cloud)
    if same_cloud and parent_res.region == child_res.region:
        return 0.0
    if same_cloud:
        return parent_res.cloud.get_egress_cost(gigabytes) * 0.1
    return parent_res.cloud.get_egress_cost(gigabytes)


def _objective(task: 'task_lib.Task', res: 'resources_lib.Resources',
               minimize: OptimizeTarget) -> float:
    cost, seconds = _candidate_cost_time(task, res)
    return cost if minimize is OptimizeTarget.COST else seconds


def _optimize_by_dp(
    dag: 'dag_lib.Dag',
    candidates: Dict['task_lib.Task', List['resources_lib.Resources']],
    minimize: OptimizeTarget,
) -> Tuple[Dict['task_lib.Task', 'resources_lib.Resources'], float]:
    order = dag.topological_order()
    # best[i][res] = (objective-so-far, chosen res of predecessor)
    prev_best: Dict['resources_lib.Resources', Tuple[float, Optional[
        'resources_lib.Resources']]] = {None: (0.0, None)}  # type: ignore
    choices: List[Dict] = []
    for i, task in enumerate(order):
        cur: Dict['resources_lib.Resources', Tuple[float, Optional[
            'resources_lib.Resources']]] = {}
        parent_gb = 0.0
        if i > 0:
            parent_gb = float(
                getattr(order[i - 1], 'estimated_output_gb', 0.0) or 0.0)
        for res in candidates[task]:
            node_obj = _objective(task, res, minimize)
            best_val, best_prev = float('inf'), None
            for prev_res, (prev_val, _) in prev_best.items():
                edge = 0.0
                if prev_res is not None and minimize is OptimizeTarget.COST:
                    edge = _edge_cost(prev_res, res, parent_gb)
                total = prev_val + node_obj + edge
                if total < best_val:
                    best_val, best_prev = total, prev_res
            cur[res] = (best_val, best_prev)
        choices.append(cur)
        prev_best = cur
    # Backtrack.
    assignment: Dict['task_lib.Task', 'resources_lib.Resources'] = {}
    best_res = min(prev_best, key=lambda r: prev_best[r][0])
    objective = prev_best[best_res][0]
    for i in range(len(order) - 1, -1, -1):
        assignment[order[i]] = best_res
        best_res = choices[i][best_res][1]
    return assignment, objective


# ---------------------------------------------------------------------------
# General DAG: exact search with pruning, greedy fallback
# (reference uses ILP via pulp, sky/optimizer.py:490)
# ---------------------------------------------------------------------------
def _optimize_general(
    dag: 'dag_lib.Dag',
    candidates: Dict['task_lib.Task', List['resources_lib.Resources']],
    minimize: OptimizeTarget,
) -> Tuple[Dict['task_lib.Task', 'resources_lib.Resources'], float]:
    order = dag.topological_order()
    sizes = [len(candidates[t]) for t in order]
    total = 1
    for s in sizes:
        total *= s
        if total > _EXACT_SEARCH_LIMIT:
            break
    if total > _EXACT_SEARCH_LIMIT:
        assignment = {
            t: min(candidates[t], key=lambda r: _objective(t, r, minimize))
            for t in order
        }
        objective = sum(
            _objective(t, r, minimize) for t, r in assignment.items())
        return assignment, objective

    graph = dag.get_graph()
    best_assignment: Dict = {}
    best_obj = float('inf')
    cur: Dict['task_lib.Task', 'resources_lib.Resources'] = {}

    # Lower bound per remaining task for pruning.
    node_min = {
        t: min(_objective(t, r, minimize) for r in candidates[t])
        for t in order
    }

    def dfs(i: int, acc: float) -> None:
        nonlocal best_obj, best_assignment
        if acc + sum(node_min[t] for t in order[i:]) >= best_obj:
            return
        if i == len(order):
            best_obj = acc
            best_assignment = dict(cur)
            return
        task = order[i]
        scored = sorted(candidates[task],
                        key=lambda r: _objective(task, r, minimize))
        for res in scored:
            obj = _objective(task, res, minimize)
            edge = 0.0
            if minimize is OptimizeTarget.COST:
                for parent in graph.predecessors(task):
                    if parent in cur:
                        gb = float(
                            getattr(parent, 'estimated_output_gb', 0.0) or 0.0)
                        edge += _edge_cost(cur[parent], res, gb)
            cur[task] = res
            dfs(i + 1, acc + obj + edge)
            del cur[task]

    dfs(0, 0.0)
    return best_assignment, best_obj


# ---------------------------------------------------------------------------
# Plan printing (analog: the reference's optimizer table)
# ---------------------------------------------------------------------------
def _print_plan(dag: 'dag_lib.Dag', assignment: Dict, objective: float,
                minimize: OptimizeTarget) -> None:
    rows = []
    for task in dag.topological_order():
        res = assignment[task]
        cost, seconds = _candidate_cost_time(task, res)
        sl = res.tpu
        rows.append((
            task.name or '-',
            repr(res.cloud),
            sl.name if sl else '-',
            sl.topology_str if sl else '-',
            str(sl.total_hosts if sl else 1),
            res.region or '-',
            'spot' if res.use_spot else 'on-demand',
            f'${cost:.2f}',
            f'{seconds / 3600:.1f}h',
        ))
    header = ('TASK', 'CLOUD', 'SLICE', 'ICI TOPO', 'HOSTS', 'REGION',
              'BILLING', 'EST.COST', 'EST.TIME')
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows))
        for i in range(len(header))
    ]
    lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
    unit = '$' if minimize is OptimizeTarget.COST else 's'
    sky_logging.print_status(
        f'Optimizer plan (minimizing {minimize.value}, objective '
        f'{objective:.2f}{unit}):\n' + '\n'.join(lines))
