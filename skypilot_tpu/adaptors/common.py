"""Lazy SDK imports (reference analog: sky/adaptors/common.py:10)."""
from __future__ import annotations

import importlib
import threading
from typing import Any, Optional


class LazyImport:
    """Defer a module import until first attribute access.

    Keeps `import skypilot_tpu` fast and lets clouds whose SDKs are absent
    stay registered (errors surface only when actually used).
    """

    def __init__(self, module_name: str,
                 import_error_message: Optional[str] = None):
        self._module_name = module_name
        self._module: Any = None
        self._error_message = import_error_message
        self._lock = threading.Lock()

    def _load(self) -> Any:
        if self._module is None:
            with self._lock:
                if self._module is None:
                    try:
                        self._module = importlib.import_module(
                            self._module_name)
                    except ImportError as e:
                        msg = self._error_message or (
                            f'Failed to import {self._module_name!r}.')
                        raise ImportError(msg) from e
        return self._module

    def __getattr__(self, item: str) -> Any:
        return getattr(self._load(), item)
