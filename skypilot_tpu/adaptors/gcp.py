"""GCP auth/session helpers (reference analog: sky/adaptors/gcp.py).

Uses application-default credentials via google.auth; all TPU control-plane
calls go through plain REST (tpu.googleapis.com) with a bearer token, so no
heavy discovery client is needed.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from skypilot_tpu.adaptors import common

google_auth = common.LazyImport(
    'google.auth', 'google-auth is required for GCP support.')
google_auth_transport = common.LazyImport('google.auth.transport.requests')

_token_lock = threading.Lock()
_cached_token: Optional[str] = None
_cached_expiry: float = 0.0
_cached_project: Optional[str] = None


def get_project_id() -> str:
    import os
    # Env wins without touching ADC: resolving credentials just to read a
    # project id fails on boxes that set the env var but have no ADC.
    env_project = os.environ.get('GOOGLE_CLOUD_PROJECT')
    if env_project:
        return env_project
    _, project = _credentials()
    if not project:
        raise RuntimeError(
            'No GCP project configured. Set GOOGLE_CLOUD_PROJECT or run '
            '`gcloud config set project <id>`.')
    return project


def _credentials() -> Tuple[object, Optional[str]]:
    import os
    creds, project = google_auth.default(
        scopes=['https://www.googleapis.com/auth/cloud-platform'])
    project = os.environ.get('GOOGLE_CLOUD_PROJECT', project)
    return creds, project


def get_access_token() -> str:
    """Cached ADC bearer token, refreshed ahead of expiry."""
    global _cached_token, _cached_expiry
    with _token_lock:
        if _cached_token is not None and time.time() < _cached_expiry - 120:
            return _cached_token
        creds, _ = _credentials()
        request = google_auth_transport.Request()
        creds.refresh(request)
        _cached_token = creds.token
        expiry = getattr(creds, 'expiry', None)
        if expiry is not None:
            # google-auth expiry datetimes are naive UTC; attach the UTC
            # tzinfo before .timestamp() or local-time skew poisons the
            # cache window.
            from datetime import timezone
            if expiry.tzinfo is None:
                expiry = expiry.replace(tzinfo=timezone.utc)
            _cached_expiry = expiry.timestamp()
        else:
            _cached_expiry = time.time() + 1800
        return _cached_token
