"""Harvest-run harness: dispatcher + worker subprocesses + learner.

One entry point (:func:`run_harvest`) drives a complete harvested-RL
run on this box — in-process dispatcher and learner (so callers can
read the journal and the learner's accounting), REAL worker
subprocesses (so SIGKILL means SIGKILL) — under a seeded kill/respawn
schedule. Shared by ``bench.py rl_harvest`` (the scorecard pair:
0-kill control vs seeded-kill harvest) and the chaos suite
(tests/chaos/test_rollout_churn.py), so the numbers the scorecard
reports come from exactly the code path the chaos proof exercises.

Cost accounting (:func:`cost_per_sample`) prices the learner at
on-demand and the workers at spot (or on-demand, for the control
configuration) using the catalog layer — the RLBoost economics: spot
rollout capacity is ~40% of on-demand price, and the harness measures
how much of that saving preemption churn gives back.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.train.rollout import dispatcher as dispatcher_lib
from skypilot_tpu.train.rollout import learner as learner_lib
from skypilot_tpu.train.rollout import spec as spec_lib
from skypilot_tpu.utils import framed

logger = sky_logging.init_logger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def default_spec(run_dir: str, tag: str = 'run',
                 **overrides) -> spec_lib.RolloutSpec:
    """The tiny CPU-proxy job both the bench and the chaos suite run.

    ``snapshot_dir`` is TAG-scoped: bench's control/harvested run pair
    shares one run_dir, and a shared snapshot directory would let the
    second run's workers restore the FIRST run's final policy (its
    step numbers sort newer than the fresh run's version 0)."""
    from skypilot_tpu import models as models_lib
    fields = dict(
        model='llama-debug',
        reward='count_token:42',
        snapshot_dir=os.path.join(run_dir, f'snapshots-{tag}'),
        vocab_size=models_lib.get_config('llama-debug').vocab_size,
        prompt_len=8, group_size=4, max_new_tokens=8,
        temperature=1.0, seed=0,
        # Pacing: the tiny model generates near-instantly on CPU, so
        # without a per-group cost the learner banks the whole run in
        # its prefetch buffer and worker churn is invisible. The delay
        # makes rollout capacity the bottleneck — kills visibly
        # degrade samples/sec, rejoin visibly restores it.
        rollout_delay_s=0.25)
    fields.update(overrides)
    return spec_lib.RolloutSpec(**fields)


def spawn_worker(dispatcher_addr, worker_id: str, *,
                 heartbeat_interval: float = 0.3,
                 extra_env: Optional[Dict[str, str]] = None
                 ) -> subprocess.Popen:
    """A REAL rollout-worker subprocess (CPU jax). The persistent
    jax compile cache is disabled: jax 0.4.x segfaults reloading this
    program mix (the train-churn suite's documented workaround)."""
    env = {**os.environ, 'PYTHONPATH': _REPO, 'JAX_PLATFORMS': 'cpu',
           'JAX_ENABLE_COMPILATION_CACHE': 'false'}
    env.pop('JAX_COMPILATION_CACHE_DIR', None)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.train.rollout', 'worker',
         '--dispatcher', f'{dispatcher_addr[0]}:{dispatcher_addr[1]}',
         '--worker-id', worker_id,
         '--heartbeat-interval', str(heartbeat_interval)],
        cwd=_REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)


def wait_alive(dispatcher_addr, n: int, timeout: float = 90.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reply, _ = framed.request(dispatcher_addr, {'op': 'stats'},
                                  timeout=5.0)
        if reply['workers'].get('ALIVE', 0) >= n:
            return
        time.sleep(0.1)
    raise TimeoutError(f'{n} rollout workers not ALIVE within '
                       f'{timeout}s')


def _window_rate(walls: List[float], lo: int, hi: int,
                 samples_per_step: float) -> Optional[float]:
    """samples/sec over completed steps [lo, hi) (wall = step-end
    monotonic stamps)."""
    span = walls[lo:hi]
    if len(span) < 2:
        return None
    dt = span[-1] - span[0]
    return (len(span) - 1) * samples_per_step / dt if dt > 0 else None


def run_harvest(run_dir: str, *,
                n_workers: int,
                total_steps: int,
                kill_at_step: Optional[int] = None,
                kill_count: int = 0,
                respawn_at_step: Optional[int] = None,
                groups_per_step: int = 2,
                publish_every: int = 4,
                max_staleness: int = 8,
                learning_rate: float = 1e-3,
                heartbeat_timeout: float = 1.5,
                lease_timeout: float = 20.0,
                max_outstanding: int = 6,
                result_cap: int = 4,
                stall_budget_s: float = 120.0,
                worker_env: Optional[Dict[str, str]] = None,
                spec_overrides: Optional[Dict[str, Any]] = None,
                tag: str = 'run') -> Dict[str, Any]:
    """One complete harvested run under a deterministic kill schedule.

    ``kill_at_step``: after the learner completes that step, SIGKILL
    ``kill_count`` workers (no goodbye — mid-generation for any worker
    currently holding a lease). ``respawn_at_step``: spawn the same
    number of fresh workers after that step (capacity rejoins).
    Returns the run artifact: learner history, samples/sec windows,
    recovery time, per-role busy seconds for cost accounting, and the
    killed worker ids (journal evidence keys).
    """
    os.makedirs(run_dir, exist_ok=True)
    spec = default_spec(run_dir, tag=tag, **(spec_overrides or {}))
    disp = dispatcher_lib.RolloutDispatcher(
        os.path.join(run_dir, f'dispatcher-{tag}.db'),
        heartbeat_timeout=heartbeat_timeout,
        lease_timeout=lease_timeout,
        # Tight backpressure: the buffer must not bank the run, or
        # worker churn would be invisible to the learner's cadence.
        max_outstanding=max_outstanding,
        result_cap=result_cap).start()
    procs: Dict[str, subprocess.Popen] = {}
    spawn_ts: Dict[str, float] = {}
    dead_ts: Dict[str, float] = {}
    killed: List[str] = []
    kill_wall: Optional[float] = None
    respawn_wall: Optional[float] = None

    def _spawn(i: int) -> None:
        wid = f'rw-{tag}-{i}'
        procs[wid] = spawn_worker(disp.addr, wid,
                                  extra_env=worker_env)
        spawn_ts[wid] = time.monotonic()

    learner = None
    try:
        learner = learner_lib.RolloutLearner(
            spec, disp.addr, total_steps=total_steps,
            groups_per_step=groups_per_step,
            publish_every=publish_every, max_staleness=max_staleness,
            learning_rate=learning_rate,
            traj_log_dir=os.path.join(run_dir, f'traj-{tag}'),
            stall_budget_s=stall_budget_s,
            on_step=lambda step: _schedule(step))

        def _schedule(step: int) -> None:
            nonlocal kill_wall, respawn_wall
            if kill_at_step is not None and step + 1 == kill_at_step \
                    and not killed:
                for wid in list(procs)[:kill_count]:
                    procs[wid].send_signal(signal.SIGKILL)
                    procs[wid].wait(timeout=10)
                    dead_ts[wid] = time.monotonic()
                    killed.append(wid)
                kill_wall = time.monotonic()
            if respawn_at_step is not None and \
                    step + 1 == respawn_at_step and \
                    respawn_wall is None and killed:
                base = len(procs)
                for j in range(len(killed)):
                    _spawn(base + j)
                respawn_wall = time.monotonic()

        t_start = time.monotonic()
        # Workers first: their jax boot overlaps the learner's
        # put_spec + initial publish + update-jit warmup.
        for i in range(n_workers):
            _spawn(i)
        learner.start()
        wait_alive(disp.addr, n_workers)
        history = learner.run()
        duration = time.monotonic() - t_start

        walls = learner.step_walls
        per_step = groups_per_step * spec.group_size
        sps_all = _window_rate(walls, 0, len(walls), per_step)
        pre = post = degraded = best_post = None
        recovery_s = None
        if kill_at_step is not None and killed:
            # Pre-kill rate over the steady approach to the kill —
            # the first steps drain whatever the fleet banked during
            # the learner's compile and would inflate the baseline.
            pre = _window_rate(walls, max(1, kill_at_step - 5),
                               kill_at_step, per_step)
            degraded = _window_rate(
                walls, kill_at_step,
                min(len(walls), kill_at_step + 6), per_step)
            # Post-rejoin rate = the steady tail (respawned workers
            # pay jax boot + compile before they contribute — that
            # warm-up IS part of recovery time, not of the recovered
            # rate).
            post = _window_rate(walls, max(0, len(walls) - 5),
                                len(walls), per_step)
            # Recovery: kill → first moment the trailing 3-step rate
            # is back to >= 90% of the pre-kill rate. Also export the
            # BEST trailing window after the rejoin — the
            # contention-robust recovery signal chaos tests assert on
            # (the tail itself can be noisy on a loaded box).
            best_post = None
            for i in range(kill_at_step + 3, len(walls)):
                rate = _window_rate(walls, i - 3, i + 1, per_step)
                if rate is None:
                    continue
                if pre and recovery_s is None and rate >= 0.9 * pre:
                    recovery_s = walls[i] - kill_wall
                if respawn_at_step is not None and \
                        i >= respawn_at_step + 1 and \
                        (best_post is None or rate > best_post):
                    best_post = rate
        now = time.monotonic()
        worker_busy_s = sum(
            (dead_ts.get(wid, now) - t0)
            for wid, t0 in spawn_ts.items())
        return {
            'tag': tag,
            'spec_fp': spec.fingerprint(),
            'spec': spec,
            'steps': len(history),
            'duration_s': round(duration, 3),
            'history': history,
            'report': learner.report(),
            'samples_total': learner.samples_total,
            'samples_per_sec': sps_all,
            'pre_kill_sps': pre,
            'degraded_sps': degraded,
            'post_rejoin_sps': post,
            'best_post_rejoin_sps': best_post,
            'recovery_s': recovery_s,
            'killed': killed,
            'kill_wall': kill_wall,
            'learner_busy_s': duration,
            'worker_busy_s': worker_busy_s,
            'traj_log_dir': os.path.join(run_dir, f'traj-{tag}'),
            'losses': [h['loss'] for h in history],
        }
    finally:
        # Learner first (stops the collect thread's redial loop), on
        # EVERY exit path — a RolloutStallError must not leak a live
        # thread + open sockets into the calling pytest process.
        if learner is not None:
            learner.close()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in procs.values():
            if proc.poll() is None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        disp.stop()


def cost_per_sample(samples: int, learner_busy_s: float,
                    worker_busy_s: float, *,
                    accelerator: str = 'v5litepod-8',
                    workers_spot: bool = True) -> Dict[str, Any]:
    """$/sample for a run: stable learner at on-demand price, rollout
    fleet at spot (harvested) or on-demand (control). A thin delegate
    since the cost-attribution plane landed: every price resolution
    and accrual goes through observe/costs.py's CostMeter — rollout
    and serve bill from ONE code path (RL_HARVEST_LAST_GOOD.json pins
    the key set, rates and rounding this must keep reproducing)."""
    from skypilot_tpu.observe import costs
    return costs.cost_per_sample(samples, learner_busy_s,
                                 worker_busy_s,
                                 accelerator=accelerator,
                                 workers_spot=workers_spot)
