"""RolloutSpec: everything a stateless rollout worker needs.

The determinism backbone of the harvested plane, mirroring
``data_service/spec.py``'s "batch = f(seed, corpus, step)" contract:
the PROMPT of lease ``i`` is a pure function of ``(spec, i)``
(:func:`prompt_for`), and the sampling RNG a worker uses for lease
``i`` is seeded from ``(spec.seed, i)`` (:func:`lease_rng_seed`).  So
reassigning a lease ships one integer, any worker can serve any lease,
and a duplicate execution of the same lease AGAINST THE SAME SNAPSHOT
produces byte-identical trajectories (first submission wins either
way — at-least-once is safe by construction).

Completions additionally depend on the policy snapshot the worker
holds — that is the off-policy reality of harvested rollouts, made
explicit by stamping every trajectory with its snapshot version (the
learner's staleness window keys on it).

Specs are fingerprinted (sha256 of canonical JSON) and ``from_json``
refuses unknown fields: two processes silently disagreeing about the
pipeline must fail loudly at the first RPC, not ship garbage
trajectories into the policy gradient.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class RolloutSpec:
    """One harvested-RL job: model, reward, GRPO shape, snapshot dir.

    ``snapshot_dir`` must resolve on every worker (shared storage /
    mounted bucket — the same contract ``--ckpt-dir`` places on the
    trainer). ``vocab_size`` is explicit (not derived from the model
    preset) so the jax-free dispatcher can describe prompts without
    importing the model stack.
    """
    model: str                     # models preset name
    reward: str                    # grpo.resolve_reward spec string
    snapshot_dir: str              # learner-published policy snapshots
    vocab_size: int
    prompt_len: int = 16
    group_size: int = 4            # completions per prompt (G)
    max_new_tokens: int = 16       # completion length (T, static)
    temperature: float = 1.0
    clip_eps: float = 0.2
    kl_coef: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    # Bench/chaos knob (the DatasetSpec.preprocess_delay_s precedent):
    # an artificial per-group generation cost, so "rollout capacity is
    # the bottleneck and worker churn is visible" holds on a CPU proxy
    # whose tiny model generates faster than real rollouts ever would.
    # Affects timing only, never trajectory content.
    rollout_delay_s: float = 0.0

    def __post_init__(self):
        if self.vocab_size <= 0:
            raise ValueError(f'vocab_size={self.vocab_size} must be > 0')
        if self.prompt_len <= 0 or self.max_new_tokens <= 0:
            raise ValueError('prompt_len and max_new_tokens must be > 0')
        if self.group_size < 2:
            raise ValueError(
                f'group_size={self.group_size} must be >= 2: the group '
                f'IS the GRPO baseline — a singleton group has zero '
                f'advantage by construction and learns nothing')

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> 'RolloutSpec':
        if not isinstance(obj, dict):
            raise TypeError(f'RolloutSpec JSON must be an object, '
                            f'got {type(obj).__name__}')
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(
                f'RolloutSpec has no fields {sorted(unknown)} — '
                f'version skew between learner and worker; upgrade '
                f'the older side')
        return cls(**obj)

    def fingerprint(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(',', ':'))
        return hashlib.sha256(blob.encode('utf-8')).hexdigest()[:16]


def prompt_for(spec: RolloutSpec, lease_id: int) -> np.ndarray:
    """Lease ``i``'s prompt: ``[prompt_len]`` int32 in ``[0, vocab)``.

    numpy's seeded Generator (not jax) on purpose: the dispatcher and
    any worker must agree on prompts without importing jax, and
    ``default_rng`` is stable across processes and platforms."""
    rng = np.random.default_rng(
        (np.uint64(spec.seed) << np.uint64(32)) ^ np.uint64(lease_id))
    return rng.integers(0, spec.vocab_size, size=spec.prompt_len,
                        dtype=np.int32)


def lease_rng_seed(spec: RolloutSpec, lease_id: int) -> int:
    """The jax PRNG seed a worker samples lease ``i``'s completions
    with — per-lease so duplicate executions against the same snapshot
    are byte-identical, offset from the prompt stream so prompts and
    samples never share a key."""
    digest = hashlib.sha256(
        f'{spec.seed}:{lease_id}:rollout'.encode('utf-8')).digest()
    return int.from_bytes(digest[:4], 'big')
