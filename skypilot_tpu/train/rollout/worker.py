"""Harvestable rollout worker: leases in, trajectory groups out.

A rollout worker holds NO state the learner depends on: its inputs
are the :class:`~skypilot_tpu.train.rollout.spec.RolloutSpec` it pulls
from the dispatcher, the lease ids it is granted, and whatever policy
snapshot is newest in ``spec.snapshot_dir`` when it looks. SIGKILL at
ANY point — mid-generation, mid-submit, between heartbeats — loses at
most the leases it held, which the dispatcher reaps and reassigns;
nothing about the learner's stream is corrupted (the chaos suite's
load-bearing invariant, tests/chaos/test_rollout_churn.py). That is
what makes the fleet harvestable: workers run as low-priority managed
jobs on spot capacity (examples/rl-harvest.yaml) and preemption is an
ordinary event, not a failure.

Topology independence comes from the snapshot path: policies are
published in the chunked, digest-verified checkpoint format
(``train/checkpoints``), and the worker restores through
``restore_newest(abstract)`` onto whatever device it has — the
learner's mesh shape never constrains where a rollout can run.

Per-lease determinism: the prompt AND the sampling RNG derive from
``(spec, lease_id)``, so a reassigned lease re-executed against the
same snapshot yields a byte-identical trajectory (at-least-once
duplicates are literal duplicates; the dispatcher keeps the first).
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.train.rollout import spec as spec_lib
from skypilot_tpu.train.rollout import telemetry
from skypilot_tpu.utils import backoff as backoff_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import framed

logger = sky_logging.init_logger(__name__)


# THE seed derivation for worker-style loops (shared with the
# data-service worker; utils/backoff owns it so the planes can't
# drift).
stable_seed = backoff_lib.stable_seed


class RolloutWorker:
    """One stateless rollout process: heartbeat + lease/generate loop."""

    def __init__(self, dispatcher_addr: Tuple[str, int], *,
                 worker_id: Optional[str] = None,
                 heartbeat_interval: float = 2.0,
                 register_timeout: float = 60.0,
                 rpc_timeout: float = 10.0,
                 leases_per_round: int = 1):
        self.worker_id = worker_id or f'rw-{uuid.uuid4().hex[:8]}'
        self._dispatcher_addr = dispatcher_addr
        self._heartbeat_interval = heartbeat_interval
        self._register_timeout = register_timeout
        self._rpc_timeout = rpc_timeout
        self._leases_per_round = max(1, leases_per_round)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._spec: Optional[spec_lib.RolloutSpec] = None
        self._latest_version = -1     # newest announced by the learner
        self._held_version = -1       # version of the params we hold
        self._seed = stable_seed(self.worker_id)
        # Model state, built lazily on the run loop (jax import +
        # compile must not block registration/heartbeats).
        self._cfg = None
        self._mod = None
        self._dec = None
        self._params = None
        self._reward_fn = None
        self._lp_fn = None
        self._ckpt = None
        # One persistent connection per owning thread (the framed
        # idiom): heartbeats must not share a socket with a main loop
        # that may be mid-request when the heartbeat fires.
        self._hb_conn = framed.FramedClient(dispatcher_addr)
        self._main_conn = framed.FramedClient(dispatcher_addr)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f'{self.worker_id}-heartbeat')

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'RolloutWorker':
        self._register(self._hb_conn, deadline_s=self._register_timeout)
        self._hb_thread.start()
        logger.info(f'rollout worker {self.worker_id} registered with '
                    f'dispatcher {self._dispatcher_addr[0]}:'
                    f'{self._dispatcher_addr[1]}')
        return self

    def stop(self) -> None:
        self._stop.set()
        self._hb_thread.join(timeout=5.0)
        self._hb_conn.close()
        self._main_conn.close()

    def _register(self, conn: framed.FramedClient,
                  deadline_s: float) -> None:
        deadline = time.monotonic() + deadline_s
        boff = backoff_lib.Backoff(base=0.2, cap=2.0, seed=self._seed)
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                reply, _ = conn.request(
                    {'op': 'register', 'worker_id': self.worker_id},
                    timeout=self._rpc_timeout)
                self._adopt(reply)
                return
            except (framed.ProtocolError, framed.RemoteError,
                    OSError) as e:
                last_err = e
                boff.sleep()
        raise TimeoutError(
            f'rollout worker {self.worker_id} could not register with '
            f'dispatcher at {self._dispatcher_addr} within '
            f'{deadline_s}s: {last_err}')

    def _adopt(self, reply: Dict[str, Any]) -> None:
        with self._lock:
            version = int(reply.get('snapshot_version', -1))
            if version > self._latest_version:
                self._latest_version = version
            if self._spec is None and reply.get('spec') is not None:
                self._spec = spec_lib.RolloutSpec.from_json(
                    reply['spec'])

    # ------------------------------------------------------ heartbeats

    def _heartbeat_loop(self) -> None:
        boff = backoff_lib.Backoff(base=0.2, cap=5.0, seed=self._seed)
        while not self._stop.wait(self._heartbeat_interval):
            try:
                with self._lock:
                    have_spec = self._spec is not None
                reply, _ = self._hb_conn.request(
                    {'op': 'heartbeat', 'worker_id': self.worker_id,
                     'have_spec': have_spec},
                    timeout=self._rpc_timeout)
                if reply.get('resync'):
                    # Dispatcher declared us LOST: rejoin. Our old
                    # leases were reassigned — at-least-once makes the
                    # interim double-ownership harmless.
                    self._register(self._hb_conn,
                                   deadline_s=self._register_timeout)
                else:
                    self._adopt(reply)
                boff.reset()
            except (framed.ProtocolError, framed.RemoteError,
                    OSError, TimeoutError) as e:
                logger.warning(f'rollout worker {self.worker_id} '
                               f'heartbeat failed: {e}')
                boff.sleep()

    # ------------------------------------------------------ model side

    def _ensure_model(self) -> bool:
        """Build model/reward/checkpointer once a spec is known.
        Returns False while the spec has not arrived yet."""
        with self._lock:
            spec = self._spec
        if spec is None:
            return False
        if self._cfg is not None:
            return True
        from skypilot_tpu import models as models_lib
        from skypilot_tpu.models import decode as decode_lib
        from skypilot_tpu.models import mla as mla_lib
        from skypilot_tpu.train import checkpoints
        from skypilot_tpu.train import grpo
        cfg = models_lib.get_config(spec.model)
        if cfg.vocab_size != spec.vocab_size:
            raise ValueError(
                f'spec vocab_size={spec.vocab_size} disagrees with '
                f'model preset {spec.model!r} '
                f'(vocab_size={cfg.vocab_size}) — the prompt stream '
                f'would sample tokens the model cannot embed')
        self._cfg = cfg
        self._mod = models_lib.module_for(cfg)
        self._dec = (self._mod if isinstance(cfg, mla_lib.MLAConfig)
                     else decode_lib)
        self._reward_fn = grpo.resolve_reward(spec.reward, spec.eos_id)
        self._ckpt = checkpoints.Checkpointer(spec.snapshot_dir)
        import functools

        import jax
        self._lp_fn = jax.jit(functools.partial(
            grpo.token_logprobs, cfg=cfg, mod=self._mod,
            temperature=spec.temperature))
        return True

    def _ensure_snapshot(self) -> bool:
        """Fetch the newest policy snapshot when the learner announced
        one newer than what we hold. Returns True iff params are
        usable. Fetch failures (corrupt mid-GC step, injected
        ``rollout.snapshot_fetch`` fault) keep the old params — a
        stale policy degrades freshness, not correctness; the learner's
        staleness window judges the result."""
        with self._lock:
            latest = self._latest_version
        if self._params is not None and self._held_version >= latest:
            return True
        if latest < 0:
            return self._params is not None
        import jax

        from skypilot_tpu.train import checkpoints
        try:
            if failpoints.ACTIVE:
                failpoints.fire('rollout.snapshot_fetch')
            abstract = jax.eval_shape(
                lambda: self._mod.init_params(jax.random.PRNGKey(0),
                                              self._cfg))
            restored, version = self._ckpt.restore_newest(abstract)
            if restored is None:
                # Announced but not visible HERE yet (fresh shared
                # mount, dispatcher restarted with persisted meta
                # while the dir was cleaned): not an error — keep
                # whatever we hold and look again next loop.
                return self._params is not None
            self._params = jax.device_put(restored)
            self._held_version = int(version)
            logger.info(f'rollout worker {self.worker_id} holds policy '
                        f'snapshot v{self._held_version}')
            return True
        except (failpoints.FailpointError,
                checkpoints.CheckpointCorruptError, OSError,
                ValueError) as e:
            logger.warning(f'rollout worker {self.worker_id} snapshot '
                           f'fetch failed (keeping '
                           f'v{self._held_version}): {e}')
            return self._params is not None

    def _generate(self, lease_id: int) -> Dict[str, np.ndarray]:
        """One trajectory group for ``lease_id``: G completions,
        rewards, and behavior log-probs under the HELD snapshot."""
        import jax
        import jax.numpy as jnp
        spec = self._spec
        s, t, g = spec.prompt_len, spec.max_new_tokens, spec.group_size
        prompt = spec_lib.prompt_for(spec, lease_id)
        rep = jnp.asarray(np.repeat(prompt[None, :], g, axis=0))
        rng = jax.random.PRNGKey(
            spec_lib.lease_rng_seed(spec, lease_id))
        if failpoints.ACTIVE:
            failpoints.fire('rollout.generate')
        if spec.rollout_delay_s > 0:
            time.sleep(spec.rollout_delay_s)
        gen = self._dec.generate(
            self._params, rep, self._cfg, t, max_len=s + t,
            temperature=spec.temperature, eos_id=spec.eos_id, rng=rng)
        seq = jnp.concatenate([rep, gen], axis=1)
        lp_full, _ = self._lp_fn(self._params, seq)
        # Fixed-length prompts: completion token j sits at sequence
        # position s+j, scored by log-prob grid entry s+j-1.
        behavior_lp = jax.lax.stop_gradient(lp_full[:, s - 1:s - 1 + t])
        gen_np = np.asarray(jax.device_get(gen))
        rewards = np.asarray(
            [self._reward_fn(prompt, gen_np[i]) for i in range(g)],
            np.float32)
        return {'completions': gen_np.astype(np.int32),
                'rewards': rewards,
                'behavior_lp': np.asarray(jax.device_get(behavior_lp),
                                          np.float32)}

    # ------------------------------------------------------- main loop

    def _request(self, obj: Dict[str, Any],
                 arrays: Optional[framed.Arrays] = None
                 ) -> Dict[str, Any]:
        reply, _ = self._main_conn.request(obj, arrays=arrays,
                                           timeout=self._rpc_timeout)
        return reply

    def run(self) -> None:
        """Lease → generate → submit until stopped. Every failure mode
        is contained: transient RPC errors back off and retry, resync
        re-registers, a failed generation releases its lease."""
        boff = backoff_lib.Backoff(base=0.2, cap=5.0, seed=self._seed)
        while not self._stop.is_set():
            try:
                if not self._ensure_model() or \
                        not self._ensure_snapshot():
                    if self._stop.wait(0.2):
                        return
                    continue
                reply = self._request(
                    {'op': 'lease', 'worker_id': self.worker_id,
                     'max_n': self._leases_per_round,
                     'spec_fp': self._spec.fingerprint()})
                if reply.get('resync'):
                    self._register(self._main_conn,
                                   deadline_s=self._register_timeout)
                    continue
                version = int(reply.get('snapshot_version', -1))
                with self._lock:
                    if version > self._latest_version:
                        self._latest_version = version
                leases = list(reply.get('leases') or [])
                if not leases:
                    # Backpressure (learner behind) or a drained job:
                    # idle briefly, stay registered.
                    if self._stop.wait(0.2):
                        return
                    continue
                for lease_id in leases:
                    if self._stop.is_set():
                        return
                    self._serve_lease(int(lease_id))
                boff.reset()
            except (framed.ProtocolError, framed.RemoteError,
                    OSError, TimeoutError) as e:
                logger.warning(f'rollout worker {self.worker_id} '
                               f'lease round failed: {e}')
                boff.sleep()

    def _serve_lease(self, lease_id: int) -> None:
        t0 = time.perf_counter()
        try:
            traj = self._generate(lease_id)
        except Exception as e:  # noqa: BLE001 — containment, see below
            # ANY generation/reward failure — injected fault, device
            # error, a user reward_fn raising on one completion —
            # hands the lease back NOW so a healthy worker picks it
            # up, and the worker lives on to serve the next lease.
            # One bad completion must cost one re-lease, never a
            # fleet member (the reaper's lease timeout would contain
            # a crash too, but slower and with a dead worker).
            logger.warning(f'rollout worker {self.worker_id} failed '
                           f'lease {lease_id}: {e!r}; releasing')
            try:
                self._request({'op': 'release',
                               'worker_id': self.worker_id,
                               'lease_id': lease_id})
            except (framed.ProtocolError, framed.RemoteError,
                    OSError):
                pass   # reaper's lease timeout is the backstop
            return
        telemetry.GENERATE_SECONDS.observe(time.perf_counter() - t0)
        submit = {'op': 'submit', 'worker_id': self.worker_id,
                  'lease_id': lease_id,
                  'snapshot_version': self._held_version,
                  'spec_fp': self._spec.fingerprint()}
        for attempt in (0, 1):
            try:
                self._request(submit, arrays=traj)
                return
            except framed.RemoteError as e:
                # The dispatcher ANSWERED — it decided the lease's
                # fate (refusal or duplicate); retrying or releasing
                # would fight its decision.
                logger.warning(f'rollout worker {self.worker_id} '
                               f'submit of lease {lease_id} refused: '
                               f'{e}')
                return
            except (framed.ProtocolError, OSError,
                    TimeoutError) as e:
                # Transient wire failure: one reconnect-retry (the
                # trajectory in hand is real work), then hand the
                # lease back rather than stranding it LEASED until
                # the lease timeout.
                logger.warning(f'rollout worker {self.worker_id} '
                               f'submit of lease {lease_id} failed '
                               f'(attempt {attempt + 1}): {e}')
                if attempt == 0:
                    time.sleep(0.2)
        try:
            self._request({'op': 'release',
                           'worker_id': self.worker_id,
                           'lease_id': lease_id})
        except (framed.ProtocolError, framed.RemoteError, OSError,
                TimeoutError):
            pass   # reaper's lease timeout is the backstop
