"""Stable GRPO learner fed by the harvested rollout fleet.

The learner is the plane's ONE stable node: it owns the policy
(``train/grpo`` update math over a ``train_lib.TrainState``),
publishes snapshots for the fleet through the chunked checkpoint
format (``train/checkpoints`` — satellite contract: NO ad-hoc
serialization anywhere in this plane), and consumes trajectory groups
from the dispatcher with every failure mode contained:

  * **bounded prefetch** — a collect thread fills a bounded queue;
    a dead dispatcher connection is dropped and redialed under seeded
    backoff (drop-route-and-retry, the data-service client idiom);
  * **staleness window** — every trajectory carries the snapshot
    version that generated it; groups older than ``max_staleness``
    versions are dropped (counted + journaled) instead of silently
    training on ancient behavior;
  * **graceful degradation** — losing ANY subset of workers slows
    trajectory arrival, so the learner steps slower; it stalls loudly
    (``RolloutStallError``) only when NOTHING arrives for the whole
    stall budget;
  * **replayable stream** — every consumed batch is journaled to a
    trajectory log BEFORE the update; :func:`replay_losses` over the
    same log reproduces the loss trajectory bit-equal (the chaos
    suite's acceptance pin);
  * **clean preemption** — the learner itself runs under the
    trainer's ``_PreemptionWatch``: one synchronous final state save,
    a ``{"preempted": true}`` log line, resume via
    ``restore_newest`` on whatever device the relaunch lands on.

``mesh=None`` (the default) runs the whole learner single-device with
no ambient-mesh APIs — the churn-trainer idiom, and the CPU-proxy
path the chaos suite and ``bench.py rl_harvest`` measure.
"""
from __future__ import annotations

import collections
import glob
import json
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import journal
from skypilot_tpu.train.rollout import spec as spec_lib
from skypilot_tpu.train.rollout import telemetry
from skypilot_tpu.utils import backoff as backoff_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import framed
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

DEFAULT_STALL_BUDGET_S = knobs.get_float('SKYTPU_ROLLOUT_STALL_BUDGET')


class RolloutStallError(RuntimeError):
    """No trajectory arrived within the stall budget."""


# ------------------------------------------------------- shared pieces
# Module-level (not methods) so the live learner and the offline
# replay run the IDENTICAL assembly/update code — bit-equal replay is
# a property of sharing these functions, not of careful duplication.

def _grpo_pieces(spec: spec_lib.RolloutSpec, mesh, learning_rate: float,
                 total_steps: int):
    """(cfg, mod, gcfg, tx, update_fn, ref_lp_fn) for a spec.
    ``ref_lp_fn`` is the JITTED reference-logprob forward (None when
    the KL tether is off) — the hot learner loop must not dispatch a
    full model forward op-by-op every step."""
    import functools

    import jax

    from skypilot_tpu import models as models_lib
    from skypilot_tpu.train import grpo, train_lib
    cfg = models_lib.get_config(spec.model)
    if cfg.vocab_size != spec.vocab_size:
        raise ValueError(
            f'spec vocab_size={spec.vocab_size} disagrees with model '
            f'preset {spec.model!r} (vocab_size={cfg.vocab_size})')
    mod = models_lib.module_for(cfg)
    gcfg = grpo.GRPOConfig(
        group_size=spec.group_size,
        max_new_tokens=spec.max_new_tokens,
        temperature=spec.temperature, clip_eps=spec.clip_eps,
        kl_coef=spec.kl_coef)
    tx = train_lib.default_optimizer(
        learning_rate=learning_rate, warmup_steps=1,
        total_steps=max(2, total_steps + 1))
    update = grpo.make_grpo_update(cfg, mesh, tx, gcfg, mod,
                                   use_ref=spec.kl_coef > 0.0)
    ref_lp_fn = None
    if spec.kl_coef > 0.0:
        ref_lp_fn = jax.jit(functools.partial(
            grpo.token_logprobs, cfg=cfg, mod=mod,
            temperature=spec.temperature))
    return cfg, mod, gcfg, tx, update, ref_lp_fn


def _init_state(spec: spec_lib.RolloutSpec, cfg, mod, tx, mesh):
    """Fresh policy TrainState. ``mesh=None`` builds it single-device
    with plain jits (no sharding APIs)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.train import train_lib
    if mesh is not None:
        return train_lib.init_train_state(
            jax.random.PRNGKey(spec.seed), cfg, mesh, tx)
    params = jax.jit(
        lambda r: mod.init_params(r, cfg))(jax.random.PRNGKey(spec.seed))
    opt_state = jax.jit(tx.init)(params)
    return train_lib.TrainState(step=jnp.zeros((), jnp.int32),
                                params=params, opt_state=opt_state)


def _abstract_state(spec: spec_lib.RolloutSpec, cfg, mod, tx, mesh):
    """Restore target matching :func:`_init_state`'s tree."""
    import jax
    import jax.numpy as jnp
    if mesh is not None:
        from skypilot_tpu.train import checkpoints
        return checkpoints.abstract_train_state(cfg, mesh, tx)

    from skypilot_tpu.train import train_lib

    def build():
        params = mod.init_params(jax.random.PRNGKey(spec.seed), cfg)
        return train_lib.TrainState(step=jnp.zeros((), jnp.int32),
                                    params=params,
                                    opt_state=tx.init(params))

    return jax.eval_shape(build)


def _assemble_batch(spec: spec_lib.RolloutSpec, gcfg,
                    groups: List[Dict[str, Any]]):
    """Trajectory groups → the ``make_grpo_update`` argument tuple.

    One group = one prompt's G completions (the GRPO baseline group);
    batches stack groups along the row dim ([B·G, ...]), exactly the
    shapes ``GRPOTrainer.iteration`` feeds the same update."""
    import jax.numpy as jnp

    from skypilot_tpu.train import grpo
    s, t, g = spec.prompt_len, spec.max_new_tokens, spec.group_size
    b = len(groups)
    prompts = np.stack([spec_lib.prompt_for(spec, int(grp['lease_id']))
                        for grp in groups])                    # [B, S]
    rep = np.repeat(prompts, g, axis=0)                        # [B·G, S]
    gens = np.concatenate(
        [np.asarray(grp['completions'], np.int32)
         for grp in groups], axis=0)                           # [B·G, T]
    behavior_lp = np.concatenate(
        [np.asarray(grp['behavior_lp'], np.float32)
         for grp in groups], axis=0)
    rewards = np.concatenate(
        [np.asarray(grp['rewards'], np.float32) for grp in groups],
        axis=0)
    seq = jnp.asarray(np.concatenate([rep, gens], axis=1))
    comp_idx = jnp.asarray(
        np.broadcast_to(np.arange(t, dtype=np.int32) + s - 1,
                        (b * g, t)).copy())
    mask = grpo.completion_mask(jnp.asarray(gens), spec.eos_id)
    adv = grpo.group_advantages(jnp.asarray(rewards), g, gcfg.adv_eps)
    return seq, comp_idx, jnp.asarray(behavior_lp), adv, mask


def _log_path(log_dir: str, step: int) -> str:
    return os.path.join(log_dir, f'traj_{step:06d}.npz')


def _write_log_step(log_dir: str, step: int,
                    groups: List[Dict[str, Any]]) -> None:
    path = _log_path(log_dir, step)
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:   # file handle: savez won't append .npz
        np.savez(
            f,
            lease_ids=np.asarray([g['lease_id'] for g in groups],
                                 np.int64),
            versions=np.asarray([g['version'] for g in groups],
                                np.int64),
            completions=np.stack([g['completions'] for g in groups]),
            rewards=np.stack([g['rewards'] for g in groups]),
            behavior_lp=np.stack([g['behavior_lp'] for g in groups]))
    os.replace(tmp, path)   # a log step exists iff it is complete


def _read_log_step(path: str) -> List[Dict[str, Any]]:
    with np.load(path) as z:
        return [{'lease_id': int(z['lease_ids'][i]),
                 'version': int(z['versions'][i]),
                 'completions': z['completions'][i],
                 'rewards': z['rewards'][i],
                 'behavior_lp': z['behavior_lp'][i]}
                for i in range(z['lease_ids'].shape[0])]


def replay_losses(spec: spec_lib.RolloutSpec, log_dir: str, *,
                  learning_rate: float, total_steps: int,
                  mesh=None) -> List[float]:
    """Re-run the learner's update sequence over a journaled
    trajectory log. Same spec + same log ⇒ the SAME jitted programs
    see the SAME inputs in the SAME order — the returned losses match
    the live run bit-for-bit (the chaos suite's replay pin)."""
    cfg, mod, gcfg, tx, update, ref_lp_fn = _grpo_pieces(
        spec, mesh, learning_rate, total_steps)
    state = _init_state(spec, cfg, mod, tx, mesh)
    ref = _ref_params(state) if ref_lp_fn is not None else None
    losses: List[float] = []
    for path in sorted(glob.glob(os.path.join(log_dir, 'traj_*.npz'))):
        groups = _read_log_step(path)
        batch = _assemble_batch(spec, gcfg, groups)
        ref_lp = _ref_logprobs(ref_lp_fn, ref, batch) \
            if ref is not None else None
        state, metrics = update(state, *batch, ref_lp=ref_lp)
        losses.append(float(metrics['loss']))
    return losses


def _ref_params(state):
    import jax
    import jax.numpy as jnp
    # A REAL copy: the update donates the policy buffers.
    return jax.tree.map(jnp.copy, state.params)


def _ref_logprobs(ref_lp_fn, ref_params, batch):
    import jax
    import jax.numpy as jnp
    seq, comp_idx = batch[0], batch[1]
    lp_full, _ = ref_lp_fn(ref_params, seq)
    return jax.lax.stop_gradient(
        jnp.take_along_axis(lp_full, comp_idx, axis=1))


class RolloutLearner:
    """The stable node: collect → filter → update → publish, iterated."""

    def __init__(self, spec: spec_lib.RolloutSpec,
                 dispatcher_addr: Tuple[str, int], *,
                 total_steps: int,
                 groups_per_step: int = 2,
                 publish_every: int = 4,
                 max_staleness: int = 4,
                 learning_rate: float = 1e-4,
                 snapshot_max_to_keep: int = 4,
                 state_dir: Optional[str] = None,
                 traj_log_dir: Optional[str] = None,
                 mesh=None,
                 rpc_timeout: float = 10.0,
                 stall_budget_s: float = DEFAULT_STALL_BUDGET_S,
                 warmup: bool = True,
                 on_step=None):
        from skypilot_tpu.train import checkpoints
        self.spec = spec
        self._addr = dispatcher_addr
        self.total_steps = total_steps
        self._groups_per_step = max(1, groups_per_step)
        self._publish_every = max(1, publish_every)
        self._max_staleness = max(0, max_staleness)
        self._mesh = mesh
        self._rpc_timeout = rpc_timeout
        self._stall_budget_s = stall_budget_s
        self._warmup_wanted = warmup
        self._on_step = on_step
        self._stop = threading.Event()
        self._queue: 'queue.Queue[Dict[str, Any]]' = queue.Queue(
            maxsize=max(2, 4 * self._groups_per_step))
        (self._cfg, self._mod, self._gcfg, self._tx, self._update,
         self._ref_lp_fn) = _grpo_pieces(spec, mesh, learning_rate,
                                         total_steps)
        self.state = _init_state(spec, self._cfg, self._mod, self._tx,
                                 mesh)
        # KL reference = the SEED-INITIAL policy, captured BEFORE any
        # checkpoint resume overwrites self.state — the tether anchors
        # to where training started, and replay_losses derives its
        # reference the same way (resume must not move the anchor or
        # the replay contract breaks).
        self._ref = (_ref_params(self.state)
                     if spec.kl_coef > 0.0 else None)
        self.start_step = 0
        self._state_ckpt = None
        if state_dir:
            self._state_ckpt = checkpoints.Checkpointer(
                state_dir, max_to_keep=2)
            if self._state_ckpt.latest_step() is not None:
                import jax
                abstract = _abstract_state(spec, self._cfg, self._mod,
                                           self._tx, mesh)
                restored, step = self._state_ckpt.restore_newest(
                    abstract)
                self.state = (jax.device_put(restored) if mesh is None
                              else restored)
                self.start_step = int(step)
                logger.info(f'rollout learner resumed at step '
                            f'{self.start_step} from {state_dir}')
        # Snapshot publishing: THE checkpoint format, size-bounded so
        # a week-long harvest cannot fill the disk (satellite
        # contract: max_to_keep retention on the snapshot dir).
        self._snap_ckpt = checkpoints.Checkpointer(
            spec.snapshot_dir, max_to_keep=snapshot_max_to_keep,
            async_save=False)
        self._version = -1
        self._traj_log_dir = traj_log_dir
        if traj_log_dir:
            os.makedirs(traj_log_dir, exist_ok=True)
        self._ctrl = framed.FramedClient(dispatcher_addr)
        self._collect_thread = threading.Thread(
            target=self._collect_loop, daemon=True,
            name='rollout-learner-collect')
        # Accounting the harness/bench read after a run.
        self.history: List[Dict[str, float]] = []
        self.step_walls: List[float] = []
        self.samples_total = 0
        self.stale_dropped = 0
        self.staleness_seen: List[int] = []

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'RolloutLearner':
        """Register the spec, publish the initial policy snapshot, and
        start collecting. Retries until the dispatcher answers (it may
        still be booting) within the stall budget."""
        deadline = time.monotonic() + self._stall_budget_s
        boff = backoff_lib.Backoff(base=0.2, cap=2.0,
                                   seed=self.spec.seed)
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                self._ctrl.request(
                    {'op': 'put_spec', 'spec': self.spec.to_json()},
                    timeout=self._rpc_timeout)
                break
            except framed.RemoteError as e:
                if e.kind in ('spec', 'spec_mismatch'):
                    raise   # config refusal: retrying cannot heal it
                last_err = e
                boff.sleep()
            except (framed.ProtocolError, OSError) as e:
                last_err = e
                boff.sleep()
        else:
            raise RolloutStallError(
                f'dispatcher at {self._addr} unreachable for '
                f'{self._stall_budget_s}s: {last_err}')
        # Workers need a policy before the first lease is useful.
        self._publish(self.start_step // self._publish_every)
        self._collect_thread.start()
        if self._warmup_wanted:
            self._warmup()
        return self

    def _warmup(self) -> None:
        """Compile the update program on a zero batch + THROWAWAY
        state before the loop starts. Without this the fleet banks
        result_cap groups during the first step's multi-second
        compile, and every throughput window that drains them reads
        as super-production-rate — poisoning the degradation/recovery
        measurements the chaos proof and bench key on."""
        import jax.numpy as jnp
        s, t, g = (self.spec.prompt_len, self.spec.max_new_tokens,
                   self.spec.group_size)
        b = self._groups_per_step * g
        throwaway = _init_state(self.spec, self._cfg, self._mod,
                                self._tx, self._mesh)
        zeros = (jnp.zeros((b, s + t), jnp.int32),
                 jnp.zeros((b, t), jnp.int32),
                 jnp.zeros((b, t), jnp.float32),
                 jnp.zeros((b,), jnp.float32),
                 jnp.zeros((b, t), jnp.float32))
        ref_lp = (jnp.zeros((b, t), jnp.float32)
                  if self._ref is not None else None)
        self._update(throwaway, *zeros, ref_lp=ref_lp)

    def close(self) -> None:
        self._stop.set()
        if self._collect_thread.is_alive():
            self._collect_thread.join(timeout=5.0)
        self._ctrl.close()
        if self._state_ckpt is not None:
            self._state_ckpt.close()
        self._snap_ckpt.close()

    def __enter__(self) -> 'RolloutLearner':
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------ publishing

    def _publish(self, version: int) -> bool:
        """Snapshot the CURRENT policy params as ``version`` and
        announce it. Failure (injected ``rollout.publish`` fault, a
        dispatcher blip) is contained: workers keep generating against
        the previous snapshot and the next cadence retries — freshness
        degrades, the run never dies."""
        try:
            if failpoints.ACTIVE:
                failpoints.fire('rollout.publish')
            self._snap_ckpt.save(self.state.params, version, wait=True)
            self._ctrl.request({'op': 'publish', 'version': version},
                               timeout=self._rpc_timeout)
            self._version = max(self._version, version)
            return True
        except (failpoints.FailpointError, framed.ProtocolError,
                framed.RemoteError, OSError) as e:
            logger.warning(f'rollout learner: publish v{version} '
                           f'failed (fleet keeps v{self._version}): '
                           f'{e}')
            return False

    # ------------------------------------------------------ collecting

    def _collect_loop(self) -> None:
        conn: Optional[framed.FramedClient] = None
        boff = backoff_lib.Backoff(base=0.2, cap=2.0,
                                   seed=self.spec.seed ^ 0x5eed)
        # At-least-once bookkeeping: ack what we RECEIVED so the
        # dispatcher retires it, and dedupe re-deliveries (reply
        # arrived, ack lost) by lease_id — leases complete exactly
        # once, so the id is a sufficient key. The seen-set is
        # bounded: an id older than the window can never reappear
        # (the dispatcher re-delivers only its last reply's groups).
        ack: List[int] = []
        seen: 'collections.OrderedDict[int, None]' = (
            collections.OrderedDict())
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = framed.FramedClient(self._addr)
                reply, arrays = conn.request(
                    {'op': 'collect',
                     'max_n': 2 * self._groups_per_step,
                     'ack': ack},
                    timeout=self._rpc_timeout)
                metas = list(reply.get('trajectories') or [])
                ack = [int(m['lease_id']) for m in metas]
                if not metas:
                    if self._stop.wait(0.05):
                        return
                    continue
                for i, meta in enumerate(metas):
                    lease_id = int(meta['lease_id'])
                    if lease_id in seen:
                        continue   # re-delivery of an already-consumed group
                    seen[lease_id] = None
                    while len(seen) > 256:
                        seen.popitem(last=False)
                    traj = {'lease_id': lease_id,
                            'version': int(meta['version']),
                            'completions': arrays[f'completions_{i}'],
                            'rewards': arrays[f'rewards_{i}'],
                            'behavior_lp': arrays[f'behavior_lp_{i}']}
                    while not self._stop.is_set():
                        try:
                            self._queue.put(traj, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                boff.reset()
            except (framed.ProtocolError, framed.RemoteError, OSError,
                    KeyError) as e:
                # Drop the route, redial, retry — the dispatcher may
                # be restarting; its sqlite state survives.
                logger.warning(f'rollout learner collect failed: {e}')
                if conn is not None:
                    conn.close()
                    conn = None
                boff.sleep()
        if conn is not None:
            conn.close()

    def _gather(self) -> List[Dict[str, Any]]:
        """Block until a full batch of FRESH groups is available.
        Stale groups (version lag > max_staleness) are dropped and
        counted — the off-policy window is a hard bound, not advice.
        The stall deadline resets on every ACCEPTED group: the budget
        bounds uselessness, not batch-assembly time — a degraded
        fleet trickling one fresh group per minute is slow, while a
        fleet producing nothing (or nothing fresh) is stalled."""
        groups: List[Dict[str, Any]] = []
        deadline = time.monotonic() + self._stall_budget_s
        while len(groups) < self._groups_per_step:
            if self._stop.is_set():
                raise RolloutStallError('learner stopped mid-gather')
            try:
                traj = self._queue.get(timeout=0.2)
            except queue.Empty:
                if time.monotonic() >= deadline:
                    raise RolloutStallError(
                        f'no USABLE trajectory within the '
                        f'{self._stall_budget_s}s stall budget — '
                        f'fleet gone, or producing only stale '
                        f'groups?') from None
                continue
            lag = max(0, self._version - int(traj['version']))
            telemetry.STALENESS.observe(float(lag))
            self.staleness_seen.append(lag)
            if lag > self._max_staleness:
                telemetry.STALE_DROPPED.inc()
                self.stale_dropped += 1
                journal.record_event(
                    'rollout_stale_drop', 'learner',
                    data={'lease_id': traj['lease_id'],
                          'version': traj['version'],
                          'current': self._version})
                continue
            groups.append(traj)
            # Deadline resets on ACCEPTED groups only: a trickling
            # degraded fleet is slow, not stalled — but a fleet
            # producing nothing but too-stale groups can never make
            # progress and must still stall loudly.
            deadline = time.monotonic() + self._stall_budget_s
            telemetry.TRAJECTORIES.inc(role='learner')
        telemetry.QUEUE_DEPTH.set(float(self._queue.qsize()),
                                  role='learner')
        return groups

    # -------------------------------------------------------- stepping

    def run(self) -> List[Dict[str, float]]:
        """The learner loop. Returns per-step history (loss, reward,
        samples). Preemption (SIGTERM / ``trainer.preempt`` failpoint)
        exits cleanly with a final synchronous state save."""
        from skypilot_tpu.train import trainer as trainer_mod
        with trainer_mod._PreemptionWatch() as watch:
            for step in range(self.start_step, self.total_steps):
                t0 = time.perf_counter()
                groups = self._gather()
                if self._traj_log_dir:
                    _write_log_step(self._traj_log_dir, step, groups)
                batch = _assemble_batch(self.spec, self._gcfg, groups)
                ref_lp = (_ref_logprobs(self._ref_lp_fn, self._ref,
                                        batch)
                          if self._ref is not None else None)
                self.state, metrics = self._update(self.state, *batch,
                                                   ref_lp=ref_lp)
                wall = time.perf_counter() - t0
                samples = len(groups) * self.spec.group_size
                self.samples_total += samples
                telemetry.SAMPLES.inc(samples)
                telemetry.STEP_SECONDS.observe(wall)
                self.step_walls.append(time.monotonic())
                rec = {'step': step + 1,
                       'loss': float(metrics['loss']),
                       'mean_reward': float(np.mean(np.concatenate(
                           [g['rewards'] for g in groups]))),
                       'samples': samples,
                       'sec_per_step': round(wall, 4)}
                self.history.append(rec)
                logger.info(json.dumps(
                    {k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in rec.items()}))
                if (step + 1) % self._publish_every == 0:
                    self._publish((step + 1) // self._publish_every)
                if self._state_ckpt is not None and \
                        (step + 1) % self._publish_every == 0:
                    self._state_ckpt.save(self.state, step + 1)
                if self._on_step is not None:
                    self._on_step(step)
                if watch.preempted:
                    if self._state_ckpt is not None:
                        self._state_ckpt.save(self.state, step + 1,
                                              wait=True)
                    logger.info(json.dumps(
                        {'step': step + 1, 'preempted': True,
                         'final_checkpoint':
                             self._state_ckpt is not None}))
                    return self.history
        if self._state_ckpt is not None:
            self._state_ckpt.save(self.state, self.total_steps,
                                  wait=True)
        return self.history

    # ------------------------------------------------------ accounting

    def report(self) -> Dict[str, Any]:
        """Run-level accounting the harness/bench layers on top."""
        stale = self.staleness_seen
        return {
            'steps': len(self.history),
            'samples_total': self.samples_total,
            'stale_dropped': self.stale_dropped,
            'staleness_p50': float(np.percentile(stale, 50))
            if stale else None,
            'staleness_p95': float(np.percentile(stale, 95))
            if stale else None,
            'snapshot_version': self._version,
        }
