"""CLI: ``python -m skypilot_tpu.train.rollout dispatcher|worker|learner``.

Rollout workers are low-priority managed jobs to the control plane —
see examples/rl-harvest.yaml for the gang wiring (dispatcher + learner
on the stable on-demand slice, workers harvesting spot capacity). All
subcommands print one JSON readiness line to stdout (role, address,
identity) so a supervising task — or a chaos test — can harvest the
endpoint; dispatcher and worker then serve until SIGTERM/SIGINT.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from typing import List, Optional

from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs


def _serve_until_signal(on_stop=None) -> None:
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if on_stop is not None:
        on_stop()


def main(argv: Optional[List[str]] = None) -> int:
    failpoints.load_env()
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.train.rollout',
        description='Spot-harvesting RL plane '
                    '(docs/ROBUSTNESS.md, "Harvested RL plane").')
    sub = parser.add_subparsers(dest='cmd', required=True)

    disp = sub.add_parser('dispatcher',
                          help='worker registry + prompt leases')
    disp.add_argument('--host', default='0.0.0.0')
    disp.add_argument('--port', type=int, default=8480)
    disp.add_argument('--db', default='~/.skytpu/rollout/dispatcher.db')
    disp.add_argument('--heartbeat-timeout', type=float,
                      default=knobs.get_float(
                          'SKYTPU_ROLLOUT_HEARTBEAT_TIMEOUT'))
    disp.add_argument('--lease-timeout', type=float,
                      default=knobs.get_float(
                          'SKYTPU_ROLLOUT_LEASE_TIMEOUT'))
    disp.add_argument('--max-outstanding', type=int,
                      default=knobs.get_int(
                          'SKYTPU_ROLLOUT_MAX_OUTSTANDING'))

    work = sub.add_parser('worker', help='harvestable rollout worker')
    work.add_argument('--dispatcher', required=True,
                      help='host:port of the rollout dispatcher')
    work.add_argument('--worker-id', default=None)
    work.add_argument('--heartbeat-interval', type=float, default=2.0)
    work.add_argument('--leases-per-round', type=int, default=1)

    learn = sub.add_parser('learner', help='stable GRPO learner')
    learn.add_argument('--dispatcher', required=True)
    learn.add_argument('--model', default='llama-debug')
    learn.add_argument('--reward', required=True,
                       help='count_token:ID | length | module:function')
    learn.add_argument('--snapshot-dir', required=True,
                       help='shared dir for policy snapshots (workers '
                            'restore from it)')
    learn.add_argument('--steps', type=int, default=100)
    learn.add_argument('--groups-per-step', type=int, default=2)
    learn.add_argument('--group-size', type=int, default=4)
    learn.add_argument('--prompt-len', type=int, default=16)
    learn.add_argument('--max-new-tokens', type=int, default=16)
    learn.add_argument('--temperature', type=float, default=1.0)
    learn.add_argument('--kl-coef', type=float, default=0.0)
    learn.add_argument('--lr', type=float, default=1e-4)
    learn.add_argument('--eos-id', type=int, default=None)
    learn.add_argument('--seed', type=int, default=0)
    learn.add_argument('--publish-every', type=int, default=4)
    learn.add_argument('--max-staleness', type=int, default=4)
    learn.add_argument('--snapshot-keep', type=int, default=4)
    learn.add_argument('--state-dir', default=None,
                       help='learner TrainState checkpoints '
                            '(preemption resume)')
    learn.add_argument('--traj-log', default=None,
                       help='journaled trajectory log dir (replay)')

    args = parser.parse_args(argv)

    if args.cmd == 'dispatcher':
        from skypilot_tpu.train.rollout import dispatcher as disp_lib
        d = disp_lib.RolloutDispatcher(
            os.path.expanduser(args.db), host=args.host, port=args.port,
            heartbeat_timeout=args.heartbeat_timeout,
            lease_timeout=args.lease_timeout,
            max_outstanding=args.max_outstanding).start()
        print(json.dumps({'role': 'dispatcher',
                          'addr': f'{d.addr[0]}:{d.addr[1]}'}),
              flush=True)
        _serve_until_signal(d.stop)
        return 0

    if args.cmd == 'worker':
        from skypilot_tpu.utils import jax_utils
        jax_utils.pin_platform_from_env()
        from skypilot_tpu.train.rollout import worker as worker_lib
        from skypilot_tpu.utils import framed
        w = worker_lib.RolloutWorker(
            framed.parse_addr(args.dispatcher),
            worker_id=args.worker_id,
            heartbeat_interval=args.heartbeat_interval,
            leases_per_round=args.leases_per_round).start()
        print(json.dumps({'role': 'worker',
                          'worker_id': w.worker_id}), flush=True)
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: w.stop())
        try:
            w.run()
        finally:
            w.stop()
        return 0

    # learner
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    from skypilot_tpu import models as models_lib
    from skypilot_tpu.train.rollout import learner as learner_lib
    from skypilot_tpu.train.rollout import spec as spec_lib
    from skypilot_tpu.utils import framed
    cfg = models_lib.get_config(args.model)
    spec = spec_lib.RolloutSpec(
        model=args.model, reward=args.reward,
        snapshot_dir=os.path.expanduser(args.snapshot_dir),
        vocab_size=cfg.vocab_size, prompt_len=args.prompt_len,
        group_size=args.group_size,
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, kl_coef=args.kl_coef,
        eos_id=args.eos_id, seed=args.seed)
    learner = learner_lib.RolloutLearner(
        spec, framed.parse_addr(args.dispatcher),
        total_steps=args.steps,
        groups_per_step=args.groups_per_step,
        publish_every=args.publish_every,
        max_staleness=args.max_staleness,
        learning_rate=args.lr,
        snapshot_max_to_keep=args.snapshot_keep,
        state_dir=(os.path.expanduser(args.state_dir)
                   if args.state_dir else None),
        traj_log_dir=(os.path.expanduser(args.traj_log)
                      if args.traj_log else None))
    with learner:
        print(json.dumps({'role': 'learner',
                          'spec_fp': spec.fingerprint(),
                          'start_step': learner.start_step}),
              flush=True)
        learner.run()
        print(json.dumps({'role': 'learner', 'done': True,
                          **learner.report()}), flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
