"""Elastic wiring for the spot rollout fleet (docs/ELASTIC.md).

RLBoost's spot-economics play (PAPERS.md, PR 14) only pays off while
fleet size tracks what the learner can actually ABSORB: rollout
workers that outrun the learner fill the dispatcher's bounded result
buffer, and every trajectory past that point is compute the staleness
window will drop. This module declares the fleet's ElasticSpec:

  * signal — :meth:`RolloutDispatcher.result_backpressure`: result
    backlog plus live leases over buffer capacity, the exact quantity
    ``_op_lease`` mints headroom against;
  * target — an INVERTED hold band
    (`SKYTPU_ELASTIC_ROLLOUT_BACKLOG_LOW/HIGH`): backpressure above
    the band means the learner is behind → shrink the fleet BEFORE
    new leases are minted for doomed work; below the band the learner
    is keeping up → grow back toward max. Shrinking is the urgent
    direction here (the mirror of the data-worker pool), so the
    DOWNSCALE delay defaults to zero while growth waits out the
    upscale delay and the cooldown;
  * hooks — ``scale_up`` / ``scale_down`` add or retire workers (spot
    Tasks in production; harness RolloutWorker objects in tests — a
    retired worker just stops heartbeating and the lease reaper
    reassigns, the same at-least-once machinery preemption exercises).

Safety is the uniform elastic contract: an unreachable dispatcher is
NO SIGNAL → hold the fleet (never a guess).
"""
from __future__ import annotations

from typing import Callable, Optional

from skypilot_tpu.elastic import signals
from skypilot_tpu.elastic import spec as elastic_spec
from skypilot_tpu.utils import knobs


def backpressure_signal(dispatcher) -> signals.SignalFn:
    """In-process probe of the dispatcher's result-buffer fill share
    (always fresh — it reads the live buffer, not a scrape)."""
    return signals.callback(dispatcher.result_backpressure)


def fleet_spec(
        signal: signals.SignalFn, *,
        scale_up: Callable[[int], None],
        scale_down: Callable[[int], None],
        min_workers: int = 0,
        max_workers: Optional[int] = None,
        initial_workers: Optional[int] = None,
        band: Optional[tuple] = None,
        upscale_delay_seconds: float = 0.0,
        downscale_delay_seconds: float = 0.0,
) -> elastic_spec.ElasticSpec:
    """The rollout fleet's declared elastic contract."""
    if band is None:
        band = (knobs.get_float('SKYTPU_ELASTIC_ROLLOUT_BACKLOG_LOW'),
                knobs.get_float('SKYTPU_ELASTIC_ROLLOUT_BACKLOG_HIGH'))
    return elastic_spec.ElasticSpec(
        pool='rollout',
        signal=signal,
        band=band,
        # High backpressure → FEWER producers: the inverted band.
        invert=True,
        min_units=min_workers,
        max_units=max_workers,
        initial_units=initial_workers,
        upscale_delay_seconds=upscale_delay_seconds,
        downscale_delay_seconds=downscale_delay_seconds,
        cooldown_seconds=knobs.get_float(
            'SKYTPU_ELASTIC_COOLDOWN_SECONDS'),
        # clean_rounds gates the shrink direction; for this pool
        # shrinking is urgent, so flap resistance rides the upscale
        # delay/cooldown instead.
        clean_rounds=1,
        stale_after=knobs.get_float('SKYTPU_ELASTIC_STALE_SECONDS'),
        scale_up=scale_up,
        scale_down=scale_down)
