"""Shared metric declarations for the rollout plane.

One module owns every ``skytpu_rollout_*`` declaration (the
``data_service/telemetry.py`` precedent): dispatcher, worker and
learner all import from here, so two copy-pasted declarations can
never drift and break whichever module imports second.
Catalog: docs/OBSERVABILITY.md, "Harvested RL plane".
"""
from __future__ import annotations

from skypilot_tpu.observe import metrics as metrics_lib

WORKERS_UP = metrics_lib.gauge(
    'skytpu_rollout_workers_up',
    'Rollout workers currently ALIVE in the dispatcher registry')

LEASES = metrics_lib.counter(
    'skytpu_rollout_leases_total',
    'Prompt-lease events at the dispatcher',
    labels={'event': ('minted', 'leased', 'done', 'reassigned',
                      'duplicate', 'released')})

TRAJECTORIES = metrics_lib.counter(
    'skytpu_rollout_trajectories_total',
    'Completed trajectory groups by role (worker=submitted, '
    'learner=consumed)',
    labels={'role': ('worker', 'learner')})

SAMPLES = metrics_lib.counter(
    'skytpu_rollout_samples_total',
    'Completions consumed by the learner (trajectory groups x G)')

STALENESS = metrics_lib.histogram(
    'skytpu_rollout_staleness',
    'Snapshot-version lag (published - generating version) of each '
    'trajectory group at consumption',
    buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0))

STALE_DROPPED = metrics_lib.counter(
    'skytpu_rollout_stale_dropped_total',
    'Trajectory groups dropped for exceeding the staleness window')

SNAPSHOT_VERSION = metrics_lib.gauge(
    'skytpu_rollout_snapshot_version',
    'Latest policy snapshot version announced to the dispatcher')

QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_rollout_queue_depth',
    'Buffered trajectory groups awaiting consumption',
    labels={'role': ('dispatcher', 'learner')})

STEP_SECONDS = metrics_lib.histogram(
    'skytpu_rollout_step_seconds',
    'Learner wall-clock per optimizer step (gather + update)')

GENERATE_SECONDS = metrics_lib.histogram(
    'skytpu_rollout_generate_seconds',
    'Worker wall-clock per trajectory group (generate + score)')
