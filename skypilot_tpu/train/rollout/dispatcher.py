"""Rollout dispatcher: worker registry + prompt-lease state machine.

The control plane of the harvested-RL topology — it never runs a
model. It tracks rollout workers (heartbeats → ALIVE/LOST, the
``data_service`` registry idiom), owns the :class:`RolloutSpec` of the
job it serves, and runs the prompt-lease machine: every trajectory
group starts life as a lease (``PENDING``), is handed to exactly one
worker at a time (``LEASED``), and is completed exactly once
(``DONE``, first submission wins). Because a lease's prompt is a pure
function of ``(spec, lease_id)`` (``rollout/spec.py``), reassignment
is *at-least-once by construction*: handing a dead worker's leases to
a survivor — or to a worker that turns out to still be alive — can
duplicate rollout work but never corrupt the stream; the learner
consumes each completed group once.

Leases come back from the dead three ways, all funneled through the
guarded ``set_lease_status`` setter and journaled:

  * **worker loss** — the reaper marks silent workers LOST and moves
    their LEASED leases back to PENDING (``rollout_lease_reassign``
    with the orphaned lease ids, one event per lost worker — the
    chaos suite counts these against its kill schedule);
  * **orphan sweep** — LEASED leases owned by a non-ALIVE worker
    (a crash between the LOST write and its reassignment) rebalance
    on every reaper pass;
  * **lease timeout** — a wedged-but-heartbeating worker cannot sit
    on a lease forever.

State lives in WAL sqlite (``utils/sqlite_utils``; 3.34-safe, no
RETURNING). All status writes go through the guarded setters declared
in ``analysis/state_machines.py`` (enforced by the skylint
``state-machine`` checker) inside ``BEGIN IMMEDIATE`` transactions.
Completed trajectories are buffered in a BOUNDED in-memory queue for
the learner's ``collect`` — backpressure gates lease minting, so a
slow learner throttles the fleet instead of hoarding its output.
Delivery to the learner is at-least-once over the wire (unacked
collect replies re-deliver); a dispatcher CRASH, by contrast, loses
at most ``result_cap`` buffered groups whose leases are already DONE
— bounded wasted compute, never corruption or a stall (lease state
is durable, fresh leases keep flowing on restart). Persisting the
result buffer is deliberately out of scope: trajectories are
megabytes of npy per group and the window is seconds wide.
"""
from __future__ import annotations

import collections
import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.analysis import state_machines
from skypilot_tpu.observe import journal
from skypilot_tpu.train.rollout import spec as spec_lib
from skypilot_tpu.train.rollout import telemetry
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import framed
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

logger = sky_logging.init_logger(__name__)

DEFAULT_HEARTBEAT_TIMEOUT = knobs.get_float(
    'SKYTPU_ROLLOUT_HEARTBEAT_TIMEOUT')
DEFAULT_LEASE_TIMEOUT = knobs.get_float('SKYTPU_ROLLOUT_LEASE_TIMEOUT')
# Outstanding = minted-but-not-DONE leases. Bounds duplicated work
# after a mass preemption AND (with the result cap) the dispatcher's
# memory; the learner's consumption rate is the real throttle.
DEFAULT_MAX_OUTSTANDING = knobs.get_int('SKYTPU_ROLLOUT_MAX_OUTSTANDING')
DEFAULT_RESULT_CAP = knobs.get_int('SKYTPU_ROLLOUT_RESULT_CAP')
# DONE lease rows kept for accounting before the reaper GCs them.
_DONE_KEEP_ROWS = 10_000


class RolloutWorkerStatus(enum.Enum):
    """Registry state of one rollout worker (docs/STATE_MACHINES.md)."""
    ALIVE = 'ALIVE'
    LOST = 'LOST'


class RolloutLeaseStatus(enum.Enum):
    """Lifecycle of one prompt lease (docs/STATE_MACHINES.md)."""
    PENDING = 'PENDING'
    LEASED = 'LEASED'
    DONE = 'DONE'


def _connect(path: str) -> sqlite3.Connection:
    conn = sqlite_utils.connect_wal(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS workers (
            worker_id TEXT PRIMARY KEY,
            status TEXT,
            last_heartbeat REAL,
            joined_ts REAL
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS leases (
            lease_id INTEGER PRIMARY KEY,
            status TEXT,
            worker_id TEXT,
            assigned_ts REAL,
            attempts INTEGER DEFAULT 0
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS meta (
            key TEXT PRIMARY KEY,
            value TEXT
        )""")
    conn.commit()
    return conn


# ----------------------------------------------------- guarded setters

def set_rollout_worker_status(
        conn: sqlite3.Connection, worker_id: str,
        new: RolloutWorkerStatus, *,
        reason: Optional[str] = None,
        require_heartbeat_before: Optional[float] = None,
) -> Tuple[Optional[str], bool]:
    """THE worker-status write path (state-machine checker contract).

    Returns ``(old_status, changed)``. A missing row is created only
    for ``new == ALIVE`` (registration is the machine's entry point).
    ``require_heartbeat_before`` makes the reaper's LOST write
    conditional: a heartbeat landing between the reaper's scan and
    this transaction keeps the worker ALIVE (no stale kill). Journals
    ``rollout_worker_join`` / ``rollout_worker_lost`` exactly once per
    winning edge, inside the transaction.
    """
    now = time.time()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT status, last_heartbeat FROM workers '
            'WHERE worker_id = ?', (worker_id,)).fetchone()
        if row is None:
            if new is not RolloutWorkerStatus.ALIVE:
                return None, False
            conn.execute(
                'INSERT INTO workers (worker_id, status, '
                'last_heartbeat, joined_ts) VALUES (?, ?, ?, ?)',
                (worker_id, new.value, now, now))
            journal.record_event('rollout_worker_join', worker_id,
                                 reason=reason or 'register')
            return None, True
        old, last_hb = row
        if require_heartbeat_before is not None and \
                last_hb is not None and \
                last_hb >= require_heartbeat_before:
            return old, False
        if not state_machines.can_transition(
                state_machines.ROLLOUT_WORKER_TRANSITIONS, old,
                new.value):
            return old, False
        if old == new.value:
            # Self-loop: refresh liveness facts, no journal.
            conn.execute(
                'UPDATE workers SET last_heartbeat = ? '
                'WHERE worker_id = ?', (now, worker_id))
            return old, False
        conn.execute(
            'UPDATE workers SET status = ?, last_heartbeat = ? '
            'WHERE worker_id = ?', (new.value, now, worker_id))
        if new is RolloutWorkerStatus.ALIVE:
            journal.record_event('rollout_worker_join', worker_id,
                                 reason=reason or 'rejoin',
                                 data={'old': old})
        else:
            journal.record_event('rollout_worker_lost', worker_id,
                                 reason=reason, data={'old': old})
        return old, True


def set_lease_status(
        conn: sqlite3.Connection,
        changes: List[Tuple[int, 'RolloutLeaseStatus', Optional[str]]],
        *,
        require_owner: Optional[str] = None,
) -> List[Tuple[int, str, str]]:
    """THE lease-status write path: bulk edges in ONE transaction.

    ``changes`` is ``[(lease_id, new_status, worker_id)]`` —
    ``worker_id`` is the new owner for LEASED, ``None`` otherwise. A
    missing row is created only for ``new == PENDING`` (minting is
    the machine's entry point). Transitions not declared in
    ``ROLLOUT_LEASE_TRANSITIONS`` are refused silently (the caller's
    plan raced a faster writer — at-least-once semantics make that
    harmless). ``require_owner`` makes every edge conditional on the
    lease's CURRENT owner — a compare-and-set inside this
    transaction, so callers never need to hold a process lock across
    the read and the write (the owner check and the status flip are
    atomic at the DB). Returns the applied ``(lease_id, old, new)``
    edges.
    """
    applied: List[Tuple[int, str, str]] = []
    now = time.time()
    with sqlite_utils.immediate(conn):
        for lease_id, new, worker_id in changes:
            row = conn.execute(
                'SELECT status, worker_id FROM leases '
                'WHERE lease_id = ?', (lease_id,)).fetchone()
            if row is None:
                if new is not RolloutLeaseStatus.PENDING or \
                        require_owner is not None:
                    continue
                conn.execute(
                    'INSERT INTO leases (lease_id, status, worker_id, '
                    'assigned_ts, attempts) VALUES (?, ?, NULL, ?, 0)',
                    (lease_id, new.value, now))
                applied.append((lease_id, '', new.value))
                continue
            old, old_owner = row
            if require_owner is not None and old_owner != require_owner:
                continue
            if old == new.value or not state_machines.can_transition(
                    state_machines.ROLLOUT_LEASE_TRANSITIONS, old,
                    new.value):
                continue
            if new is RolloutLeaseStatus.LEASED:
                conn.execute(
                    'UPDATE leases SET status = ?, worker_id = ?, '
                    'assigned_ts = ?, attempts = attempts + 1 '
                    'WHERE lease_id = ?',
                    (new.value, worker_id, now, lease_id))
            else:
                conn.execute(
                    'UPDATE leases SET status = ?, worker_id = ?, '
                    'assigned_ts = ? WHERE lease_id = ?',
                    (new.value, worker_id, now, lease_id))
            applied.append((lease_id, old, new.value))
    return applied


class RolloutDispatcher:
    """TCP front + sqlite lease/registry state + heartbeat reaper."""

    def __init__(self, db_path: str, *, host: str = '127.0.0.1',
                 port: int = 0,
                 heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT,
                 lease_timeout: float = DEFAULT_LEASE_TIMEOUT,
                 max_outstanding: int = DEFAULT_MAX_OUTSTANDING,
                 result_cap: int = DEFAULT_RESULT_CAP):
        self._db_path = db_path
        self._heartbeat_timeout = heartbeat_timeout
        self._lease_timeout = lease_timeout
        self._max_outstanding = max(1, max_outstanding)
        self._local = threading.local()
        self._stop = threading.Event()
        # Serializes the lease handler's read-plan phase (bounding
        # over-mint between concurrent lease RPCs). NEVER held across
        # a commit: every write is its own guarded transaction whose
        # compare-and-set refuses a plan that raced a faster writer
        # (``set_lease_status`` returns the edges that actually
        # applied; ``require_owner`` makes release owner-conditional;
        # ``_mint_ids`` reserves the id counter atomically), so
        # correctness comes from the DB — right even across processes
        # — and no handler thread ever stalls behind another's
        # WAL-contention retry sleep.
        self._assign_lock = threading.Lock()
        # Completed trajectory groups awaiting the learner. Bounded:
        # when full, the oldest (stalest — the learner would likely
        # drop it anyway) is evicted, and lease minting pauses.
        self._results: 'collections.deque[Dict[str, Any]]' = (
            collections.deque(maxlen=max(1, result_cap)))
        # Groups handed to a collect reply but not yet acked by the
        # NEXT collect: a reply lost on the wire must not lose real
        # rollout compute (the lease is already DONE — the work could
        # never be re-executed). Unacked groups are re-delivered; the
        # learner dedupes by lease_id.
        self._inflight: List[Dict[str, Any]] = []
        self._results_lock = threading.Lock()
        self._conn()   # create tables before the server answers
        self._server = framed.FramedServer(host, port, self._handle,
                                           name='rollout-dispatcher')
        self.addr = self._server.addr
        self._reaper = threading.Thread(
            target=self._reap_loop, name='rollout-dispatcher-reaper',
            daemon=True)

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'RolloutDispatcher':
        self._server.start()
        self._reaper.start()
        logger.info(
            f'rollout dispatcher on {self.addr[0]}:{self.addr[1]} '
            f'(db={self._db_path}, heartbeat_timeout='
            f'{self._heartbeat_timeout}s, lease_timeout='
            f'{self._lease_timeout}s)')
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.stop()
        self._reaper.join(timeout=5.0)

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, 'conn', None)
        if conn is None:
            conn = _connect(self._db_path)
            self._local.conn = conn
        return conn

    # ------------------------------------------------------------ meta

    def _meta_get(self, key: str) -> Optional[str]:
        row = self._conn().execute(
            'SELECT value FROM meta WHERE key = ?', (key,)).fetchone()
        return row[0] if row else None

    def _meta_set(self, conn: sqlite3.Connection, key: str,
                  value: str) -> None:
        with sqlite_utils.immediate(conn):
            conn.execute(
                'INSERT INTO meta (key, value) VALUES (?, ?) '
                'ON CONFLICT(key) DO UPDATE SET value = excluded.value',
                (key, value))

    def _mint_ids(self, conn: sqlite3.Connection, n: int) -> List[int]:
        """Reserve ``n`` fresh lease ids: the counter's
        read-increment-write is ONE BEGIN IMMEDIATE transaction, so
        sqlite's write lock is the arbiter and no Python lock is
        needed — concurrent minters get disjoint ranges even across
        processes."""
        with sqlite_utils.immediate(conn):
            row = conn.execute(
                "SELECT value FROM meta WHERE key = 'next_lease_id'"
            ).fetchone()
            next_id = int(row[0]) if row else 0
            conn.execute(
                'INSERT INTO meta (key, value) VALUES (?, ?) '
                'ON CONFLICT(key) DO UPDATE SET value = excluded.value',
                ('next_lease_id', str(next_id + n)))
        return list(range(next_id, next_id + n))

    def snapshot_version(self) -> int:
        return int(self._meta_get('snapshot_version') or -1)

    def spec_fp(self) -> Optional[str]:
        return self._meta_get('spec_fp')

    # -------------------------------------------------------- handlers

    def _handle(self, obj: Dict[str, Any], arrays: framed.Arrays
                ) -> Tuple[Dict[str, Any], Optional[framed.Arrays]]:
        op = str(obj.get('op', ''))
        if op == 'register':
            return self._op_register(obj), None
        if op == 'heartbeat':
            return self._op_heartbeat(obj), None
        if op == 'lease':
            return self._op_lease(obj), None
        if op == 'submit':
            return self._op_submit(obj, arrays), None
        if op == 'release':
            return self._op_release(obj), None
        if op == 'collect':
            return self._op_collect(obj)
        if op == 'put_spec':
            return self._op_put_spec(obj), None
        if op == 'publish':
            return self._op_publish(obj), None
        if op == 'stats':
            return self._op_stats(), None
        raise framed.RemoteError(f'unknown op {op!r}', kind='bad_op')

    def _spec_reply(self, reply: Dict[str, Any]) -> Dict[str, Any]:
        raw = self._meta_get('spec')
        if raw is not None:
            reply['spec'] = json.loads(raw)
        reply['spec_fp'] = self.spec_fp()
        reply['snapshot_version'] = self.snapshot_version()
        return reply

    def _op_register(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        worker_id = str(obj['worker_id'])
        old, changed = set_rollout_worker_status(
            self._conn(), worker_id, RolloutWorkerStatus.ALIVE)
        telemetry.WORKERS_UP.set(float(self._alive_count()))
        return self._spec_reply(
            {'ok': True, 'rejoined': bool(old is not None and changed)})

    def _op_heartbeat(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        worker_id = str(obj['worker_id'])
        conn = self._conn()
        # `status IN (?)` reads the column, never writes it (the
        # state-machine lint keys on `status =` in UPDATEs).
        cur = conn.execute(
            'UPDATE workers SET last_heartbeat = ? '
            'WHERE worker_id = ? AND status IN (?)',
            (time.time(), worker_id, RolloutWorkerStatus.ALIVE.value))
        conn.commit()
        if cur.rowcount == 0:
            # Unknown or LOST: tell the worker to re-register — its
            # leases were reassigned; rejoining gets it fresh ones.
            return {'ok': False, 'resync': True}
        reply: Dict[str, Any] = {'ok': True,
                                 'snapshot_version':
                                     self.snapshot_version()}
        if not obj.get('have_spec'):
            self._spec_reply(reply)
        return reply

    def _op_put_spec(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        try:
            spec = spec_lib.RolloutSpec.from_json(obj['spec'])
        except (ValueError, TypeError) as e:
            raise framed.RemoteError(
                f'cannot parse rollout spec: {e}', kind='spec') from e
        fp = spec.fingerprint()
        conn = self._conn()
        with sqlite_utils.immediate(conn):
            row = conn.execute(
                "SELECT value FROM meta WHERE key = 'spec_fp'"
            ).fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO meta (key, value) VALUES "
                    "('spec', ?), ('spec_fp', ?)",
                    (json.dumps(spec.to_json()), fp))
            elif row[0] != fp:
                raise framed.RemoteError(
                    f'dispatcher already serves spec {row[0]}, client '
                    f'sent {fp} — one dispatcher serves one rollout '
                    f'job; start another (or a fresh --db) for a new '
                    f'one', kind='spec_mismatch')
        return {'ok': True, 'spec_fp': fp}

    def _op_publish(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        version = int(obj['version'])
        current = self.snapshot_version()
        if version <= current:
            # Stale announcement (a learner restart replaying an old
            # cadence): versions are monotonic, refuse quietly.
            return {'ok': True, 'snapshot_version': current}
        self._meta_set(self._conn(), 'snapshot_version', str(version))
        telemetry.SNAPSHOT_VERSION.set(float(version))
        journal.record_event('rollout_snapshot_publish', 'learner',
                             data={'version': version})
        return {'ok': True, 'snapshot_version': version}

    def _alive_count(self) -> int:
        return int(self._conn().execute(
            'SELECT COUNT(*) FROM workers WHERE status = ?',
            (RolloutWorkerStatus.ALIVE.value,)).fetchone()[0])

    def _op_lease(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        if failpoints.ACTIVE:
            failpoints.fire('rollout.lease')
        worker_id = str(obj['worker_id'])
        max_n = max(1, int(obj.get('max_n', 1)))
        want_fp = obj.get('spec_fp')
        have_fp = self.spec_fp()
        if want_fp is not None and have_fp is not None and \
                want_fp != have_fp:
            # Refuse BEFORE granting: generation is the expensive
            # step, and a diverged worker's trajectories would only
            # be refused at submit anyway.
            raise framed.RemoteError(
                f'dispatcher serves spec {have_fp}, worker leases '
                f'for {want_fp} — jobs diverged; restart the older '
                f'side', kind='spec_mismatch')
        conn = self._conn()
        row = conn.execute(
            'SELECT status FROM workers WHERE worker_id = ?',
            (worker_id,)).fetchone()
        if row is None or row[0] != RolloutWorkerStatus.ALIVE.value:
            return {'ok': False, 'resync': True}
        with self._assign_lock:
            # Reads + arithmetic only — the lock bounds over-minting
            # between concurrent lease RPCs, never a commit.
            pending = [l for (l,) in conn.execute(
                'SELECT lease_id FROM leases WHERE status = ? '
                'ORDER BY lease_id LIMIT ?',
                (RolloutLeaseStatus.PENDING.value, max_n)).fetchall()]
            want_new = max_n - len(pending)
            to_mint = 0
            if want_new > 0:
                outstanding = int(conn.execute(
                    'SELECT COUNT(*) FROM leases WHERE status != ?',
                    (RolloutLeaseStatus.DONE.value,)).fetchone()[0])
                with self._results_lock:
                    backlog = len(self._results)
                # Backpressure: don't mint work the learner is not
                # consuming — a full result buffer means new leases
                # would only evict completed groups.
                headroom = min(
                    self._max_outstanding - outstanding,
                    (self._results.maxlen or 1) - backlog - outstanding)
                to_mint = min(want_new, max(0, headroom))
        # Writes OUTSIDE the lock: each sets its own transaction and
        # can sleep on WAL contention or an armed sqlite.commit
        # failpoint — other handler threads must keep moving.
        minted: List[int] = []
        if to_mint > 0:
            minted = self._mint_ids(conn, to_mint)
            set_lease_status(conn, [
                (l, RolloutLeaseStatus.PENDING, None) for l in minted])
            telemetry.LEASES.inc(len(minted), event='minted')
        grant: List[int] = []
        if pending or minted:
            # The grant is whatever the guarded setter ACTUALLY
            # applied: a concurrent granter of the same PENDING ids
            # loses cleanly (LEASED -> LEASED refused) instead of two
            # workers both believing they own the lease.
            applied = set_lease_status(conn, [
                (l, RolloutLeaseStatus.LEASED, worker_id)
                for l in pending + minted])
            grant = [l for l, _, _ in applied]
            if grant:
                telemetry.LEASES.inc(len(grant), event='leased')
        return {'ok': True, 'leases': grant,
                'spec_fp': self.spec_fp(),
                'snapshot_version': self.snapshot_version()}

    def _op_submit(self, obj: Dict[str, Any],
                   arrays: framed.Arrays) -> Dict[str, Any]:
        worker_id = str(obj['worker_id'])
        lease_id = int(obj['lease_id'])
        version = int(obj.get('snapshot_version', -1))
        want_fp = obj.get('spec_fp')
        have_fp = self.spec_fp()
        if want_fp is not None and have_fp is not None and \
                want_fp != have_fp:
            raise framed.RemoteError(
                f'dispatcher serves spec {have_fp}, worker submitted '
                f'for {want_fp} — jobs diverged; restart the older '
                f'side', kind='spec_mismatch')
        traj = self._validate_trajectory(lease_id, version, arrays)
        conn = self._conn()
        # Apply first, diagnose on refusal: the guarded setter's
        # transaction is the arbiter (DONE is terminal, so the first
        # writer wins atomically) — no lock held across the commit,
        # and no check-then-act window between a status read and the
        # write.
        applied = set_lease_status(
            conn, [(lease_id, RolloutLeaseStatus.DONE, None)])
        if not applied:
            row = conn.execute(
                'SELECT status FROM leases WHERE lease_id = ?',
                (lease_id,)).fetchone()
            if row is None:
                raise framed.RemoteError(
                    f'unknown lease {lease_id}', kind='unknown_lease')
            if row[0] == RolloutLeaseStatus.DONE.value:
                # At-least-once duplicate (the lease was reassigned
                # and someone else finished first): drop quietly.
                telemetry.LEASES.inc(event='duplicate')
                return {'ok': True, 'accepted': False,
                        'duplicate': True}
            raise framed.RemoteError(
                f'lease {lease_id} refused DONE from {row[0]}',
                kind='bad_transition')
        telemetry.LEASES.inc(event='done')
        with self._results_lock:
            self._results.append(traj)
            telemetry.QUEUE_DEPTH.set(float(len(self._results)),
                                      role='dispatcher')
        telemetry.TRAJECTORIES.inc(role='worker')
        return {'ok': True, 'accepted': True, 'duplicate': False,
                'worker_id': worker_id}

    def _validate_trajectory(self, lease_id: int, version: int,
                             arrays: framed.Arrays) -> Dict[str, Any]:
        missing = {'completions', 'rewards', 'behavior_lp'} - set(
            arrays or {})
        if missing:
            raise framed.RemoteError(
                f'trajectory for lease {lease_id} lacks arrays '
                f'{sorted(missing)}', kind='bad_trajectory')
        comp = arrays['completions']
        rew = arrays['rewards']
        lp = arrays['behavior_lp']
        if comp.ndim != 2 or rew.shape != (comp.shape[0],) or \
                lp.shape != comp.shape:
            raise framed.RemoteError(
                f'trajectory shapes disagree: completions '
                f'{comp.shape}, rewards {rew.shape}, behavior_lp '
                f'{lp.shape}', kind='bad_trajectory')
        return {'lease_id': lease_id, 'version': version,
                'completions': np.asarray(comp, np.int32),
                'rewards': np.asarray(rew, np.float32),
                'behavior_lp': np.asarray(lp, np.float32)}

    def _op_release(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """A worker hands back a lease it cannot serve (failed
        generation, shutdown): LEASED -> PENDING without waiting for
        the lease timeout. Only the current owner may release."""
        worker_id = str(obj['worker_id'])
        lease_id = int(obj['lease_id'])
        conn = self._conn()
        # Owner-conditional compare-and-set inside the setter's own
        # transaction: "only the current owner may release" holds
        # without holding a process lock across the commit (a lease
        # reassigned-and-re-leased between any read here and the
        # write can no longer be released by its old owner).
        applied = set_lease_status(
            conn, [(lease_id, RolloutLeaseStatus.PENDING, None)],
            require_owner=worker_id)
        if not applied:
            return {'ok': True, 'released': False}
        telemetry.LEASES.inc(event='released')
        return {'ok': True, 'released': True}

    def _op_collect(self, obj: Dict[str, Any]
                    ) -> Tuple[Dict[str, Any], framed.Arrays]:
        """Hand up to ``max_n`` completed groups to the learner.

        At-least-once delivery: ``ack`` carries the lease ids the
        learner actually received from the PREVIOUS reply; anything
        handed out but not acked (a reply torn mid-send, a collect
        timeout) is re-delivered ahead of fresh groups. Duplicates
        (reply arrived, ack lost) are deduped learner-side by
        lease_id — leases complete exactly once, so the id is a
        sufficient key."""
        max_n = max(1, int(obj.get('max_n', 1)))
        acked = set(int(a) for a in (obj.get('ack') or []))
        out: List[Dict[str, Any]] = []
        with self._results_lock:
            unacked = [t for t in self._inflight
                       if t['lease_id'] not in acked]
            out.extend(unacked[:max_n])
            while self._results and len(out) < max_n:
                out.append(self._results.popleft())
            # Unacked overflow (a smaller max_n than last time) stays
            # inflight for the round after.
            self._inflight = list(out) + unacked[max_n:]
            telemetry.QUEUE_DEPTH.set(float(len(self._results)),
                                      role='dispatcher')
        meta = [{'lease_id': t['lease_id'], 'version': t['version']}
                for t in out]
        arrays: framed.Arrays = {}
        for i, t in enumerate(out):
            arrays[f'completions_{i}'] = t['completions']
            arrays[f'rewards_{i}'] = t['rewards']
            arrays[f'behavior_lp_{i}'] = t['behavior_lp']
        return {'ok': True, 'trajectories': meta,
                'snapshot_version': self.snapshot_version()}, arrays

    def _op_stats(self) -> Dict[str, Any]:
        conn = self._conn()
        workers = dict(conn.execute(
            'SELECT status, COUNT(*) FROM workers GROUP BY status'
        ).fetchall())
        leases = dict(conn.execute(
            'SELECT status, COUNT(*) FROM leases GROUP BY status'
        ).fetchall())
        with self._results_lock:
            backlog = len(self._results)
        return {'ok': True, 'workers': workers, 'leases': leases,
                'result_backlog': backlog,
                'snapshot_version': self.snapshot_version(),
                'spec_fp': self.spec_fp()}

    def result_backpressure(self) -> float:
        """Result-buffer fill share in [0, 1]: (backlog + live leases)
        over the buffer capacity — the complement of the headroom
        ``_op_lease`` mints against. 1.0 means a new lease would only
        evict completed groups; the elastic fleet wiring
        (train/rollout/elastic.py) scales the rollout pool DOWN before
        that point, so no worker generates a trajectory the staleness
        window would drop. Thread-safe (thread-local conn + the
        results lock), so the controller loop may probe it directly."""
        outstanding = int(self._conn().execute(
            'SELECT COUNT(*) FROM leases WHERE status != ?',
            (RolloutLeaseStatus.DONE.value,)).fetchone()[0])
        with self._results_lock:
            backlog = len(self._results)
        cap = self._results.maxlen or 1
        return min(1.0, max(0.0, (backlog + outstanding) / cap))

    # ----------------------------------------------------------- reaper

    def _reap_loop(self) -> None:
        interval = max(0.05, self._heartbeat_timeout / 4.0)
        while not self._stop.wait(interval):
            try:
                self._reap_once()
            except Exception as e:  # noqa: BLE001 — reaper must survive
                logger.warning(f'rollout reaper pass failed: {e}')

    def _leases_of(self, conn: sqlite3.Connection,
                   worker_id: str) -> List[int]:
        return [l for (l,) in conn.execute(
            'SELECT lease_id FROM leases WHERE status = ? AND '
            'worker_id = ?',
            (RolloutLeaseStatus.LEASED.value, worker_id)).fetchall()]

    def _reassign(self, conn: sqlite3.Connection, lease_ids: List[int],
                  entity: str, reason: str) -> None:
        applied = set_lease_status(conn, [
            (l, RolloutLeaseStatus.PENDING, None) for l in lease_ids])
        if not applied:
            # A faster writer (submit, release, another sweep) moved
            # every lease first — nothing happened, journal nothing.
            return
        telemetry.LEASES.inc(len(applied), event='reassigned')
        journal.record_event(
            'rollout_lease_reassign', entity, reason=reason,
            data={'leases': [l for l, _, _ in applied]})

    def _reap_once(self) -> None:
        conn = self._conn()
        now = time.time()
        # 1. Silent workers -> LOST, their leases -> PENDING.
        cutoff = now - self._heartbeat_timeout
        stale = [w for (w,) in conn.execute(
            'SELECT worker_id FROM workers WHERE status = ? AND '
            'last_heartbeat < ?',
            (RolloutWorkerStatus.ALIVE.value, cutoff)).fetchall()]
        for worker_id in stale:
            # No lock: the LOST write is a compare-and-set
            # (require_heartbeat_before) in its own transaction, and
            # the reassign's LEASED -> PENDING edges are refused by
            # the setter for any lease a faster writer already moved.
            # A lease acquired between the two is caught by the
            # orphan sweep below.
            _, changed = set_rollout_worker_status(
                conn, worker_id, RolloutWorkerStatus.LOST,
                reason='heartbeat_timeout',
                require_heartbeat_before=cutoff)
            if not changed:
                continue
            orphaned = self._leases_of(conn, worker_id)
            self._reassign(conn, orphaned, worker_id,
                           'heartbeat_timeout')
            logger.warning(
                f'rollout worker {worker_id} lost (no heartbeat for '
                f'{self._heartbeat_timeout}s); reassigned leases '
                f'{orphaned}')
        # 2. Orphan sweep: LEASED leases owned by a non-ALIVE worker —
        # a crash between the LOST write and its reassignment would
        # otherwise strand them forever (survivors only heartbeat).
        orphans = [l for (l,) in conn.execute(
            'SELECT lease_id FROM leases WHERE status = ? AND '
            '(worker_id IS NULL OR worker_id NOT IN '
            '(SELECT worker_id FROM workers WHERE status = ?))',
            (RolloutLeaseStatus.LEASED.value,
             RolloutWorkerStatus.ALIVE.value)).fetchall()]
        if orphans:
            self._reassign(conn, orphans, 'dispatcher',
                           'orphan_sweep')
        # 3. Lease timeout: a wedged-but-heartbeating owner cannot sit
        # on a lease forever (at-least-once makes re-execution safe).
        timed_out = [l for (l,) in conn.execute(
            'SELECT lease_id FROM leases WHERE status = ? AND '
            'assigned_ts < ?',
            (RolloutLeaseStatus.LEASED.value,
             now - self._lease_timeout)).fetchall()]
        if timed_out:
            self._reassign(conn, timed_out, 'dispatcher',
                           'lease_timeout')
        # 4. DONE-row GC: keep a bounded accounting tail.
        with sqlite_utils.immediate(conn):
            row = conn.execute(
                'SELECT lease_id FROM leases WHERE status = ? '
                'ORDER BY lease_id DESC LIMIT 1 OFFSET ?',
                (RolloutLeaseStatus.DONE.value,
                 _DONE_KEEP_ROWS)).fetchone()
            if row is not None:
                conn.execute(
                    'DELETE FROM leases WHERE status = ? AND '
                    'lease_id <= ?',
                    (RolloutLeaseStatus.DONE.value, row[0]))
        telemetry.WORKERS_UP.set(float(self._alive_count()))
