"""Spot-harvesting RL plane: preemptible rollout fleet → stable learner.

The RLBoost topology (PAPERS.md) built from planes this repo already
has: a **dispatcher** (WAL-sqlite worker registry + prompt-lease state
machine, the ``data_service/`` idiom over ``utils/framed`` TCP),
**harvestable rollout workers** (stateless jax processes that generate
GRPO completion groups from a learner-published policy snapshot and
survive SIGKILL at any point), and a **stable learner**
(``train/grpo`` update math fed by an at-least-once trajectory stream,
staleness-bounded off-policy window, journaled trajectory log whose
replay reproduces the loss trajectory bit-equal).

Why it is robust by construction:

  * a lease's prompt is a pure function of ``(spec, lease_id)`` — any
    worker can (re)compute it, so reassignment ships one integer;
  * trajectories are stamped with the snapshot version that generated
    them — the learner drops anything older than its staleness window
    instead of silently training on ancient behavior;
  * policy snapshots ride the chunked, digest-verified checkpoint
    format (``train/checkpoints``) — workers restore onto whatever
    device/mesh they have, which is exactly what makes them
    harvestable;
  * losing ANY subset of workers degrades learner throughput but never
    stalls or corrupts it (docs/ROBUSTNESS.md, "Harvested RL plane").
"""
from skypilot_tpu.train.rollout.spec import RolloutSpec  # noqa: F401
