"""LoRA finetuning: low-rank adapters over any native model family.

The reference finetunes via external recipes (llm/llama-3_1-finetuning/
lora.yaml runs torchtune's LoRA on Llama-3.1; llm/gpt-oss-finetuning/
runs TRL) — SkyPilot itself only schedules them. Here finetuning is
native, and the design is TPU-first:

  - Adapters are a *path-keyed overlay* on the stacked-layer param
    pytrees (llama.py stacks layers on a leading [L] axis): a target
    leaf of shape [..., in, out] gets A:[..., in, r] and B:[..., r, out].
    The leading axes ride along, so the same code adapts dense layers
    ([L, in, out]), per-expert MoE weights ([L, E, in, out]) and 2-D
    heads — one einsum '...ir,...ro->...io' covers all of them and runs
    as a single batched matmul on the MXU.
  - The merge happens *functionally inside the loss*: the train step
    computes `merged = base + scale * A@B` under jit and runs the
    family's unmodified forward. No per-family hooks, no model edits;
    XLA fuses the rank-r matmul + add into the surrounding graph, and
    autodiff gives exactly the LoRA gradients because `base` enters as
    a constant (grads are taken w.r.t. the adapters only).
  - Only adapters + their optimizer state are trained/donated; the base
    stays sharded per the family's param_specs (fsdp/tensor) and is
    passed by reference every step. Adapters are tiny (rank<<dim) and
    replicated — their all-reduce cost is noise next to the base's.

Serving the result: `merge_into()` folds adapters into the base at full
precision → the merged tree serves through the existing engine paths
(models/hf_export.py writes it back as an HF checkpoint directory).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from skypilot_tpu import models as models_lib
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding as sharding_lib
from skypilot_tpu.train import train_lib

# Default targets: the attention projections (the standard LoRA recipe,
# reference analog llm/llama-3_1-finetuning/lora.yaml's torchtune
# defaults). Leaf names are the native ones (llama.py / mla.py / moe.py).
DEFAULT_TARGETS = ('wq', 'wk', 'wv', 'wo')


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 16
    alpha: float = 32.0
    # Leaf names to adapt (matched against the last path segment).
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _leaf_key(path) -> str:
    """'/'-joined dict keys for a tree path, e.g. 'layers/wq'."""
    parts = []
    for p in path:
        if hasattr(p, 'key'):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return '/'.join(parts)


def target_keys(base_params: Any, lcfg: LoRAConfig) -> list:
    """Sorted adapter keys: targeted leaves with a matmul shape."""
    keys = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(base_params)[0]:
        key = _leaf_key(path)
        if key.split('/')[-1] in lcfg.targets and leaf.ndim >= 2:
            keys.append(key)
    if not keys:
        raise ValueError(
            f'LoRA targets {lcfg.targets} matched no >=2-D leaves; '
            f'available: '
            f'{sorted({_leaf_key(p) for p, _ in jax.tree_util.tree_flatten_with_path(base_params)[0]})}')
    return sorted(keys)


def init_adapters(rng: jax.Array, base_params: Any,
                  lcfg: LoRAConfig) -> Dict[str, Dict[str, jnp.ndarray]]:
    """{key: {'a','b'}} — A ~ N(0, 1/r) fp32, B = 0 (so the merged
    model starts EXACTLY at the base; asserted in tests)."""
    leaves = {_leaf_key(p): leaf for p, leaf in
              jax.tree_util.tree_flatten_with_path(base_params)[0]}
    adapters: Dict[str, Dict[str, jnp.ndarray]] = {}
    for i, key in enumerate(target_keys(base_params, lcfg)):
        leaf = leaves[key]
        *lead, d_in, d_out = leaf.shape
        k = jax.random.fold_in(rng, i)
        a = jax.random.normal(k, (*lead, d_in, lcfg.rank),
                              jnp.float32) / lcfg.rank
        b = jnp.zeros((*lead, lcfg.rank, d_out), jnp.float32)
        adapters[key] = {'a': a, 'b': b}
    return adapters


def merge_into(base_params: Any, adapters: Dict[str, Dict[str, Any]],
               lcfg: LoRAConfig) -> Any:
    """base + scaling * A@B on targeted leaves (fp32 math, cast back to
    each leaf's dtype). Works under jit and on concrete trees alike."""
    scaling = lcfg.scaling

    def _merge(path, leaf):
        ab = adapters.get(_leaf_key(path))
        if ab is None:
            return leaf
        delta = jnp.einsum('...ir,...ro->...io',
                           ab['a'].astype(jnp.float32),
                           ab['b'].astype(jnp.float32)) * scaling
        return (leaf.astype(jnp.float32) + delta).astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(_merge, base_params)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LoRAState:
    step: jnp.ndarray
    adapters: Any
    opt_state: Any


def init_lora_state(rng: jax.Array, base_params: Any, lcfg: LoRAConfig,
                    tx: optax.GradientTransformation) -> LoRAState:
    adapters = init_adapters(rng, base_params, lcfg)
    return LoRAState(step=jnp.zeros((), jnp.int32), adapters=adapters,
                     opt_state=tx.init(adapters))


def shard_base_params(base_params: Any, cfg, mesh: Mesh,
                      rules: Optional[sharding_lib.Rules] = None) -> Any:
    """Place an (imported) base tree onto the mesh per the family's
    param_specs — the same layout the full train step uses."""
    rules = rules or sharding_lib.Rules()
    mod = models_lib.module_for(cfg)
    specs = mod.param_specs(cfg, rules)
    shardings = sharding_lib.tree_shardings(mesh, specs)
    return jax.tree.map(jax.device_put, base_params, shardings)


def make_lora_train_step(cfg, mesh: Mesh, tx: optax.GradientTransformation,
                         lcfg: LoRAConfig,
                         rules: Optional[sharding_lib.Rules] = None):
    """Jitted (state, base_params, batch) → (state, metrics).

    Donates only the LoRA state; `base_params` is read-only (pass the
    same sharded tree every step — it is neither copied nor updated).
    Batch contract matches train_lib.make_train_step: {'tokens':
    [B, S+1]} (+ optional 'loss_mask' over target positions).
    """
    rules = rules or sharding_lib.Rules()
    mod = models_lib.module_for(cfg)
    n_zigzag = train_lib._zigzag_seq_shards(cfg, mesh)

    def step_fn(state: LoRAState, base_params, batch):
        tokens = batch['tokens']
        inputs, targets, mask, positions = train_lib._zigzag_shift(
            tokens, batch.get('loss_mask'), n_zigzag)

        def loss_fn(adapters):
            merged = merge_into(base_params, adapters, lcfg)
            if getattr(mod, 'HAS_AUX', False):
                logits, aux = mod.forward(merged, inputs, cfg, rules,
                                          positions=positions,
                                          return_aux=True)
            else:
                logits, aux = mod.forward(merged, inputs, cfg, rules,
                                          positions=positions), 0.0
            loss, denom = train_lib.cross_entropy_loss(logits, targets,
                                                       mask)
            return loss + aux, (loss, denom)

        (_, (loss, denom)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.adapters)
        updates, new_opt = tx.update(grads, state.opt_state,
                                     state.adapters)
        new_adapters = optax.apply_updates(state.adapters, updates)
        metrics = {'loss': loss, 'grad_norm': optax.global_norm(grads),
                   'tokens': denom, 'step': state.step}
        return LoRAState(step=state.step + 1, adapters=new_adapters,
                         opt_state=new_opt), metrics

    jitted = jax.jit(step_fn, donate_argnums=(0,))

    def wrapped(state, base_params, batch):
        with mesh_lib.use_mesh(mesh):
            return jitted(state, base_params, batch)

    return wrapped


# ----------------------------------------------------------------------
# Adapter persistence: one .npz (flat 'key:a'/'key:b' arrays) + a JSON
# sidecar with the LoRAConfig and the training step. Small files; no
# orbax machinery needed.

def save_adapters(directory: str, state: LoRAState,
                  lcfg: LoRAConfig) -> str:
    """Persist adapters + optimizer state. Process-0-only on multi-host
    slices (adapters are replicated, so rank 0 holds the full state; the
    orbax-style multi-writer dance is unnecessary here)."""
    directory = os.path.abspath(os.path.expanduser(directory))
    path = os.path.join(directory, 'adapters.npz')
    if jax.process_index() != 0:
        return path
    os.makedirs(directory, exist_ok=True)
    adapters = jax.device_get(state.adapters)
    flat = {}
    for key, ab in adapters.items():
        flat[key + ':a'] = np.asarray(ab['a'], np.float32)
        flat[key + ':b'] = np.asarray(ab['b'], np.float32)
    # Optimizer state rides along so a resumed run keeps its Adam
    # moments + schedule count (structure is reproducible from
    # tx.init(adapters); only the leaves are stored, in tree order).
    for i, leaf in enumerate(jax.tree.leaves(
            jax.device_get(state.opt_state))):
        flat[f'opt:{i}'] = np.asarray(leaf)
    # Step lives INSIDE the npz so weights+moments+step replace
    # atomically (lora.json's copy is advisory/human-readable; a crash
    # between the two os.replace calls can't desync resume).
    flat['_step'] = np.asarray(int(jax.device_get(state.step)), np.int64)
    tmp = os.path.join(directory, '.adapters.npz.tmp')
    with open(tmp, 'wb') as f:
        np.savez(f, **flat)
    os.replace(tmp, path)
    meta = {'rank': lcfg.rank, 'alpha': lcfg.alpha,
            'targets': list(lcfg.targets),
            'step': int(jax.device_get(state.step))}
    meta_tmp = os.path.join(directory, '.lora.json.tmp')
    with open(meta_tmp, 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=1)
    os.replace(meta_tmp, os.path.join(directory, 'lora.json'))
    return path


def load_adapters(directory: str
                  ) -> Tuple[Dict[str, Dict[str, jnp.ndarray]],
                             LoRAConfig, int, list]:
    """(adapters, lora_config, step, opt_leaves) from save_adapters
    output. opt_leaves is [] for pre-opt-state artifacts; otherwise the
    flat optimizer-state leaves in tree order (rebuild the structure
    with tx.init(adapters) and tree_unflatten)."""
    directory = os.path.abspath(os.path.expanduser(directory))
    with open(os.path.join(directory, 'lora.json'), 'r',
              encoding='utf-8') as f:
        meta = json.load(f)
    lcfg = LoRAConfig(rank=int(meta['rank']), alpha=float(meta['alpha']),
                      targets=tuple(meta['targets']))
    adapters: Dict[str, Dict[str, jnp.ndarray]] = {}
    opt: Dict[int, jnp.ndarray] = {}
    step = int(meta.get('step', 0))
    with np.load(os.path.join(directory, 'adapters.npz')) as z:
        for name in z.files:
            if name == '_step':
                step = int(z[name])   # authoritative (atomic w/ weights)
                continue
            if name.startswith('opt:'):
                opt[int(name.split(':', 1)[1])] = jnp.asarray(z[name])
                continue
            key, part = name.rsplit(':', 1)
            adapters.setdefault(key, {})[part] = jnp.asarray(z[name])
    opt_leaves = [opt[i] for i in sorted(opt)]
    return adapters, lcfg, step, opt_leaves


def restore_opt_state(tx: optax.GradientTransformation, adapters: Any,
                      opt_leaves: list) -> Any:
    """Rebuild the optax state from saved leaves (fresh init when the
    artifact predates opt-state saving or shapes drifted)."""
    template = tx.init(adapters)
    t_leaves, treedef = jax.tree.flatten(template)
    if len(opt_leaves) != len(t_leaves) or any(
            tuple(a.shape) != tuple(b.shape)
            for a, b in zip(opt_leaves, t_leaves)):
        return template
    # Cast to template dtypes (e.g. schedule counts are int32).
    opt_leaves = [jnp.asarray(a, b.dtype)
                  for a, b in zip(opt_leaves, t_leaves)]
    return jax.tree.unflatten(treedef, opt_leaves)
