"""Training driver: config → mesh → (resume|init) → step loop → checkpoints.

This is the native replacement for what the reference hands to torch-xla +
HF Trainer in its TPU recipe (examples/tpu/v6e/train-llama3-8b.yaml,
docs/source/reference/tpu.rst:100-118): one process per host, SPMD over the
slice, periodic async checkpoints, resume-from-latest. Run on a cluster via
a task YAML whose `run:` is `python -m skypilot_tpu.train.trainer ...` —
the gang env contract (skylet/constants.py) provides coordinator/worker-id
for jax.distributed on multi-host slices.
"""
from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import spans
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs

# Fixed name, not __name__: under `python -m` this module is '__main__',
# which would fall outside the 'skypilot_tpu' logging root (no handler).
logger = sky_logging.init_logger('skypilot_tpu.train.trainer')

# Input-starvation accounting: time the step loop blocks in next() on
# the batch iterator — for BOTH the in-process and the data-service
# paths. On healthy overlap (prefetch ahead of compute) this sits near
# zero; a growing batch-wait share is the "scale the input pool"
# signal (docs/OBSERVABILITY.md, bench.py train_input).
_BATCH_WAIT = metrics_lib.histogram(
    'skytpu_train_batch_wait_seconds',
    'Time the train step loop blocked waiting for the next input batch')
# The paired `train.batch_wait` span records retroactively and ONLY
# for waits past this threshold (the engine's hot-path idiom: derive
# timings, persist the interesting ones) — a span row per step on a
# 100k-step run would just churn the journal GC with near-zero
# durations the histogram already counts.
_BATCH_WAIT_SPAN_MIN_S = knobs.get_float('SKYTPU_TRAIN_BATCH_WAIT_SPAN_MIN')


@dataclasses.dataclass
class TrainerConfig:
    model: str = 'llama-debug'          # models preset name
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: Dict[str, int] = dataclasses.field(default_factory=dict)
    batch_size: int = 8
    seq_len: int = 512
    total_steps: int = 100
    learning_rate: float = 3e-4
    warmup_steps: int = 10
    log_every: int = 10
    data_path: Optional[str] = None     # None → synthetic tokens
    tokenizer: Optional[str] = None
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 50
    # >0: ALSO checkpoint whenever this many seconds elapsed since the
    # last save — the preemption-exposure bound for spot training (a
    # step-count cadence is meaningless when step time varies).
    ckpt_time_interval: float = 0.0
    # >1: split each global batch into this many sequentially-accumulated
    # microbatches (same update, lower peak activation memory).
    grad_accum_steps: int = 1
    # Held-out evaluation: a separate corpus evaluated every eval_every
    # steps over eval_batches deterministic step-indexed batches.
    eval_data_path: Optional[str] = None
    eval_every: int = 50
    eval_batches: int = 8
    # LoRA finetuning (train/lora.py): rank > 0 trains low-rank adapters
    # instead of full params. Base weights come from --hf-dir (an HF
    # checkpoint, the reference llm/llama-3_1-finetuning flow) or the
    # preset's random init. Adapters persist to lora_dir; merge with
    # `python -m skypilot_tpu.train.lora_merge` for serving.
    lora_rank: int = 0
    lora_alpha: float = 32.0
    lora_targets: Optional[List[str]] = None
    hf_dir: Optional[str] = None
    lora_dir: Optional[str] = None
    # SFT: a JSONL of {"messages": [...]} conversations; loss masks to
    # assistant turns (data/sft.py). chat_family None = auto-detect
    # from the tokenizer's specials (llama3/chatml/plain).
    sft_data_path: Optional[str] = None
    chat_family: Optional[str] = None
    # host:port of a data-service dispatcher (data_service/): input
    # preprocessing runs on its CPU worker pool instead of in-process.
    # The stream is BIT-IDENTICAL either way — both sides run
    # data_service/spec.load_source over the same DatasetSpec — so
    # flipping this flag (or losing a worker) never changes training.
    data_service: Optional[str] = None


class _PreemptionWatch(contextlib.AbstractContextManager):
    """Preemption notice → graceful final checkpoint.

    GCP delivers a spot TPU preemption as an ACPI shutdown, which
    reaches the task as SIGTERM with a grace window; the watch turns
    that (and the deterministic `trainer.preempt` failpoint, for chaos
    schedules) into a flag the step loop checks at step boundaries, so
    the trainer writes one final checkpoint and exits cleanly instead
    of losing everything since the last cadence save. Installed only on
    the main thread (signal.signal raises elsewhere — e.g. trainer
    tests driving train() from a worker thread)."""

    def __init__(self):
        self._flag = threading.Event()
        self._prev = None

    def __enter__(self) -> '_PreemptionWatch':
        if threading.current_thread() is threading.main_thread():
            self._prev = signal.signal(
                signal.SIGTERM, lambda *_: self._flag.set())
        return self

    def __exit__(self, *exc) -> None:
        if self._prev is not None:
            signal.signal(signal.SIGTERM, self._prev)

    @property
    def preempted(self) -> bool:
        if self._flag.is_set():
            return True
        if failpoints.ACTIVE:
            try:
                failpoints.fire('trainer.preempt')
            except failpoints.FailpointError:
                self._flag.set()
                return True
        return False


def maybe_init_distributed() -> None:
    """Initialise jax.distributed on multi-host slices from the gang env
    (skylet/constants.py gang_env: coordinator + TPU_WORKER_ID)."""
    import jax
    coordinator = knobs.get_str('SKYTPU_COORDINATOR_ADDRESS')
    num_procs = knobs.get_int('SKYTPU_NUM_PROCESSES')
    if coordinator and num_procs > 1:
        # SKYTPU_NODE_RANK is the global rank across all slices;
        # TPU_WORKER_ID is slice-local and would collide on multi-slice.
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_procs,
            process_id=knobs.get_int('SKYTPU_NODE_RANK'))


def _model_config(tcfg: TrainerConfig):
    from skypilot_tpu.models import llama, mla, moe
    presets = dict(llama.PRESETS)
    presets.update(moe.PRESETS)
    presets.update(mla.PRESETS)
    if tcfg.model not in presets:
        raise ValueError(f'Unknown model preset {tcfg.model!r}; '
                         f'available: {sorted(presets)}')
    cfg = presets[tcfg.model]
    if tcfg.model_overrides:
        cfg = dataclasses.replace(cfg, **tcfg.model_overrides)
    return cfg


def _dataset_spec(tcfg: TrainerConfig, vocab_size: int):
    """TrainerConfig → the DatasetSpec BOTH input paths run on.

    One spec drives the in-process source and every data-service
    worker; tokenizer resolution (the hf_dir tokenizer.json rule) and
    vocab validation (data/loader.validate_vocab) happen inside
    spec.load_source, so neither path can drift from the other.
    """
    from skypilot_tpu.data_service import spec as spec_lib
    tokenizer = tcfg.tokenizer
    if tcfg.sft_data_path and tokenizer is None and tcfg.hf_dir:
        # No silent byte fallback for an HF finetune: a missing
        # tokenizer.json must error (load_tokenizer's hint), not train
        # the model on byte-tokenized garbage.
        tokenizer = os.path.join(
            os.path.expanduser(tcfg.hf_dir), 'tokenizer.json')
    return spec_lib.DatasetSpec(
        batch_size=tcfg.batch_size, seq_len=tcfg.seq_len,
        vocab_size=vocab_size, data_path=tcfg.data_path,
        tokenizer=tokenizer, sft_data_path=tcfg.sft_data_path,
        chat_family=tcfg.chat_family)


def _batch_iter(tcfg: TrainerConfig, vocab_size: int, start_step: int,
                mesh) -> Iterator[Dict[str, Any]]:
    from skypilot_tpu.data import loader
    from skypilot_tpu.data_service import spec as spec_lib
    dspec = _dataset_spec(tcfg, vocab_size)
    if tcfg.data_service:
        from skypilot_tpu.data_service import client as ds_client
        cl = ds_client.DataServiceClient(tcfg.data_service, dspec,
                                         start_step=start_step)
        logger.info(f'Input via data service at {tcfg.data_service} '
                    f'(spec {dspec.fingerprint()}).')
        try:
            for batch in cl:
                yield loader.shard_batch(batch, mesh)
        finally:
            cl.close()
        return
    source = spec_lib.load_source(dspec)
    step = start_step
    while True:
        yield loader.shard_batch(source.batch_at_step(step), mesh)
        step += 1


def train(tcfg: TrainerConfig) -> List[Dict[str, float]]:
    """Run the loop; returns per-log-interval metrics (loss, step time)."""
    import jax
    from skypilot_tpu.parallel import MeshSpec, build_mesh
    from skypilot_tpu.train import train_lib

    maybe_init_distributed()
    base_params = None
    load_base = None
    if tcfg.hf_dir:
        # Finetune flow: config comes from the HF checkpoint (the preset
        # name is ignored, loudly). Weights load lazily — a resumed run
        # restores from its own checkpoint and never reads them.
        import jax.numpy as jnp
        from skypilot_tpu.models import hf_import
        cfg = hf_import.load_hf_config(tcfg.hf_dir)
        if tcfg.model_overrides:
            cfg = dataclasses.replace(cfg, **tcfg.model_overrides)
        logger.info(f'--hf-dir given: model config from {tcfg.hf_dir} '
                    f'(preset {tcfg.model!r} ignored).')

        def load_base(dtype=jnp.float32):
            # fp32 for full finetuning (optimizer masters); LoRA keeps
            # the stored dtype (the frozen base is read-only and
            # merge_into does its math in fp32 regardless).
            _, p = hf_import.load_hf_checkpoint(tcfg.hf_dir, dtype=dtype)
            return p
    else:
        cfg = _model_config(tcfg)
    mesh = build_mesh(MeshSpec(**tcfg.mesh) if tcfg.mesh else MeshSpec())
    tx = train_lib.default_optimizer(learning_rate=tcfg.learning_rate,
                                     warmup_steps=tcfg.warmup_steps,
                                     total_steps=tcfg.total_steps)

    batch_shards = mesh.shape['data'] * mesh.shape['fsdp']
    if tcfg.batch_size % batch_shards != 0:
        raise ValueError(
            f'batch_size={tcfg.batch_size} must be divisible by '
            f'data*fsdp={batch_shards} (the batch-dim mesh axes).')

    if tcfg.sft_data_path and tcfg.data_path:
        raise ValueError('--sft-data and --data are exclusive (chat '
                         'SFT vs plain-corpus LM).')
    lora_mode = tcfg.lora_rank > 0
    if lora_mode and tcfg.ckpt_dir:
        raise ValueError('--lora-rank and --ckpt-dir are exclusive: LoRA '
                         'persists adapters to --lora-dir instead.')
    if lora_mode and tcfg.grad_accum_steps > 1:
        raise ValueError('--grad-accum is not supported with --lora-rank '
                         'yet; lower --batch-size instead (LoRA peak '
                         'memory is dominated by activations, same as '
                         'the full step).')

    ckpt = None
    start_step = 0
    lcfg = None
    if lora_mode:
        from skypilot_tpu.train import lora as lora_lib
        lcfg = lora_lib.LoRAConfig(
            rank=tcfg.lora_rank, alpha=tcfg.lora_alpha,
            targets=tuple(tcfg.lora_targets or lora_lib.DEFAULT_TARGETS))
        if load_base is not None:
            base_params = load_base(dtype=None)
            base_params = lora_lib.shard_base_params(base_params, cfg,
                                                     mesh)
        else:
            # Init directly sharded (no single-device staging — the
            # same reason train_lib.init_train_state shards its init).
            from skypilot_tpu import models as models_lib
            from skypilot_tpu.parallel import mesh as mesh_lib
            from skypilot_tpu.parallel import sharding as sharding_lib
            mod = models_lib.module_for(cfg)
            shardings = sharding_lib.tree_shardings(
                mesh, mod.param_specs(cfg, sharding_lib.Rules()))
            with mesh_lib.use_mesh(mesh):
                base_params = jax.jit(
                    lambda r: mod.init_params(r, cfg),
                    out_shardings=shardings)(jax.random.PRNGKey(0))
        resume = (tcfg.lora_dir and os.path.exists(
            os.path.join(os.path.expanduser(tcfg.lora_dir),
                         'adapters.npz')))
        if jax.process_count() > 1:
            # All hosts must take the SAME branch: save_adapters writes
            # on process 0 only, so without a shared filesystem the
            # exists() answers diverge and the gang deadlocks at the
            # first collective. Allgather lets EVERY host detect the
            # divergence and raise cleanly (no one-sided hang).
            import numpy as _np
            from jax.experimental import multihost_utils
            flags = multihost_utils.process_allgather(
                _np.asarray(bool(resume)))
            if bool(flags.any()) != bool(flags.all()):
                raise FileNotFoundError(
                    f'--lora-dir {tcfg.lora_dir!r} holds adapters.npz on '
                    f'only {int(flags.sum())}/{flags.size} hosts — LoRA '
                    f'resume on multi-host slices needs --lora-dir on '
                    f'shared storage (mounted bucket).')
            resume = bool(flags.all())
        if resume:
            adapters, saved_lcfg, start_step, opt_leaves = (
                lora_lib.load_adapters(tcfg.lora_dir))
            if (saved_lcfg.rank, saved_lcfg.alpha,
                    saved_lcfg.targets) != (lcfg.rank, lcfg.alpha,
                                            lcfg.targets):
                raise ValueError(
                    f'--lora-dir holds rank={saved_lcfg.rank} '
                    f'alpha={saved_lcfg.alpha} '
                    f'targets={saved_lcfg.targets}; requested '
                    f'rank={lcfg.rank} alpha={lcfg.alpha} '
                    f'targets={lcfg.targets}.')
            import jax.numpy as jnp
            state = lora_lib.LoRAState(
                step=jnp.asarray(start_step, jnp.int32),
                adapters=adapters,
                opt_state=lora_lib.restore_opt_state(tx, adapters,
                                                     opt_leaves))
            logger.info(f'Resumed LoRA adapters at step {start_step} '
                        f'from {tcfg.lora_dir}.')
        else:
            state = lora_lib.init_lora_state(jax.random.PRNGKey(1),
                                             base_params, lcfg, tx)
        lora_step = lora_lib.make_lora_train_step(cfg, mesh, tx, lcfg)

        def step_fn(s, b):
            return lora_step(s, base_params, b)
    else:
        def _state_from_hf():
            # Full finetune from HF weights: build the TrainState around
            # the imported base directly (no throwaway random init).
            import jax.numpy as jnp
            from skypilot_tpu.parallel import mesh as mesh_lib
            shardings = train_lib.state_shardings(cfg, mesh, tx)
            params = jax.device_put(load_base(), shardings.params)
            with mesh_lib.use_mesh(mesh):
                opt_state = jax.jit(
                    tx.init, out_shardings=shardings.opt_state)(params)
            return train_lib.TrainState(step=jnp.zeros((), jnp.int32),
                                        params=params,
                                        opt_state=opt_state)

        if tcfg.ckpt_dir:
            from skypilot_tpu.train import checkpoints
            if load_base is not None:
                # Peek before restore_or_init would materialize a random
                # init we'd immediately discard for the HF weights.
                ckpt = checkpoints.Checkpointer(tcfg.ckpt_dir)
                if ckpt.latest_step() is None:
                    state, start_step = _state_from_hf(), 0
                else:
                    # Same corrupt-step fallback as restore_or_init: a
                    # truncated newest step must not crash-loop every
                    # recovery round while an older complete step sits
                    # in the same directory.
                    abstract = checkpoints.abstract_train_state(
                        cfg, mesh, tx)
                    state, start_step = ckpt.restore_newest(abstract)
                    logger.info(f'Resumed from checkpoint step '
                                f'{start_step} in {tcfg.ckpt_dir}.')
            else:
                state, start_step, ckpt = checkpoints.restore_or_init(
                    tcfg.ckpt_dir, cfg, mesh, tx)
        elif load_base is not None:
            state = _state_from_hf()
        else:
            state = train_lib.init_train_state(jax.random.PRNGKey(0), cfg,
                                               mesh, tx)
        if tcfg.batch_size % tcfg.grad_accum_steps != 0:
            raise ValueError(
                f'batch_size={tcfg.batch_size} must be divisible by '
                f'grad_accum_steps={tcfg.grad_accum_steps}')
        step_fn = train_lib.make_train_step(
            cfg, mesh, tx, grad_accum_steps=tcfg.grad_accum_steps)
    batches = _batch_iter(tcfg, cfg.vocab_size, start_step, mesh)

    eval_fn = None
    if tcfg.eval_data_path:
        from skypilot_tpu.data import loader as loader_lib
        eval_tokens = loader_lib.load_tokens(tcfg.eval_data_path,
                                             tcfg.tokenizer)
        eval_step = train_lib.make_eval_step(cfg, mesh)
        if lora_mode:
            from skypilot_tpu.train import lora as lora_lib
            merged_of = jax.jit(
                lambda a: lora_lib.merge_into(base_params, a, lcfg))

        def _eval_params():
            return (merged_of(state.adapters) if lora_mode
                    else state.params)

        def eval_fn():
            # Fixed batches 0..K-1 of the eval corpus: the metric is
            # comparable across steps AND across resumed runs.
            eval_params = _eval_params()
            total = 0.0
            for i in range(tcfg.eval_batches):
                eb = loader_lib.batch_at_step(eval_tokens, i,
                                              tcfg.batch_size,
                                              tcfg.seq_len)
                eb = loader_lib.shard_batch({'tokens': eb}, mesh)
                total += float(eval_step(eval_params, eb))
            return total / tcfg.eval_batches

    history: List[Dict[str, float]] = []
    t_last = time.perf_counter()
    t_last_save = time.monotonic()
    steps_since_log = 0
    try:
        with _PreemptionWatch() as watch:
            for step in range(start_step, tcfg.total_steps):
                wait_wall = time.time()
                t_wait = time.perf_counter()
                batch = next(batches)
                waited = time.perf_counter() - t_wait
                _BATCH_WAIT.observe(waited)
                if waited >= _BATCH_WAIT_SPAN_MIN_S:
                    spans.record('train.batch_wait',
                                 start_wall=wait_wall,
                                 duration=waited,
                                 parent_id=spans.current(),
                                 attrs={'step': step})
                state, metrics = step_fn(state, batch)
                steps_since_log += 1
                # Eval cadence is INDEPENDENT of log cadence: an
                # eval-only step emits its own record.
                do_log = ((step + 1) % tcfg.log_every == 0 or
                          step + 1 == tcfg.total_steps)
                do_eval = (eval_fn is not None and
                           (step + 1) % tcfg.eval_every == 0)
                if do_log or do_eval:
                    rec = {'step': step + 1}
                    if do_log:
                        loss = float(metrics['loss'])  # device sync point
                        now = time.perf_counter()
                        rec.update(loss=round(loss, 4),
                                   sec_per_step=round(
                                       (now - t_last) / steps_since_log,
                                       4))
                    if do_eval:
                        rec['eval_loss'] = round(eval_fn(), 4)
                    t_last = time.perf_counter()   # exclude eval time
                    steps_since_log = 0
                    history.append(rec)
                    logger.info(json.dumps(rec))
                save_due = (step + 1) % tcfg.ckpt_every == 0
                if (not save_due and tcfg.ckpt_time_interval > 0 and
                        time.monotonic() - t_last_save >=
                        tcfg.ckpt_time_interval):
                    save_due = True
                if ckpt is not None and save_due:
                    ckpt.save(state, step + 1)
                    t_last_save = time.monotonic()
                if lora_mode and tcfg.lora_dir and save_due:
                    lora_lib.save_adapters(tcfg.lora_dir, state, lcfg)
                    t_last_save = time.monotonic()
                if watch.preempted:
                    # Preemption notice: one synchronous final save —
                    # the relaunch rebuilds its mesh from whatever
                    # topology recovery lands on and restores through
                    # the resharding path, so nothing after this point
                    # depends on the current slice shape surviving.
                    if ckpt is not None:
                        ckpt.save(state, step + 1, wait=True)
                    if lora_mode and tcfg.lora_dir:
                        lora_lib.save_adapters(tcfg.lora_dir, state, lcfg)
                    logger.info(json.dumps(
                        {'step': step + 1, 'preempted': True,
                         'final_checkpoint': ckpt is not None or
                         bool(lora_mode and tcfg.lora_dir)}))
                    return history
            if ckpt is not None:
                ckpt.save(state, tcfg.total_steps)
            if (lora_mode and tcfg.lora_dir and
                    tcfg.total_steps % tcfg.ckpt_every != 0):
                # The in-loop cadence already saved on aligned totals.
                lora_lib.save_adapters(tcfg.lora_dir, state, lcfg)
    finally:
        if ckpt is not None:
            # Exit flush barrier: async saves must be durable before the
            # job exits (the MOUNT_CACHED-flush analog).
            ckpt.close()
    return history


def main() -> None:
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    parser = argparse.ArgumentParser(prog='skytpu-trainer')
    parser.add_argument('--model', default='llama-debug')
    parser.add_argument('--model-override', action='append', default=[],
                        help='key=value on the model config (repeatable).')
    parser.add_argument('--mesh', default='',
                        help='axis=N comma list, e.g. data=2,fsdp=4')
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--seq-len', type=int, default=512)
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--log-every', type=int, default=10)
    parser.add_argument('--data', default=None)
    parser.add_argument('--tokenizer', default=None)
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--ckpt-every', type=int, default=50)
    parser.add_argument('--ckpt-time-interval', type=float, default=0.0,
                        help='>0: also checkpoint every N seconds (the '
                             'preemption-exposure bound on spot).')
    parser.add_argument('--grad-accum', type=int, default=1,
                        help='Accumulate grads over N microbatches per '
                             'optimizer step (lower peak memory).')
    parser.add_argument('--eval-data', default=None,
                        help='Held-out corpus; eval loss is logged every '
                             '--eval-every steps.')
    parser.add_argument('--eval-every', type=int, default=50)
    parser.add_argument('--eval-batches', type=int, default=8)
    parser.add_argument('--lora-rank', type=int, default=0,
                        help='>0 trains LoRA adapters instead of full '
                             'params (train/lora.py).')
    parser.add_argument('--lora-alpha', type=float, default=32.0)
    parser.add_argument('--lora-targets', default=None,
                        help='Comma list of leaf names to adapt '
                             '(default: wq,wk,wv,wo).')
    parser.add_argument('--hf-dir', default=None,
                        help='HF checkpoint to finetune from (config + '
                             'base weights; preset ignored).')
    parser.add_argument('--lora-dir', default=None,
                        help='Directory for adapters.npz (save/resume).')
    parser.add_argument('--sft-data', default=None,
                        help='JSONL of {"messages": [...]} conversations '
                             '(assistant-only loss, data/sft.py).')
    parser.add_argument('--chat-family', default=None,
                        choices=('llama3', 'chatml', 'plain'),
                        help='Chat template (default: from the '
                             "tokenizer's special tokens).")
    parser.add_argument('--data-service', default=None,
                        help='host:port of a data-service dispatcher '
                             '(docs/DATA_SERVICE.md): preprocess on '
                             'its CPU worker pool; the stream is '
                             'bit-identical to in-process input.')
    args = parser.parse_args()

    def _parse_kv(items):
        out = {}
        for item in items:
            k, v = item.split('=', 1)
            try:
                out[k] = int(v)
            except ValueError:
                try:
                    out[k] = float(v)
                except ValueError:
                    out[k] = v
        return out

    mesh = {}
    if args.mesh:
        for part in args.mesh.split(','):
            k, v = part.split('=')
            mesh[k] = int(v)
    tcfg = TrainerConfig(
        model=args.model, model_overrides=_parse_kv(args.model_override),
        mesh=mesh, batch_size=args.batch_size, seq_len=args.seq_len,
        total_steps=args.steps, learning_rate=args.lr,
        log_every=args.log_every, data_path=args.data,
        tokenizer=args.tokenizer, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every,
        ckpt_time_interval=args.ckpt_time_interval,
        grad_accum_steps=args.grad_accum,
        eval_data_path=args.eval_data, eval_every=args.eval_every,
        eval_batches=args.eval_batches,
        lora_rank=args.lora_rank, lora_alpha=args.lora_alpha,
        lora_targets=([t.strip() for t in args.lora_targets.split(',')
                       if t.strip()]
                      if args.lora_targets else None),
        hf_dir=args.hf_dir, lora_dir=args.lora_dir,
        sft_data_path=args.sft_data, chat_family=args.chat_family,
        data_service=args.data_service)
    train(tcfg)


if __name__ == '__main__':
    main()
