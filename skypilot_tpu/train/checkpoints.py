"""Topology-independent sharded checkpoints for TrainState pytrees.

The reference has no native checkpointing — its contract is "write to a
mounted bucket, flush before exit" (sky/backends/cloud_vm_ray_backend.py:
763-790 MOUNT_CACHED flush barrier; llm/llama-3_1-finetuning/lora.yaml:26-31
writes checkpoints to a MOUNTed /output). This framework owns the trainer,
so checkpointing is native, and built for the managed-jobs preemption
contract (jobs/controller.py + recovery_strategy.py): a preempted job's
recovery may land on a *different* slice topology, so the on-disk format
records the logical axis layout (named mesh axes per array dim), never the
physical device assignment. A checkpoint written on a 2×4 mesh restores
onto 1×8, 4×2, or a single host: every array is reassembled on host from
its chunk files and re-sliced per-device through
``jax.make_array_from_callback`` against the *current* mesh's shardings
(parallel/sharding.py host_to_sharded).

Durability contract (what a preemption mid-save can and cannot do):

  * every step writes into a hidden temp dir and is renamed into place
    only after its MANIFEST.json (per-array tree path, shape, dtype,
    logical spec, and per-chunk sha256 content digests) is durable —
    a killed save leaves a manifest-less temp dir that ``latest_step``
    can never see, never a half step;
  * restore verifies every chunk digest; a truncated or bit-flipped
    file raises :class:`CheckpointCorruptError` instead of silently
    restoring garbage;
  * :func:`restore_or_init` refuses corrupt steps LOUDLY and falls back
    to the newest older complete step; if steps exist but none restores
    it raises rather than silently reinitializing (that would be data
    loss dressed up as a fresh run).

Format (one directory per step)::

    <dir>/step_00000012/
        MANIFEST.json
        arrays/a0003.c00.npy     # one .npy per addressable chunk

On multi-host slices every process writes only its own replica-0 shards
plus a per-process chunk index; process 0 merges the indexes into the
manifest and performs the rename after a global barrier.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import queue
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import optax

from skypilot_tpu import sky_logging
from skypilot_tpu.parallel import sharding as sharding_lib
from skypilot_tpu.train import train_lib
from skypilot_tpu.utils import failpoints

logger = sky_logging.init_logger(__name__)

FORMAT_VERSION = 2
MANIFEST_NAME = 'MANIFEST.json'
_STEP_RE = re.compile(r'^step_(\d{8})$')
_TMP_PREFIX = '.tmp-step_'


class CheckpointCorruptError(RuntimeError):
    """A step directory exists but cannot be restored faithfully:
    malformed/missing manifest, missing chunk files, digest mismatch,
    or chunk coverage that does not tile the array."""


def abstract_train_state(cfg, mesh, tx: optax.GradientTransformation,
                         rules=None) -> train_lib.TrainState:
    """TrainState-shaped tree of ShapeDtypeStructs carrying NamedShardings —
    the restore target that tells the loader how to place every shard."""
    import functools
    from skypilot_tpu import models as models_lib
    shardings = train_lib.state_shardings(cfg, mesh, tx, rules)
    mod = models_lib.module_for(cfg)

    def _init(r):
        params = mod.init_params(r, cfg)
        return train_lib.TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32), params=params,
            opt_state=tx.init(params))

    shapes = jax.eval_shape(functools.partial(_init), jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


# ---------------------------------------------------------------- helpers

def _step_dirname(step: int) -> str:
    return f'step_{step:08d}'


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat]


def _npy_bytes(array: np.ndarray) -> bytes:
    buf = io.BytesIO()
    array = np.asarray(array)
    if array.ndim > 0:
        # NOT on 0-d: ascontiguousarray promotes scalars to shape (1,).
        array = np.ascontiguousarray(array)
    np.save(buf, array, allow_pickle=False)
    return buf.getvalue()


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _leaf_chunks(leaf) -> List[Dict[str, Any]]:
    """Snapshot this process's owned shards of one leaf to host memory.

    Each distinct array slice is written by exactly one process (the
    one holding its replica-0 shard), so the union over processes tiles
    the array with no duplicate writers. Plain numpy/python leaves are
    a single full chunk owned by process 0.
    """
    chunks: List[Dict[str, Any]] = []
    if isinstance(leaf, jax.Array) and hasattr(leaf, 'addressable_shards'):
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            start = [0 if sl.start is None else int(sl.start)
                     for sl in shard.index]
            data = np.asarray(jax.device_get(shard.data))
            chunks.append({'start': start, 'data': data})
    else:
        if jax.process_index() == 0:
            data = np.asarray(leaf)
            chunks.append({'start': [0] * data.ndim, 'data': data})
    return chunks


def _leaf_spec_json(leaf) -> Optional[List[Any]]:
    sharding = getattr(leaf, 'sharding', None)
    spec = getattr(sharding, 'spec', None)
    if spec is None:
        return None
    return sharding_lib.spec_to_json(spec)


# ---------------------------------------------------------------- writer

class _SaveJob:
    """A host-side snapshot of one step, ready for (async) file IO."""

    def __init__(self, step: int, arrays: List[Dict[str, Any]],
                 mesh_axes: Optional[Dict[str, int]]):
        self.step = step
        self.arrays = arrays          # [{path, shape, dtype, spec, chunks}]
        self.mesh_axes = mesh_axes


class Checkpointer:
    """Step-directory checkpoint manager with atomic completes.

    Single writer per directory (the trainer contract); saves are async
    by default — arrays are snapshotted to host synchronously (so the
    caller may donate/mutate state immediately) and file IO proceeds on
    a background thread. ``wait()`` is the exit flush barrier (the
    native analog of the reference's MOUNT_CACHED flush-before-exit).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True, keep_period: Optional[int] = None):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.keep_period = keep_period
        self._async = async_save
        self._queue: 'queue.Queue[Optional[_SaveJob]]' = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        # Stale-tmp sweeping happens on the WRITE path (first save), not
        # here: a restore-only Checkpointer opened on a live training
        # directory must never delete the trainer's in-progress save.
        self._swept_stale = False

    # ------------------------------------------------------------------
    def save(self, state, step: Optional[int] = None, *,
             wait: bool = False) -> int:
        """Snapshot `state` and persist it as `step`. Async by default:
        returns as soon as arrays are snapshotted to host; the write +
        atomic rename proceed while training continues."""
        self._raise_pending_error()
        if step is None:
            step = int(jax.device_get(state.step))
        # Drain the in-flight save FIRST, for both paths: it bounds the
        # backlog to one host-memory snapshot at a time (a slow disk
        # under a short time-cadence must not accumulate full TrainState
        # copies until OOM), and it serializes a synchronous save of a
        # step the worker is currently writing (same deterministic tmp
        # dir — concurrent writers would race on the rename).
        self._queue.join()
        self._raise_pending_error()
        job = self._snapshot(state, step)
        if self._async and not wait and jax.process_count() == 1:
            self._ensure_worker()
            self._queue.put(job)
        else:
            # Synchronous: multi-process saves barrier inside and must
            # not skew across hosts by queueing behind unrelated IO.
            self._write_step(job)
        if wait:
            self.wait()
        return step

    def _snapshot(self, state, step: int) -> _SaveJob:
        arrays = []
        mesh_axes: Optional[Dict[str, int]] = None
        for path, leaf in _flatten_with_paths(state):
            sharding = getattr(leaf, 'sharding', None)
            mesh = getattr(sharding, 'mesh', None)
            if mesh_axes is None and mesh is not None:
                try:
                    mesh_axes = {str(k): int(v)
                                 for k, v in dict(mesh.shape).items()}
                except (TypeError, AttributeError):
                    mesh_axes = None
            dtype = (leaf.dtype if isinstance(leaf, jax.Array)
                     else np.asarray(leaf).dtype)
            arrays.append({
                'path': path,
                'shape': [int(d) for d in np.shape(leaf)],
                'dtype': str(dtype),
                'spec': _leaf_spec_json(leaf),
                'chunks': _leaf_chunks(leaf),
            })
        return _SaveJob(step, arrays, mesh_axes)

    # ------------------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._worker_loop, name='ckpt-writer', daemon=True)
        self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                # Account for the sentinel too: a missed task_done here
                # leaves join() blocking forever on the next wait()/
                # close() after shutdown.
                self._queue.task_done()
                return
            try:
                self._write_step(job)
            except BaseException as e:  # pylint: disable=broad-except
                # Surfaces at the next save()/wait()/close(): a failed
                # async save must not be silently droppable.
                with self._error_lock:
                    if self._error is None:
                        self._error = e
                logger.error(f'async checkpoint save of step {job.step} '
                             f'failed: {e}')
            finally:
                self._queue.task_done()

    def _raise_pending_error(self) -> None:
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    # ------------------------------------------------------------------
    def _tmp_dir(self, step: int) -> str:
        # Deterministic (no pid): on multi-host shared storage every
        # process must write into the SAME in-progress dir.
        return os.path.join(self.directory, f'{_TMP_PREFIX}{step:08d}')

    def _clean_stale_tmp(self) -> None:
        for name in os.listdir(self.directory):
            if name.startswith(_TMP_PREFIX):
                logger.warning(
                    f'Removing stale in-progress checkpoint {name!r} '
                    f'(a previous save was killed mid-write; the step '
                    f'was never completed and cannot be restored).')
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def _write_step(self, job: _SaveJob) -> None:
        final_dir = os.path.join(self.directory, _step_dirname(job.step))
        if os.path.isdir(final_dir):
            logger.debug(f'checkpoint step {job.step} already complete; '
                         f'skipping re-save.')
            return
        tmp_dir = self._tmp_dir(job.step)
        if jax.process_count() > 1:
            # Shared storage: only process 0 clears debris, and every
            # process waits for it before writing into the shared dir.
            if jax.process_index() == 0 and os.path.isdir(tmp_dir):
                shutil.rmtree(tmp_dir, ignore_errors=True)
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(
                f'skytpu_ckpt_begin_{job.step}')
        else:
            if not self._swept_stale:
                self._swept_stale = True
                self._clean_stale_tmp()
            elif os.path.isdir(tmp_dir):
                # A previous crashed save of THIS step: its leftover
                # chunk files must not leak into the new manifest's dir.
                shutil.rmtree(tmp_dir, ignore_errors=True)
        # Chunk names carry the process index: on multi-host shared
        # storage every process writes its own shards into the SAME
        # temp dir, and per-process local chunk counters would collide.
        proc = jax.process_index()
        manifest_arrays = []
        write_error: Optional[BaseException] = None
        try:
            os.makedirs(os.path.join(tmp_dir, 'arrays'), exist_ok=True)
            for i, rec in enumerate(job.arrays):
                stem = f'a{i:04d}'
                chunk_records = []
                for j, chunk in enumerate(rec['chunks']):
                    fname = f'arrays/{stem}.p{proc:04d}.c{j:02d}.npy'
                    data = _npy_bytes(chunk['data'])
                    with open(os.path.join(tmp_dir, fname), 'wb') as f:
                        f.write(data)
                    chunk_records.append({
                        'file': fname,
                        'start': chunk['start'],
                        'shape': [int(d) for d in chunk['data'].shape],
                        'sha256': _sha256(data),
                    })
                manifest_arrays.append({
                    'path': rec['path'], 'shape': rec['shape'],
                    'dtype': rec['dtype'], 'spec': rec['spec'],
                    'chunks': chunk_records,
                })
        except OSError as e:
            if jax.process_count() == 1:
                raise
            # Multi-host: a one-sided raise here would leave every peer
            # blocked in the barrier below. Carry the error TO the
            # barrier instead; everyone aborts together.
            write_error = e

        if jax.process_count() > 1:
            # Every process contributes its chunk index; process 0
            # merges after the barrier so the manifest covers ALL
            # shards, with digests computed by whoever wrote each file.
            # The barrier doubles as failure propagation: a process
            # whose IO failed still REACHES it (we got here, so ours
            # succeeded — peers report theirs), because a one-sided
            # raise would leave the other hosts blocked forever.
            index_path = os.path.join(
                tmp_dir, f'chunks.p{jax.process_index():04d}.json')
            if write_error is None:
                try:
                    with open(index_path, 'w', encoding='utf-8') as f:
                        json.dump({'arrays': manifest_arrays}, f)
                except OSError as e:
                    write_error = e
            if not self._all_processes_ok(write_error is None):
                raise write_error if write_error is not None else IOError(
                    f'checkpoint step {job.step}: a peer process failed '
                    f'writing its shards; aborting the save on every '
                    f'host (the step stays invisible).')
            if jax.process_index() == 0:
                manifest_arrays = self._merge_chunk_indexes(tmp_dir)

        def _commit() -> None:
            # Deterministic mid-save fault site: fires with every chunk
            # on disk but no manifest/rename — exactly the window a
            # real preemption hits; the step must stay invisible.
            if failpoints.ACTIVE:
                failpoints.fire('ckpt.save')
            if jax.process_index() != 0:
                return
            manifest = {
                'format': FORMAT_VERSION,
                'step': job.step,
                'time': time.time(),
                'process_count': jax.process_count(),
                'mesh_axes': job.mesh_axes,
                'arrays': manifest_arrays,
            }
            mpath = os.path.join(tmp_dir, MANIFEST_NAME)
            with open(mpath + '.tmp', 'w', encoding='utf-8') as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mpath + '.tmp', mpath)
            # The commit point: a step exists iff this rename happened.
            os.replace(tmp_dir, final_dir)
            self._gc_steps()

        if jax.process_count() == 1:
            _commit()
        else:
            # Same carry-the-error-to-the-barrier protocol as above: a
            # failed manifest fsync/rename on process 0 (or a one-sided
            # failpoint firing) must surface on EVERY host, not wedge
            # the peers in a barrier.
            commit_error: Optional[BaseException] = None
            try:
                _commit()
            except BaseException as e:  # pylint: disable=broad-except
                commit_error = e
            if not self._all_processes_ok(commit_error is None):
                if commit_error is not None:
                    raise commit_error
                raise IOError(
                    f'checkpoint step {job.step}: commit failed on a '
                    f'peer process; the step was not published.')

    @staticmethod
    def _all_processes_ok(local_ok: bool) -> bool:
        """Collective status exchange doubling as a barrier: every
        process reports whether its local IO succeeded; all learn
        whether ALL succeeded. Used instead of a bare barrier so a
        one-sided failure aborts the save everywhere rather than
        leaving the healthy hosts blocked forever."""
        import numpy as _np
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(_np.asarray(local_ok))
        return bool(flags.all())

    @staticmethod
    def _merge_chunk_indexes(tmp_dir: str) -> List[Dict[str, Any]]:
        merged: Dict[str, Dict[str, Any]] = {}
        for name in sorted(os.listdir(tmp_dir)):
            if not (name.startswith('chunks.p') and name.endswith('.json')):
                continue
            with open(os.path.join(tmp_dir, name), encoding='utf-8') as f:
                index = json.load(f)
            for rec in index['arrays']:
                have = merged.setdefault(rec['path'], dict(rec, chunks=[]))
                have['chunks'].extend(rec['chunks'])
            os.unlink(os.path.join(tmp_dir, name))
        return list(merged.values())

    def _gc_steps(self) -> None:
        steps = self.all_steps()
        if self.max_to_keep is None or len(steps) <= self.max_to_keep:
            return
        victims = steps[:-self.max_to_keep]
        for step in victims:
            if self.keep_period and step % self.keep_period == 0:
                continue
            shutil.rmtree(
                os.path.join(self.directory, _step_dirname(step)),
                ignore_errors=True)

    # ------------------------------------------------------------------
    def restore(self, cfg, mesh, tx: optax.GradientTransformation,
                step: Optional[int] = None, rules=None
                ) -> Tuple[train_lib.TrainState, int]:
        """Restore (state, step) sharded onto `mesh`. step=None → latest.

        `mesh` is the CURRENT topology — the checkpoint's own mesh shape
        is advisory metadata only; arrays reshard through the logical
        layout regardless of what slice shape wrote them."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f'No checkpoint found under {self.directory}.')
        abstract = abstract_train_state(cfg, mesh, tx, rules)
        return self.restore_tree(abstract, step), step

    def restore_tree(self, abstract, step: int):
        """Generic restore: `abstract` is any pytree of ShapeDtypeStructs
        carrying NamedShardings (the target placement). Verifies the
        manifest + every chunk digest; raises CheckpointCorruptError on
        any integrity failure, ValueError on shape/dtype/tree mismatch
        (a config mismatch, not corruption)."""
        if failpoints.ACTIVE:
            failpoints.fire('ckpt.restore')
        step_dir = os.path.join(self.directory, _step_dirname(step))
        manifest = self._load_manifest(step_dir, step)
        by_path = {rec['path']: rec for rec in manifest['arrays']}
        saved_axes = manifest.get('mesh_axes')

        flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
        want_paths = [jax.tree_util.keystr(kp) for kp, _ in flat]
        missing = [p for p in want_paths if p not in by_path]
        extra = set(by_path) - set(want_paths)
        if missing or extra:
            raise ValueError(
                f'Checkpoint step {step} tree does not match the restore '
                f'target: missing={missing[:5]} extra={sorted(extra)[:5]} '
                f'(model/optimizer config mismatch).')

        cur_axes = None
        leaves = []
        for (kp, leaf), path in zip(flat, want_paths):
            rec = by_path[path]
            shape = tuple(rec['shape'])
            if shape != tuple(leaf.shape) or rec['dtype'] != str(leaf.dtype):
                raise ValueError(
                    f'Checkpoint array {path} is {rec["dtype"]}{shape}, '
                    f'restore target wants {leaf.dtype}'
                    f'{tuple(leaf.shape)} — config mismatch.')
            host = self._assemble_array(step_dir, step, rec)
            sharding = leaf.sharding
            if cur_axes is None and hasattr(sharding, 'mesh'):
                cur_axes = {str(k): int(v)
                            for k, v in dict(sharding.mesh.shape).items()}
            if sharding is None:
                leaves.append(host)
            else:
                leaves.append(sharding_lib.host_to_sharded(host, sharding))
        if saved_axes and cur_axes and saved_axes != cur_axes:
            logger.info(
                f'Resharded checkpoint step {step}: saved on mesh '
                f'{saved_axes}, restored onto {cur_axes} (logical layout '
                f'preserved, per-array re-slice).')
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _load_manifest(self, step_dir: str, step: int) -> Dict[str, Any]:
        mpath = os.path.join(step_dir, MANIFEST_NAME)
        if not os.path.isdir(step_dir):
            raise FileNotFoundError(
                f'No checkpoint step {step} under {self.directory}.')
        try:
            with open(mpath, encoding='utf-8') as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f'step {step}: no {MANIFEST_NAME} — save was interrupted '
                f'before commit; this step is partial.') from None
        except (ValueError, OSError) as e:
            raise CheckpointCorruptError(
                f'step {step}: unreadable manifest: {e}') from None
        if (not isinstance(manifest, dict) or
                manifest.get('format') != FORMAT_VERSION or
                not isinstance(manifest.get('arrays'), list)):
            raise CheckpointCorruptError(
                f'step {step}: manifest malformed or format '
                f'{manifest.get("format") if isinstance(manifest, dict) else "?"!r} '
                f'!= {FORMAT_VERSION}.')
        return manifest

    @staticmethod
    def _assemble_array(step_dir: str, step: int,
                        rec: Dict[str, Any]) -> np.ndarray:
        """Reassemble one array from its chunk files, verifying every
        content digest and that the chunks exactly tile the array."""
        shape = tuple(rec['shape'])
        dtype = np.dtype(rec['dtype'])
        # Geometry is manifest data, and the sha256s cover only the
        # chunk FILES — a corrupted manifest could carry out-of-range,
        # overlapping, or duplicated 'start's that a size-sum check
        # would pass (silently permuted values / uninitialized memory).
        # In-bounds + pairwise-disjoint + volume-sum == array volume
        # proves exact tiling in O(k²·ndim), no per-element bitmap (an
        # extra byte per element would be real money on the host-
        # memory-bound restore path). Validated BEFORE any file reads.
        boxes = []
        volume = 0
        for chunk in rec['chunks']:
            start = chunk.get('start')
            cshape = chunk.get('shape')
            if (not isinstance(start, list) or not isinstance(cshape, list)
                    or len(start) != len(shape) or len(cshape) != len(shape)
                    or any(s < 0 or d < 0 or s + d > dim for s, d, dim
                           in zip(start, cshape, shape))):
                raise CheckpointCorruptError(
                    f'step {step}: chunk {chunk.get("file")} geometry '
                    f'start={start} shape={cshape} does not fit array '
                    f'{rec["path"]} {shape}.')
            boxes.append((chunk.get('file'), start, cshape))
            volume += int(np.prod(cshape, dtype=np.int64))
        if volume != int(np.prod(shape, dtype=np.int64)):
            raise CheckpointCorruptError(
                f'step {step}: array {rec["path"]} chunks cover {volume} '
                f'of {int(np.prod(shape, dtype=np.int64))} elements — '
                f'partial shard set.')
        for a in range(len(boxes)):
            for b in range(a + 1, len(boxes)):
                _, sa, da = boxes[a]
                _, sb, db = boxes[b]
                disjoint = any(sa[k] + da[k] <= sb[k] or
                               sb[k] + db[k] <= sa[k]
                               for k in range(len(shape)))
                if not disjoint:
                    raise CheckpointCorruptError(
                        f'step {step}: chunks {boxes[a][0]} and '
                        f'{boxes[b][0]} of {rec["path"]} overlap — '
                        f'duplicated/shifted shard set.')
        out = np.empty(shape, dtype)
        for chunk in rec['chunks']:
            cpath = os.path.join(step_dir, chunk['file'])
            try:
                with open(cpath, 'rb') as f:
                    raw = f.read()
            except OSError as e:
                raise CheckpointCorruptError(
                    f'step {step}: chunk {chunk["file"]} unreadable: '
                    f'{e}') from None
            if _sha256(raw) != chunk['sha256']:
                raise CheckpointCorruptError(
                    f'step {step}: chunk {chunk["file"]} content digest '
                    f'mismatch (truncated or corrupted on disk).')
            try:
                data = np.load(io.BytesIO(raw), allow_pickle=False)
            except ValueError as e:
                raise CheckpointCorruptError(
                    f'step {step}: chunk {chunk["file"]} undecodable: '
                    f'{e}') from None
            if list(data.shape) != list(chunk['shape']):
                raise CheckpointCorruptError(
                    f'step {step}: chunk {chunk["file"]} shape '
                    f'{data.shape} != manifest {chunk["shape"]}.')
            index = tuple(slice(s, s + d)
                          for s, d in zip(chunk['start'], data.shape))
            out[index] = data
        return out

    def restore_newest(self, abstract) -> Tuple[Optional[Any],
                                                Optional[int]]:
        """Walk complete steps newest→oldest; refuse corrupt steps loudly
        and fall back. Returns (None, None) when the directory has no
        steps at all; raises CheckpointCorruptError when steps exist but
        none restores (silent reinit would be data loss)."""
        steps = self.all_steps()
        if not steps:
            return None, None
        for step in reversed(steps):
            try:
                return self.restore_tree(abstract, step), step
            except CheckpointCorruptError as e:
                logger.error(
                    f'REFUSING corrupt checkpoint step {step}: {e} — '
                    f'falling back to the next older complete step.')
        raise CheckpointCorruptError(
            f'All {len(steps)} checkpoint step(s) under {self.directory} '
            f'failed integrity verification; refusing to silently '
            f'reinitialize.')

    # ------------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> list:
        """Complete steps only (manifest present), ascending. An
        in-progress or interrupted save is invisible by construction."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isfile(os.path.join(self.directory, name,
                                                 MANIFEST_NAME)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def wait(self) -> None:
        """The exit flush barrier: block until in-flight async saves are
        durable (the native analog of the reference's MOUNT_CACHED
        flush-before-exit script)."""
        self._queue.join()
        self._raise_pending_error()

    def close(self) -> None:
        self.wait()
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(None)
            self._worker.join(timeout=60)

    def __enter__(self) -> 'Checkpointer':
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def restore_or_init(directory: str, cfg: Any, mesh, tx,
                    rng: Optional[jax.Array] = None, rules=None
                    ) -> Tuple[train_lib.TrainState, int, Checkpointer]:
    """The resume entrypoint used by the trainer: newest restorable
    checkpoint if one exists (resharded onto the CURRENT mesh — the
    recovery may have landed on a different slice topology), else a
    fresh sharded init. Returns (state, start_step, ckpt)."""
    ckpt = Checkpointer(directory)
    if ckpt.latest_step() is not None:
        abstract = abstract_train_state(cfg, mesh, tx, rules)
        state, step = ckpt.restore_newest(abstract)
        logger.info(f'Resumed from checkpoint step {step} in {directory}.')
        return state, step, ckpt
    if rng is None:
        rng = jax.random.PRNGKey(0)
    state = train_lib.init_train_state(rng, cfg, mesh, tx, rules)
    return state, 0, ckpt
