"""Sharded checkpoint save/restore for TrainState (async, mesh-aware).

The reference has no native checkpointing — its contract is "write to a
mounted bucket, flush before exit" (sky/backends/cloud_vm_ray_backend.py:
763-790 MOUNT_CACHED flush barrier; llm/llama-3_1-finetuning/lora.yaml:26-31
writes checkpoints to a MOUNTed /output). This framework owns the trainer,
so checkpointing is native: orbax per-shard save where every host writes
exactly its addressable shards (no gather — HBM and DCN stay quiet), async
so the save overlaps the next train steps, and restore materialises arrays
directly with the target mesh's NamedShardings.

The managed-jobs recovery contract (jobs/controller.py) composes with this:
point `--ckpt-dir` at the job's storage mount, and a recovered job resumes
from `latest_step()` instead of step 0.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import optax
import orbax.checkpoint as ocp

from skypilot_tpu import sky_logging
from skypilot_tpu.train import train_lib

logger = sky_logging.init_logger(__name__)


def abstract_train_state(cfg, mesh, tx: optax.GradientTransformation,
                         rules=None) -> train_lib.TrainState:
    """TrainState-shaped tree of ShapeDtypeStructs carrying NamedShardings —
    the restore target that tells orbax how to place every shard."""
    import functools
    from skypilot_tpu import models as models_lib
    shardings = train_lib.state_shardings(cfg, mesh, tx, rules)
    mod = models_lib.module_for(cfg)

    def _init(r):
        params = mod.init_params(r, cfg)
        return train_lib.TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32), params=params,
            opt_state=tx.init(params))

    shapes = jax.eval_shape(functools.partial(_init), jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


class Checkpointer:
    """Thin, opinionated wrapper over an orbax CheckpointManager."""

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True, keep_period: Optional[int] = None):
        self.directory = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                keep_period=keep_period,
                enable_async_checkpointing=async_save,
            ))

    # ------------------------------------------------------------------
    def save(self, state: train_lib.TrainState,
             step: Optional[int] = None, *, wait: bool = False) -> int:
        """Async by default: returns as soon as arrays are snapshotted;
        the write proceeds while training continues."""
        if step is None:
            step = int(jax.device_get(state.step))
        self._mngr.save(step, args=ocp.args.PyTreeSave(state))
        if wait:
            self._mngr.wait_until_finished()
        return step

    def restore(self, cfg, mesh, tx: optax.GradientTransformation,
                step: Optional[int] = None, rules=None
                ) -> Tuple[train_lib.TrainState, int]:
        """Restore (state, step) sharded onto `mesh`. step=None → latest."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f'No checkpoint found under {self.directory}.')
        abstract = abstract_train_state(cfg, mesh, tx, rules)
        # Explicit per-leaf shardings: without restore_args orbax falls back
        # to the shardings recorded in the checkpoint, which is wrong when
        # recovery lands on a different slice topology than the save.
        restore_args = ocp.checkpoint_utils.construct_restore_args(abstract)
        state = self._mngr.restore(
            step, args=ocp.args.PyTreeRestore(abstract,
                                              restore_args=restore_args))
        return state, step

    # ------------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> list:
        return list(self._mngr.all_steps())

    def wait(self) -> None:
        """The exit flush barrier: block until in-flight async saves are
        durable (the native analog of the reference's MOUNT_CACHED
        flush-before-exit script)."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mngr.close()

    def __enter__(self) -> 'Checkpointer':
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def restore_or_init(directory: str, cfg: Any, mesh, tx,
                    rng: Optional[jax.Array] = None, rules=None
                    ) -> Tuple[train_lib.TrainState, int, Checkpointer]:
    """The resume entrypoint used by the trainer: latest checkpoint if one
    exists, else a fresh sharded init. Returns (state, start_step, ckpt)."""
    ckpt = Checkpointer(directory)
    if ckpt.latest_step() is not None:
        state, step = ckpt.restore(cfg, mesh, tx, rules=rules)
        logger.info(f'Resumed from checkpoint step {step} in {directory}.')
        return state, step, ckpt
    if rng is None:
        rng = jax.random.PRNGKey(0)
    state = train_lib.init_train_state(rng, cfg, mesh, tx, rules)
    return state, 0, ckpt
