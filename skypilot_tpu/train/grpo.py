"""GRPO reinforcement-learning finetuning, native and TPU-first.

Reference analog: the RL recipes SkyPilot launches as external
frameworks — llm/verl/multinode.yaml (Ray + vLLM rollouts + FSDP
updates), llm/skyrl/, llm/nemorl/ (SURVEY §2.11). There the RL loop
lives outside the launcher; here it is native: rollouts ride the same
jitted `decode.generate` the serve engine uses (static shapes, KV
cache, temperature sampling on-device) and the update is one jitted
SPMD step over the same mesh/sharding rules as supervised training.

GRPO (group-relative policy optimization, the DeepSeek-R1 recipe):
  - G rollouts per prompt; advantage = (r - mean_group)/(std_group+ε)
    — no value network, the group IS the baseline.
  - Clipped importance-ratio surrogate (PPO-style) over completion
    tokens only.
  - Optional KL penalty vs the frozen initial policy (k3 estimator:
    exp(Δ) − Δ − 1, where Δ = logp_ref − logp), added per token.

TPU shape discipline: prompts pad to one bucket, completions are a
fixed `max_new_tokens`, groups fold into the batch dim ([B·G, S+T]) —
every iteration reuses two compiled programs (generate + update).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from skypilot_tpu import sky_logging
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import train_lib

# Fixed name, not __name__: under `python -m` this module is '__main__',
# which would fall outside the 'skypilot_tpu' logging root (no handler).
logger = sky_logging.init_logger('skypilot_tpu.train.grpo')


@dataclasses.dataclass(frozen=True)
class GRPOConfig:
    group_size: int = 8           # rollouts per prompt (G)
    max_new_tokens: int = 32      # completion length (T, static)
    temperature: float = 1.0      # rollout sampling temperature
    clip_eps: float = 0.2         # PPO ratio clip
    kl_coef: float = 0.0          # β for the k3 KL penalty (0 = off)
    inner_steps: int = 1          # optimizer updates per rollout batch
    adv_eps: float = 1e-4         # group-std floor


# A reward maps (prompt_tokens [S], completion_tokens [T], eos_id) →
# float. Completion tokens after the first EOS are already masked out
# by the caller (they arrive as eos-fill from decode.generate).
RewardFn = Callable[[Any, Any], float]


def token_logprobs(params, seq: jnp.ndarray, cfg, mod,
                   temperature: float = 1.0
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(log-prob of each NEXT token, router aux loss) under the policy:
    [B, L-1] fp32 (entry t scores seq[:, t+1] given seq[:, :t+1]).

    `temperature`: the ROLLOUT sampling temperature — the behavior
    policy is softmax(logits/τ), so the importance ratio must score
    tokens under the same τ-scaled distribution (τ≠1 without this
    correction is a systematically biased gradient). aux is the MoE
    load-balance loss (0.0 for dense families) — the update keeps the
    same routing pressure as supervised training."""
    if getattr(mod, 'HAS_AUX', False):
        logits, aux = mod.forward(params, seq[:, :-1], cfg,
                                  return_aux=True)
    else:
        logits, aux = mod.forward(params, seq[:, :-1], cfg), 0.0
    logits = logits.astype(jnp.float32) / temperature
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, seq[:, 1:, None],
                               axis=-1)[..., 0]
    return gold - logz, jnp.asarray(aux, jnp.float32)


def completion_mask(completions: jnp.ndarray,
                    eos_id: Optional[int]) -> jnp.ndarray:
    """[B, T] float mask: tokens up to AND INCLUDING the first EOS
    (decode.generate fills post-eos slots with eos)."""
    if eos_id is None:
        return jnp.ones(completions.shape, jnp.float32)
    is_eos = (completions == eos_id)
    after_eos = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
        - is_eos.astype(jnp.int32)
    return (after_eos == 0).astype(jnp.float32)


def group_advantages(rewards: jnp.ndarray, group_size: int,
                     eps: float = 1e-4) -> jnp.ndarray:
    """[B·G] rewards (group-major: prompt i owns rows i·G..(i+1)·G−1) →
    group-normalized advantages (the GRPO baseline)."""
    g = rewards.reshape(-1, group_size)
    mean = g.mean(axis=1, keepdims=True)
    std = g.std(axis=1, keepdims=True)
    return ((g - mean) / (std + eps)).reshape(-1)


def make_grpo_update(cfg, mesh, tx: optax.GradientTransformation,
                     gcfg: GRPOConfig, mod,
                     use_ref: bool = False):
    """Jitted (state, seq, comp_idx, behavior_lp, advantages, mask,
    ref_lp) → (state, metrics). Donates state. `comp_idx` [B, T] holds
    each row's completion positions in the [L-1] log-prob grid (rows are
    PACKED — prompt then completion at the row's true length — so
    ragged prompt batches score completions at the positions they were
    actually sampled at).

    ``mesh=None`` runs the update WITHOUT an ambient mesh — the
    single-device path the harvested-RL learner (train/rollout) uses:
    no sharding APIs touched, so it runs on every jax version the repo
    supports (the churn-trainer idiom)."""

    def update(state: train_lib.TrainState, seq, comp_idx, behavior_lp,
               adv, mask, ref_lp):

        def loss_fn(params):
            lp_full, aux = token_logprobs(params, seq, cfg, mod,
                                          gcfg.temperature)
            lp = jnp.take_along_axis(lp_full, comp_idx, axis=1)
            ratio = jnp.exp(lp - behavior_lp)
            clipped = jnp.clip(ratio, 1.0 - gcfg.clip_eps,
                               1.0 + gcfg.clip_eps)
            surr = jnp.minimum(ratio * adv[:, None],
                               clipped * adv[:, None])
            loss_tok = -surr
            if use_ref:
                # k3 estimator of KL(policy ‖ ref): unbiased, positive.
                delta = ref_lp - lp
                loss_tok = loss_tok + gcfg.kl_coef * (
                    jnp.exp(delta) - delta - 1.0)
            denom = jnp.maximum(mask.sum(), 1.0)
            # aux keeps MoE router load-balancing pressure in RL, same
            # as the supervised step (train_lib loss_fn adds it too).
            loss = (loss_tok * mask).sum() / denom + aux
            frac_clipped = ((jnp.abs(ratio - clipped) > 1e-9)
                            .astype(jnp.float32) * mask).sum() / denom
            return loss, (ratio, frac_clipped)

        (loss, (ratio, frac_clipped)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {'loss': loss,
                   'grad_norm': optax.global_norm(grads),
                   'mean_ratio': (ratio * mask).sum()
                   / jnp.maximum(mask.sum(), 1.0),
                   'frac_clipped': frac_clipped}
        return train_lib.TrainState(step=state.step + 1,
                                    params=new_params,
                                    opt_state=new_opt), metrics

    jitted = jax.jit(update, donate_argnums=(0,))

    def wrapped(state, seq, comp_idx, behavior_lp, adv, mask,
                ref_lp=None):
        if ref_lp is None:
            ref_lp = jnp.zeros_like(behavior_lp)
        if mesh is None:
            return jitted(state, seq, comp_idx, behavior_lp, adv, mask,
                          ref_lp)
        with mesh_lib.use_mesh(mesh):
            return jitted(state, seq, comp_idx, behavior_lp, adv, mask,
                          ref_lp)

    return wrapped


class GRPOTrainer:
    """Rollout → reward → group advantage → clipped update, iterated."""

    def __init__(self, cfg, gcfg: GRPOConfig, reward_fn: RewardFn,
                 mesh=None, tx: Optional[optax.GradientTransformation]
                 = None, eos_id: Optional[int] = None,
                 init_params=None, seed: int = 0):
        from skypilot_tpu import models as models_lib
        from skypilot_tpu.parallel import MeshSpec, build_mesh
        self.cfg, self.gcfg = cfg, gcfg
        self.mod = models_lib.module_for(cfg)
        self.reward_fn = reward_fn
        self.eos_id = eos_id
        self.mesh = mesh if mesh is not None else build_mesh(MeshSpec())
        self.tx = tx or train_lib.default_optimizer(
            learning_rate=1e-5, warmup_steps=1, total_steps=10_000,
            max_grad_norm=1.0)
        self.rng = jax.random.PRNGKey(seed)
        if init_params is None:
            self.state = train_lib.init_train_state(
                jax.random.PRNGKey(seed), cfg, self.mesh, self.tx)
        else:
            shardings = train_lib.state_shardings(cfg, self.mesh, self.tx)
            params = jax.device_put(init_params, shardings.params)
            with mesh_lib.use_mesh(self.mesh):
                opt_state = jax.jit(
                    self.tx.init,
                    out_shardings=shardings.opt_state)(params)
            self.state = train_lib.TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=opt_state)
        use_ref = gcfg.kl_coef > 0.0
        # A REAL copy: the jitted update donates the policy buffers, so
        # aliased leaves would be invalidated after the first step on
        # TPU/GPU (and would silently track the policy anywhere).
        self._ref_params = (jax.tree.map(jnp.copy, self.state.params)
                            if use_ref else None)
        self._update = make_grpo_update(cfg, self.mesh, self.tx, gcfg,
                                        self.mod, use_ref=use_ref)
        self._lp_fn = jax.jit(functools.partial(
            token_logprobs, cfg=cfg, mod=self.mod,
            temperature=gcfg.temperature))

    def _next_rng(self):
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def iteration(self, prompts: jnp.ndarray,
                  prompt_lengths: Optional[jnp.ndarray] = None
                  ) -> Dict[str, float]:
        """One GRPO iteration on a [B, S] prompt batch. Returns metrics
        (mean_reward, loss, mean_ratio, frac_clipped)."""
        from skypilot_tpu.models import decode as decode_lib
        cfg, gcfg = self.cfg, self.gcfg
        b, s = prompts.shape
        g = gcfg.group_size
        rep = jnp.repeat(prompts, g, axis=0)            # group-major
        rep_lens = (jnp.repeat(prompt_lengths, g)
                    if prompt_lengths is not None else None)
        from skypilot_tpu.models import mla as mla_lib
        dec = (self.mod if isinstance(cfg, mla_lib.MLAConfig)
               else decode_lib)
        with mesh_lib.use_mesh(self.mesh):
            gen = dec.generate(
                self.state.params, rep, cfg, gcfg.max_new_tokens,
                max_len=s + gcfg.max_new_tokens,
                temperature=gcfg.temperature, eos_id=self.eos_id,
                prompt_lengths=rep_lens, rng=self._next_rng())
        # One bulk device→host transfer; rewards and sequence packing
        # are host-side per-row work.
        import numpy as np
        rep_np = np.asarray(jax.device_get(rep))
        gen_np = np.asarray(jax.device_get(gen))
        t = gcfg.max_new_tokens
        if rep_lens is None:
            seq_np = np.concatenate([rep_np, gen_np], axis=1)
            comp_idx = np.broadcast_to(np.arange(t) + s - 1,
                                       (b * g, t)).copy()
        else:
            # PACK ragged rows: prompt[:len] + completion, right-padded
            # — completions stay at the positions generate() sampled
            # them at (a pad gap would shift RoPE and poison the
            # conditioning, making behavior_lp wrong).
            lens_np = np.asarray(rep_lens)
            seq_np = np.zeros((b * g, s + t), rep_np.dtype)
            comp_idx = np.zeros((b * g, t), np.int32)
            for i in range(b * g):
                li = int(lens_np[i])
                seq_np[i, :li] = rep_np[i, :li]
                seq_np[i, li:li + t] = gen_np[i]
                comp_idx[i] = np.arange(t) + li - 1
        seq = jnp.asarray(seq_np)
        comp_idx = jnp.asarray(comp_idx, jnp.int32)
        mask = completion_mask(gen, self.eos_id)

        rewards = jnp.asarray(
            [self.reward_fn(rep_np[i], gen_np[i]) for i in range(b * g)],
            jnp.float32)
        adv = group_advantages(rewards, g, gcfg.adv_eps)

        with mesh_lib.use_mesh(self.mesh):
            lp_full, _ = self._lp_fn(self.state.params, seq)
            behavior_lp = jax.lax.stop_gradient(
                jnp.take_along_axis(lp_full, comp_idx, axis=1))
            ref_lp = None
            if self._ref_params is not None:
                ref_full, _ = self._lp_fn(self._ref_params, seq)
                ref_lp = jax.lax.stop_gradient(
                    jnp.take_along_axis(ref_full, comp_idx, axis=1))

        metrics: Dict[str, float] = {}
        for _ in range(gcfg.inner_steps):
            self.state, m = self._update(self.state, seq, comp_idx,
                                         behavior_lp, adv, mask, ref_lp)
            metrics = {k: float(v) for k, v in m.items()}
        metrics['mean_reward'] = float(rewards.mean())
        metrics['mean_completion_len'] = float(mask.sum(1).mean())
        return metrics


# --- Built-in rewards (demo/test; real use passes a callable) ----------

def count_token_reward(target_id: int) -> RewardFn:
    """Fraction of completion tokens equal to `target_id` — a toy
    objective whose optimum is unambiguous (hermetic learning tests)."""
    def fn(prompt, completion) -> float:
        import numpy as np
        c = np.asarray(completion)
        return float((c == target_id).mean())
    return fn


def length_reward(eos_id: int) -> RewardFn:
    """Fraction of the budget used before the first EOS — rewards
    longer completions (normalized to [0, 1])."""
    def fn(prompt, completion) -> float:
        import numpy as np
        c = np.asarray(completion)
        hits = np.flatnonzero(c == eos_id)
        used = hits[0] if hits.size else c.shape[0]
        return float(used) / float(c.shape[0])
    return fn


def resolve_reward(spec: str, eos_id: Optional[int]) -> RewardFn:
    """CLI reward resolution: 'count_token:ID', 'length', or
    'module.path:function' (a callable taking (prompt, completion))."""
    if spec.startswith('count_token:'):
        return count_token_reward(int(spec.split(':', 1)[1]))
    if spec == 'length':
        if eos_id is None:
            raise ValueError("reward 'length' needs --eos-id")
        return length_reward(eos_id)
    if ':' in spec:
        import importlib
        mod_name, fn_name = spec.rsplit(':', 1)
        return getattr(importlib.import_module(mod_name), fn_name)
    raise ValueError(
        f'Unknown reward {spec!r}: use count_token:ID, length, or '
        f'module.path:function')


def main() -> None:
    """CLI: native GRPO finetuning (the reference's verl/skyrl recipes'
    role, minus the external framework).

        python -m skypilot_tpu.train.grpo --model llama-debug \
            --reward count_token:42 --iterations 50
    """
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    import argparse
    import json

    from skypilot_tpu import models as models_lib
    from skypilot_tpu.train import trainer as trainer_mod
    parser = argparse.ArgumentParser(prog='skytpu-grpo')
    parser.add_argument('--model', default='llama-debug')
    parser.add_argument('--hf-dir', default=None,
                        help='HF checkpoint for the initial policy.')
    parser.add_argument('--reward', required=True,
                        help='count_token:ID | length | module:function')
    parser.add_argument('--iterations', type=int, default=100)
    parser.add_argument('--prompts', default=None,
                        help='JSONL of {"tokens": [...]} prompt batches '
                             '(default: random token prompts).')
    parser.add_argument('--batch-prompts', type=int, default=4)
    parser.add_argument('--prompt-len', type=int, default=16)
    parser.add_argument('--group-size', type=int, default=8)
    parser.add_argument('--max-new-tokens', type=int, default=32)
    parser.add_argument('--temperature', type=float, default=1.0)
    parser.add_argument('--kl-coef', type=float, default=0.0)
    parser.add_argument('--clip-eps', type=float, default=0.2)
    parser.add_argument('--inner-steps', type=int, default=1)
    parser.add_argument('--lr', type=float, default=1e-5)
    parser.add_argument('--eos-id', type=int, default=None)
    parser.add_argument('--mesh', default='')
    parser.add_argument('--ckpt-dir', default=None,
                        help='Checkpoint dir for the policy (native '
                             'chunked format; resume-from-newest).')
    parser.add_argument('--ckpt-every', type=int, default=50)
    args = parser.parse_args()

    trainer_mod.maybe_init_distributed()
    init_params = None
    if args.hf_dir:
        from skypilot_tpu.models import hf_import
        cfg, init_params = hf_import.load_hf_checkpoint(
            args.hf_dir, dtype=jnp.float32)
        eos = hf_import.hf_eos_ids(args.hf_dir)
        if args.eos_id is None and eos:
            args.eos_id = eos[0]
    else:
        cfg = models_lib.get_config(args.model)
    gcfg = GRPOConfig(group_size=args.group_size,
                      max_new_tokens=args.max_new_tokens,
                      temperature=args.temperature,
                      clip_eps=args.clip_eps, kl_coef=args.kl_coef,
                      inner_steps=args.inner_steps)
    from skypilot_tpu.parallel import MeshSpec, build_mesh
    mesh_kv = {}
    for part in args.mesh.split(','):
        if part:
            k, v = part.split('=')
            mesh_kv[k.strip()] = int(v)
    mesh = build_mesh(MeshSpec(**mesh_kv))
    tx = train_lib.default_optimizer(learning_rate=args.lr,
                                     warmup_steps=1,
                                     total_steps=args.iterations + 1)
    trainer = GRPOTrainer(cfg, gcfg,
                          resolve_reward(args.reward, args.eos_id),
                          mesh=mesh, tx=tx, eos_id=args.eos_id,
                          init_params=init_params)

    def prompt_batches():
        if args.prompts:
            import json as json_lib
            rows: List[List[int]] = []
            with open(args.prompts, 'r', encoding='utf-8') as f:
                rows = [json_lib.loads(line)['tokens'] for line in f
                        if line.strip()]
            if len(rows) < args.batch_prompts:
                raise ValueError(
                    f'--prompts has {len(rows)} rows but '
                    f'--batch-prompts is {args.batch_prompts}; add '
                    f'prompts or lower the batch.')
            if len(rows) % args.batch_prompts:
                logger.warning(
                    f'{len(rows) % args.batch_prompts} trailing '
                    f'prompt(s) are skipped each epoch (static batch '
                    f'of {args.batch_prompts}).')
            while True:
                for lo in range(0, len(rows) - args.batch_prompts + 1,
                                args.batch_prompts):
                    chunk = rows[lo:lo + args.batch_prompts]
                    width = max(len(r) for r in chunk)
                    arr = jnp.zeros((len(chunk), width), jnp.int32)
                    lens = []
                    for i, r in enumerate(chunk):
                        arr = arr.at[i, :len(r)].set(
                            jnp.asarray(r, jnp.int32))
                        lens.append(len(r))
                    yield arr, jnp.asarray(lens, jnp.int32)
        else:
            i = 0
            while True:
                rng = jax.random.PRNGKey(1000 + i)
                yield (jax.random.randint(
                    rng, (args.batch_prompts, args.prompt_len), 0,
                    cfg.vocab_size, dtype=jnp.int32), None)
                i += 1

    ckpt = None
    start_it = 0
    if args.ckpt_dir:
        from skypilot_tpu.train import checkpoints
        ckpt = checkpoints.Checkpointer(args.ckpt_dir)
        if ckpt.latest_step() is not None:
            # Elastic resume (the trainer-CLI contract from the jobs
            # plane): restore the newest COMPLETE step through the
            # resharding path — a preempted GRPO job relaunched on a
            # different mesh picks up where it left off instead of
            # losing the run. Corrupt-newest falls back to an older
            # complete step inside restore_newest.
            abstract = checkpoints.abstract_train_state(
                trainer.cfg, trainer.mesh, trainer.tx)
            state, start_it = ckpt.restore_newest(abstract)
            trainer.state = state
            logger.info(f'Resumed GRPO policy at iteration {start_it} '
                        f'from {args.ckpt_dir}.')
    try:
        batches = prompt_batches()
        for _ in range(start_it):
            # Fast-forward: iteration i's prompts must be the same
            # whether or not the run was preempted before it (prompt
            # construction is cheap; the stream is a pure function of
            # the iteration index).
            next(batches)
        with trainer_mod._PreemptionWatch() as watch:
            for it in range(start_it, args.iterations):
                prompts, lens = next(batches)
                metrics = trainer.iteration(prompts, prompt_lengths=lens)
                logger.info(json.dumps(
                    {'iter': it + 1,
                     **{k: round(v, 4) for k, v in metrics.items()}}))
                if ckpt is not None and (it + 1) % args.ckpt_every == 0:
                    ckpt.save(trainer.state, it + 1)
                if watch.preempted:
                    # Preemption notice (SIGTERM / trainer.preempt
                    # failpoint): one synchronous final save, clean
                    # exit — the relaunch resumes via restore_newest
                    # on whatever mesh recovery lands on.
                    if ckpt is not None:
                        ckpt.save(trainer.state, it + 1, wait=True)
                    logger.info(json.dumps(
                        {'iter': it + 1, 'preempted': True,
                         'final_checkpoint': ckpt is not None}))
                    return
            if ckpt is not None and args.iterations % args.ckpt_every != 0:
                # Aligned totals were already saved by the in-loop
                # cadence (a complete step is durable; re-saving it is
                # a no-op).
                ckpt.save(trainer.state, args.iterations)
    finally:
        if ckpt is not None:
            ckpt.close()


if __name__ == '__main__':
    main()
